"""Multi-tenant ingest service: one worker fleet, many consumer jobs.

PR 9's `IngestCoordinator` bound the lease/replay machinery to exactly one
consumer and one epoch — the coordinator died with the run. This module
promotes it to a shared SERVICE in the tf.data-service shape (arXiv
2210.14826): one long-lived lease table and worker fleet serving MANY
concurrent consumer jobs (grid-search folds, simultaneous `op run`s, the
serving daemon's monitor), each job with its own frozen file listing,
reorder/dedupe frontier, and delivery connection. The checkpointed service
state — not connection liveness — is the source of truth (the TensorFlow
fault-model position, arXiv 1605.08695 §4.2).

Robustness contract, in order of importance:

* **Coordinator checkpoint/restart.** The lease table and every job's
  committed frontier checkpoint atomically (temp + `os.replace`, the model-
  save discipline) on a short cadence. A SIGKILL'd service restarts from the
  checkpoint, re-adopts reconnecting workers (they retry HELLO under seeded
  backoff) and consumers (idempotent JOB_OPEN attaches to the restored job),
  and resumes every job from its acked frontier. The consumer client dedupes
  by `(file, chunk)` ordinal, so a stale checkpoint only costs re-delivery,
  never correctness: output stays byte-identical with zero consumer-visible
  errors. `ingest_coordinator_restarts_total` counts non-clean restores.
* **Consumer isolation.** Each job has a bounded delivery buffer. LOCAL
  (in-process) jobs keep the blocking backpressure of the single-job
  coordinator — a slow consumer slows its own workers. REMOTE jobs must
  never block a SHARED worker thread, so a full buffer SHEDS far-ahead
  batches (`ingest_backpressure_shed_total`) instead; the gap is repaired by
  the SHARD_DONE completeness check, which requeues the shard until every
  chunk is really committed. A crashed consumer's job is parked (its shards
  stop granting) and touches nothing belonging to other jobs.
* **Autoscaling with graceful degradation.** The housekeeping loop watches
  queue-wait (how long the oldest grantable shard has sat pending) and
  spawns workers up to `AutoscaleConfig.max_workers`; a sustained-idle fleet
  retires workers down to `min_workers` (SHUTDOWN on their next poll). If
  the fleet is gone entirely, the per-job stalled-shard fallback extracts
  in-process — a job can always finish as a slow version of the in-process
  reader path.

Chaos: `coord:kill` (FaultInjector.coord_kills, keyed `(epoch, seq)` like
`worker:kill`) crashes the service at a deterministic batch ordinal —
`kill_mode="process"` is a real SIGKILL for `op ingest-serve`, the
in-process mode is an abrupt teardown that skips the clean checkpoint, so
tests drive the same restore path without a subprocess.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from .. import obs
from ..resilience import chaos
from ..resilience.lockcheck import make_condition, make_lock
from . import transport
from .frames import (
    compress_buffers,
    decompress_buffers,
    payload_nrows,
    payload_rows,
)
from .source import source_from_wire
from .worker import IngestWorker, extract_shard

#: shard-count auto rule: enough shards that one straggler does not halve
#: the fleet's utilization, never more than the file count
_MAX_AUTO_SHARDS = 8

_STATE_FILE = "ingest_state.json"


def _sever(sock: socket.socket) -> None:
    """Hard-sever a connection: shutdown(SHUT_RDWR) BEFORE close. A bare
    close() cannot interrupt another thread blocked in recv()/sendall() on
    the same socket — the in-flight syscall pins the open file description,
    so the fd leaks, no FIN is sent, and the PEER blocks forever too.
    shutdown() tears the TCP stream down immediately regardless of who is
    parked inside a syscall on it."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class IngestError(RuntimeError):
    """A shard failed extraction on two independent holders — the data (or
    the source spec) is bad, and the job fails the way the in-process
    reader would."""


@dataclass
class AutoscaleConfig:
    """Queue-wait-driven worker autoscaling knobs (housekeeping loop)."""

    min_workers: int = 0
    max_workers: int = 4
    #: oldest grantable pending-shard age that triggers one spawn
    scale_up_wait_s: float = 1.0
    #: fleet-wide idle duration (no grantable pending work, no leases)
    #: before one worker is retired
    scale_down_idle_s: float = 5.0
    #: minimum seconds between autoscale actions (spawn storms are worse
    #: than a briefly-underscaled fleet)
    cooldown_s: float = 2.0


@dataclass
class _Lease:
    job_id: str
    shard: int
    lease_id: int
    worker_id: str
    deadline: float
    #: the _Worker CONNECTION the lease was granted over — revocation on
    #: disconnect matches on this object, never on worker_id: a worker that
    #: reconnects (same id, new connection) and takes a fresh lease before
    #: its old handler finished cleaning up must not have the NEW lease
    #: revoked along with the old one
    owner: object = None


@dataclass
class _Worker:
    worker_id: str
    pid: int
    sock: socket.socket
    live: bool = True
    #: autoscale retire flag: answered with SHUTDOWN on the next poll
    retire: bool = False


@dataclass
class _ShardState:
    files: list = field(default_factory=list)   # [(file_index, name), ...]
    granted: int = 0                            # lease grants so far
    errors: int = 0                             # worker-reported failures
    pending_since: Optional[float] = None


class _Job:
    """One consumer job: frozen file listing, per-job reorder/dedupe
    frontier, bounded delivery buffer, and (for remote jobs) the consumer
    connection + acked frontier the checkpoint persists."""

    def __init__(self, job_id: str, source, *, plan_fp: str, n_shards: int,
                 files: list, local: bool, max_buffered: int,
                 epoch: int = 0):
        self.job_id = job_id
        self.epoch = int(epoch)
        self.source = source
        self.plan_fp = plan_fp
        self.files = list(files)
        self.n_shards = int(n_shards)
        self.shards: dict[int, _ShardState] = {
            s: _ShardState() for s in range(self.n_shards)}
        for i, name in enumerate(self.files):
            self.shards[i % self.n_shards].files.append((i, name))
        self.file_chunks: dict[int, int] = {}
        self.committed: set[tuple[int, int]] = set()
        #: (file, chunk) -> payload; payload is a rows list (legacy BATCH /
        #: self-extract) or a (meta, buffers) columnar pair (COLBATCH)
        self.buffer: dict[tuple[int, int], object] = {}
        self.shards_done: set[int] = set()
        #: emission cursor: next (file, chunk) to hand to the consumer —
        #: the local stream's read position, or the remote sender's cursor
        self.emit: list[int] = [0, 0]
        #: remote consumer's acked frontier: everything strictly below is
        #: durable WITH THE CONSUMER — this is what the checkpoint persists
        self.acked: list[int] = [0, 0]
        self.error: Optional[BaseException] = None
        self.error_sent = False
        self.stop = False
        self.local = bool(local)
        self.conn: Optional[socket.socket] = None
        #: bumped on every attach/detach so a superseded sender thread
        #: notices and exits even if it holds the same conn object
        self.conn_gen = 0
        #: negotiated JOB_BATCH buffer compression ("zlib" or None) — set
        #: from the consumer's JOB_OPEN options on every attach; stored
        #: payloads are re/de-flated at the delivery edge to match
        self.wire_compression: Optional[str] = None
        self.eof_sent = False
        self.self_extracting: set[int] = set()
        self.max_buffered = int(max_buffered)

    @property
    def paused(self) -> bool:
        """A remote job with no consumer attached: its shards stop granting
        (no point extracting into a shedding buffer for a dead consumer)."""
        return (not self.local) and self.conn is None

    def done(self) -> bool:
        """Every file's chunk count known and every chunk committed
        (delivery may still be draining the buffer)."""
        if len(self.file_chunks) < len(self.files):
            return False
        return all(
            (fi, c) in self.committed
            for fi, nc in self.file_chunks.items() for c in range(nc))

    def shard_complete(self, shard: int) -> bool:
        """Every chunk of every file in `shard` committed (chunk counts
        known) — the SHARD_DONE admission test that repairs shed gaps."""
        for fi, _name in self.shards[shard].files:
            nc = self.file_chunks.get(fi)
            if nc is None:
                return False
            for c in range(nc):
                if (fi, c) not in self.committed:
                    return False
        return True


class IngestService:
    """See the module docstring for the architecture. Sizing note:
    `lease_timeout_s` must exceed the worst single-file read OR parse time —
    workers heartbeat between files and between the read and parse phases,
    and every BATCH frame refreshes the lease, but one monolithic phase has
    no beat inside it. Too-small a timeout costs duplicate extraction churn
    (dedupe keeps the output correct), never correctness."""

    def __init__(self, *, host: str = "127.0.0.1", port: int = 0,
                 state_dir: Optional[str] = None,
                 cache_dir: Optional[str] = None,
                 lease_timeout_s: float = 10.0,
                 self_extract_after_s: float = 15.0,
                 poll_s: float = 0.25,
                 checkpoint_every_s: float = 0.25,
                 max_buffered_batches: int = 64,
                 inflight_window: int = 32,
                 autoscale: Optional[AutoscaleConfig] = None,
                 spawn_fn: Optional[Callable] = None,
                 single_epoch: bool = False,
                 kill_mode: str = "raise",
                 registry=None):
        self.cache_dir = cache_dir
        self.state_dir = state_dir
        self.lease_timeout_s = float(lease_timeout_s)
        self.self_extract_after_s = float(self_extract_after_s)
        self.poll_s = float(poll_s)
        self.checkpoint_every_s = float(checkpoint_every_s)
        self.max_buffered = int(max_buffered_batches)
        self.inflight_window = int(inflight_window)
        self.autoscale = autoscale
        #: injectable for tests; default spawns `op ingest-worker` subprocesses
        self._spawn_fn = spawn_fn or (lambda svc, n: svc.spawn_workers(n))
        #: single_epoch: the IngestCoordinator facade — workers are told
        #: SHUTDOWN once every registered job is done (the `op run
        #: --ingest-workers` worker-exit contract). A standalone service
        #: keeps its fleet alive for future jobs instead.
        self.single_epoch = bool(single_epoch)
        #: "process" = real SIGKILL of this pid on coord:kill (ingest-serve);
        #: anything else = abrupt in-process teardown (tests)
        self.kill_mode = kill_mode
        self._host, self._port = host, int(port)
        self._reg = registry if registry is not None else obs.default_registry()
        #: fleet metrics federation (obs/fleet.py): workers push METRICS
        #: frames, the coordinator's own registry attaches as a pull source,
        #: and FLEET_METRICS requests read the raw per-process snapshots back
        self.fleet = obs.FleetAggregator()
        self.fleet.attach_local("coordinator", os.getpid(),
                                lambda: self._reg.snapshot(samples=True))

        # --- shared state (everything below under _cond) ---
        self._cond = make_condition("IngestService._cond")
        self._jobs: dict[str, _Job] = {}
        self._pending: list[tuple[str, int]] = []   # (job_id, shard)
        self._leases: dict[tuple[str, int], _Lease] = {}
        self._next_lease_id = 0
        self._workers: dict[str, _Worker] = {}
        self._closed = False
        self._crashed = False
        self._stop_requested = False

        self._server: Optional[socket.socket] = None
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._send_locks: dict[socket.socket, threading.Lock] = {}
        self._procs: list[subprocess.Popen] = []
        self._local_workers: list[IngestWorker] = []

        self._restarts = 0
        self._last_ckpt: Optional[float] = None
        self._ckpt_lock = make_lock("IngestService._ckpt_lock")
        self._as_last = 0.0            # last autoscale action (monotonic)
        self._as_idle_since: Optional[float] = None

    # --- metrics ----------------------------------------------------------------------
    def _counter(self, name: str, help: str, **labels):
        return self._reg.counter(name, help=help, labels=labels or None)

    def _worker_gauges(self, n_live: int) -> None:
        for name in ("ingest_workers", "ingest_active_workers"):
            self._reg.gauge(name, help="extraction workers currently "
                                       "connected").set(n_live)

    def _jobs_gauge(self) -> None:
        self._reg.gauge("ingest_jobs_active",
                        help="consumer jobs registered with the ingest "
                             "service").set(len(self._jobs))

    # --- lifecycle --------------------------------------------------------------------
    def start(self) -> "IngestService":
        if self._server is not None:
            return self
        self._restore()
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self._host, self._port))
        srv.listen(64)
        self._server = srv
        for target, name in ((self._accept_loop, "ingest-accept"),
                             (self._housekeeping, "ingest-housekeeping")):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        return self

    @property
    def address(self) -> tuple[str, int]:
        if self._server is None:
            raise RuntimeError("coordinator not started")
        return self._server.getsockname()

    def register_local_job(self, job_id: str, source, *,
                           plan_fp: Optional[str] = None,
                           n_shards: Optional[int] = None,
                           max_buffered: Optional[int] = None) -> _Job:
        """Create an in-process job (the IngestCoordinator facade / embedded
        use). Freezes the file listing now; consume via `stream_local`."""
        files = source.list_files()
        n = len(files)
        shards = int(n_shards) if n_shards else max(
            1, min(_MAX_AUTO_SHARDS, n))
        job = _Job(job_id, source, plan_fp=plan_fp or "unfingerprintable",
                   n_shards=shards, files=files, local=True,
                   max_buffered=(max_buffered if max_buffered is not None
                                 else self.max_buffered))
        with self._cond:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id!r} already registered")
            self._jobs[job_id] = job
            now = time.monotonic()
            for s in range(job.n_shards):
                job.shards[s].pending_since = now
                self._pending.append((job_id, s))
            self._cond.notify_all()
            self._jobs_gauge()
        return job

    def spawn_workers(self, n: int, cache_dir: Optional[str] = None) -> list:
        """Launch n extraction worker SUBPROCESSES against this service
        (the production shape; `launch_local_workers` is the in-process twin
        for tests). Returns the Popen handles; close() reaps them."""
        host, port = self.address
        cache = cache_dir if cache_dir is not None else self.cache_dir
        for i in range(int(n)):
            # spawned through the documented CLI surface (`op ingest-worker`)
            # rather than runpy on the module, so the worker package is
            # imported exactly once in the child
            cmd = [sys.executable, "-m", "transmogrifai_tpu.cli.main",
                   "ingest-worker", "--connect", f"{host}:{port}",
                   "--worker-id", f"sub-{os.getpid()}-{len(self._procs)}"]
            if cache:
                cmd += ["--cache-dir", cache]
            self._procs.append(subprocess.Popen(cmd, env=dict(os.environ)))
        return list(self._procs)

    def launch_local_workers(self, n: int,
                             cache_dir: Optional[str] = None,
                             compress: bool = False) -> list:
        """n worker THREADS over real localhost sockets — the same protocol
        path as subprocesses, minus the process boundary (unit tests)."""
        host, port = self.address
        cache = cache_dir if cache_dir is not None else self.cache_dir
        out = []
        for i in range(int(n)):
            w = IngestWorker((host, port),
                             worker_id=f"thr-{len(self._local_workers)}",
                             cache_dir=cache, compress=compress)
            t = threading.Thread(target=w.run, daemon=True,
                                 name=f"ingest-worker-{i}")
            t.start()
            self._threads.append(t)
            self._local_workers.append(w)
            out.append(w)
        return out

    def request_stop(self) -> None:
        """Early-exit hook (`LiveSource.on_pipeline_close`): unblock local
        streams promptly; workers are told SHUTDOWN on their next poll."""
        with self._cond:
            self._stop_requested = True
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            crashed = self._crashed  # snapshot vs a concurrent _crash()
            self._cond.notify_all()
        if not crashed:
            # the CLEAN checkpoint: a later restart on this state_dir resumes
            # without counting a coordinator crash
            self._checkpoint(clean=True)
        for w in self._local_workers:
            w.stop()
        if self._server is not None:
            _sever(self._server)
        for c in list(self._conns):
            _sever(c)
        for p in self._procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 5.0
        for p in self._procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=5.0)
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=2.0)

    def __enter__(self) -> "IngestService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # --- chaos: coordinator death -----------------------------------------------------
    def _crash(self):
        """`coord:kill` landed: die the way SIGKILL dies — no clean
        checkpoint, no drain, connections severed mid-stream. The on-disk
        checkpoint stays whatever the cadence last wrote (clean=False), which
        is exactly what the restarted service restores from."""
        if self.kill_mode == "process":
            os.kill(os.getpid(), signal.SIGKILL)
        with self._cond:
            if self._crashed:
                raise ConnectionError("chaos: coordinator killed")
            self._crashed = True
            self._closed = True
            self._cond.notify_all()
        if self._server is not None:
            _sever(self._server)
        # shutdown-then-close so handler/sender threads parked in recv or
        # sendall on these sockets wake NOW — SIGKILL kills those threads
        # with the process, so an in-process crash must tear their streams
        # down for the same observable effect (peers see EOF immediately).
        # A connection accepted concurrently with this snapshot is severed
        # by _accept_loop's post-append _closed check (we set _closed above,
        # BEFORE taking the snapshot, so one of the two sides always wins).
        for c in list(self._conns):
            _sever(c)
        # local worker threads and subprocess workers are NOT touched: they
        # must survive the coordinator and re-adopt into its replacement
        raise ConnectionError("chaos: coordinator killed")

    # --- checkpoint / restore ---------------------------------------------------------
    def _state_path(self) -> Optional[str]:
        if not self.state_dir:
            return None
        return os.path.join(self.state_dir, _STATE_FILE)

    def _snapshot(self) -> dict:
        """Under _cond: the atomic restart unit — lease table + per-job
        acked frontiers + the frozen file listings the frontiers index."""
        jobs = {}
        for jid, job in self._jobs.items():
            if job.local:
                continue  # an in-process consumer dies with the process
            jobs[jid] = {
                "epoch": job.epoch,
                "plan": job.plan_fp,
                "source": job.source.to_wire(),
                "n_shards": job.n_shards,
                "files": job.files,
                "file_chunks": {str(k): v
                                for k, v in job.file_chunks.items()},
                "acked": list(job.acked),
                "shards": {str(s): {"granted": st.granted,
                                    "errors": st.errors}
                           for s, st in job.shards.items()},
                "leases": {str(s): lease.worker_id
                           for (j, s), lease in self._leases.items()
                           if j == jid},
            }
        return {"version": 1, "restarts": self._restarts, "jobs": jobs}

    def _checkpoint(self, clean: bool = False) -> None:
        path = self._state_path()
        if path is None:
            return
        with self._cond:
            snap = self._snapshot()
        snap["clean"] = bool(clean)
        with self._ckpt_lock:
            os.makedirs(self.state_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(snap, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        self._last_ckpt = time.monotonic()
        self._reg.gauge("ingest_checkpoint_age_seconds",
                        help="seconds since the service state last "
                             "checkpointed").set(0.0)

    def _restore(self) -> None:
        path = self._state_path()
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            return  # torn checkpoint from a crash mid-replace cannot happen
            # (os.replace is atomic); an unreadable file means no state
        self._restarts = int(data.get("restarts", 0))
        if not data.get("clean", True):
            self._restarts += 1
            self._counter("ingest_coordinator_restarts_total",
                          "ingest service restarts from a non-clean "
                          "(crashed) checkpoint").inc()
            obs.add_event("ingest:coordinator_restart",
                          restarts=self._restarts)
        # `start()` calls this before the accept/housekeeping threads exist,
        # but the registry mutations still go under the condvar: the lock
        # discipline is uniform (threadlint OP601) and the uncontended
        # acquisition is free
        with self._cond:
            for jid, jd in (data.get("jobs") or {}).items():
                try:
                    source = source_from_wire(jd["source"])
                except Exception:  # noqa: BLE001 — an unrestorable job is
                    continue       # skipped; its consumer re-registers with
                                   # a fresh source
                job = _Job(jid, source, plan_fp=jd.get("plan", "?"),
                           n_shards=int(jd["n_shards"]), files=jd["files"],
                           local=False, max_buffered=self.max_buffered,
                           epoch=int(jd.get("epoch", 0)))
                job.file_chunks = {int(k): int(v)
                                   for k, v in (jd.get("file_chunks") or
                                                {}).items()}
                af, ac = (list(jd.get("acked") or [0, 0]) + [0, 0])[:2]
                # clamp the frontier to the contiguous prefix of known chunk
                # counts: a file below the frontier with an unknown count
                # cannot be reconstructed, so delivery restarts from it (the
                # consumer client dedupes the overlap)
                for f in range(int(af)):
                    if f not in job.file_chunks:
                        af, ac = f, 0
                        break
                job.acked = [int(af), int(ac)]
                job.emit = list(job.acked)
                for f in range(int(af)):
                    for c in range(job.file_chunks[f]):
                        job.committed.add((f, c))
                for c in range(int(ac)):
                    job.committed.add((int(af), c))
                for s, sd in (jd.get("shards") or {}).items():
                    st = job.shards.get(int(s))
                    if st is not None:
                        st.granted = int(sd.get("granted", 0))
                        st.errors = int(sd.get("errors", 0))
                now = time.monotonic()
                for s in range(job.n_shards):
                    if job.shard_complete(s):
                        job.shards_done.add(s)
                    else:
                        job.shards[s].pending_since = now
                        self._pending.append((jid, s))
                self._jobs[jid] = job  # paused (conn=None) until JOB_OPEN
            self._jobs_gauge()

    # --- worker-facing server side ----------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return  # server socket closed: service over
            self._conns.append(conn)
            # check _closed AFTER the append: close()/_crash() set the flag
            # before snapshotting _conns, so a racing connection is severed
            # either there (appended before the snapshot) or here (appended
            # after — then this read of _closed sees True). Without this a
            # worker reconnecting in the crash window becomes a zombie
            # served by a handler on a "dead" service.
            if self._closed:  # threadlint: ok OP601 - ordering vs the _conns append (comment above) makes this bare read safe
                _sever(conn)
                continue
            # one per connection, all sharing one order-graph name (the
            # checker's same-name exemption covers peer send locks)
            self._send_locks[conn] = make_lock("IngestService._send_lock")
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True, name="ingest-conn")
            t.start()
            self._threads.append(t)

    def _send(self, conn: socket.socket, kind: int, payload: dict,
              buffers=None) -> None:
        """All frame sends go through the per-connection lock: a consumer
        connection is written by BOTH its handler thread (JOB_READY, stats)
        and its sender thread, and interleaved frames are torn frames."""
        lock = self._send_locks.get(conn)
        if lock is None:
            transport.send_frame(conn, kind, payload, buffers)
            return
        with lock:
            transport.send_frame(conn, kind, payload, buffers)

    def _handle(self, conn: socket.socket) -> None:
        worker: Optional[_Worker] = None
        consumer_job: Optional[_Job] = None
        try:
            while True:
                kind, payload = transport.recv_frame(conn)
                if kind == transport.HELLO:
                    worker = self._register(conn, payload)
                elif kind == transport.REQUEST_WORK:
                    self._grant_or_idle(conn, worker)
                elif kind in (transport.BATCH, transport.COLBATCH):
                    self._on_batch(conn, worker, kind, payload)
                elif kind == transport.FILE_DONE:
                    self._on_file_done(payload)
                elif kind == transport.SHARD_DONE:
                    self._on_shard_done(payload)
                elif kind == transport.HEARTBEAT:
                    self._refresh_lease(payload)
                elif kind == transport.ERROR:
                    self._on_worker_error(payload)
                elif kind == transport.JOB_OPEN:
                    consumer_job = self._job_open(conn, payload)
                elif kind == transport.JOB_ACK:
                    self._on_ack(payload)
                elif kind == transport.JOB_CLOSE:
                    self._job_close(payload)
                elif kind == transport.SVC_STATS:
                    self._send(conn, transport.SVC_STATS,
                               {"stats": self.service_stats()})
                elif kind == transport.METRICS:
                    self._on_metrics(payload)
                elif kind == transport.FLEET_METRICS:
                    self._send(conn, transport.FLEET_METRICS,
                               {"snapshots": self.fleet.raw_snapshots()})
                else:
                    raise transport.FrameError(f"unknown frame kind {kind}")
        except transport.FrameError as e:
            if not getattr(e, "counted", False):
                # transport-level corruption (CRC/short/garbage); chaos- and
                # plan-classified frame errors were already counted by kind
                self._counter("ingest_frame_errors_total",
                              "torn/corrupt/protocol frames on ingest "
                              "connections", kind="frame").inc()
            obs.add_event("ingest:frame_error", error=str(e)[:200])
            self._disconnect(conn, worker, consumer_job)
        except (ConnectionError, OSError):
            self._disconnect(conn, worker, consumer_job)

    def _on_metrics(self, payload: dict) -> None:
        """METRICS push from a worker: replace that process's latest snapshot
        in the aggregator (fire-and-forget — snapshots are cumulative, so a
        lost push is healed by the next one)."""
        self.fleet.ingest(str(payload.get("role", "ingest-worker")),
                          str(payload.get("process", "?")),
                          payload.get("snapshot") or {})
        self._counter("ingest_metrics_pushes_total",
                      "METRICS snapshot frames accepted for federation",
                      role="coordinator").inc()

    def _register(self, conn: socket.socket, payload: dict) -> _Worker:
        w = _Worker(worker_id=str(payload.get("worker_id", "?")),
                    pid=int(payload.get("pid", 0)), sock=conn)
        with self._cond:
            self._workers[w.worker_id] = w
            n_live = sum(1 for x in self._workers.values() if x.live)
        self._worker_gauges(n_live)
        obs.add_event("ingest:worker_join", worker=w.worker_id, pid=w.pid)
        return w

    def _disconnect(self, conn: socket.socket, worker: Optional[_Worker],
                    consumer_job: Optional[_Job] = None) -> None:
        _sever(conn)
        self._send_locks.pop(conn, None)
        with self._cond:
            if worker is not None:
                worker.live = False
                # pop the registry entry only if it is still OURS — a
                # reconnected incarnation under the same id must survive
                # the old handler's cleanup
                if self._workers.get(worker.worker_id) is worker:
                    self._workers.pop(worker.worker_id, None)
                self._revoke_worker_leases(worker)
            if consumer_job is not None and consumer_job.conn is conn:
                # the consumer died or went away: park the job — leases in
                # flight finish into the buffer, nothing new grants, and no
                # other job notices (isolation)
                consumer_job.conn = None
                consumer_job.conn_gen += 1
                obs.add_event("ingest:consumer_detach",
                              job=consumer_job.job_id)
            n_live = sum(1 for x in self._workers.values() if x.live)
            self._cond.notify_all()
        self._worker_gauges(n_live)

    # --- job resolution ---------------------------------------------------------------
    def _resolve_job(self, payload: dict) -> Optional[_Job]:
        """Under _cond. Map a worker frame to its job. Frames without a
        "job" field (the pre-service worker protocol, still spoken by raw
        test harnesses) resolve to the sole registered job. Frames for a job
        that no longer exists (consumer closed while the worker was still
        extracting) are EXPECTED in a shared fleet and return None — the
        caller drops them without killing the connection."""
        jid = payload.get("job")
        if jid is None:
            if len(self._jobs) == 1:
                return next(iter(self._jobs.values()))
            raise transport.FrameError(
                f"frame names no job and {len(self._jobs)} are registered")
        job = self._jobs.get(str(jid))
        if job is None:
            self._counter("ingest_stale_job_frames_total",
                          "worker frames for a job that was already "
                          "unregistered (consumer closed mid-extraction)"
                          ).inc()
        return job

    # --- leases -----------------------------------------------------------------------
    def _revoke_worker_leases(self, worker: _Worker) -> None:
        """Under _cond. Requeue every shard granted over the dead CONNECTION
        (object identity, not worker_id — see _Lease.owner), at the FRONT:
        the recovered shard is usually the one blocking emission."""
        for key, lease in list(self._leases.items()):
            if lease.owner is worker:
                del self._leases[key]
                job = self._jobs.get(key[0])
                if job is not None:
                    self._requeue(job, key[1])

    def _requeue(self, job: _Job, shard: int, front: bool = True) -> None:
        key = (job.job_id, shard)
        if (shard not in job.shards_done and key not in self._pending
                and shard not in job.self_extracting
                and not job.stop and not self._closed):
            if front:
                self._pending.insert(0, key)
            else:
                self._pending.append(key)
            job.shards[shard].pending_since = time.monotonic()
            self._cond.notify_all()

    def _expire_leases(self) -> None:
        """Under _cond: heartbeat expiry for wedged-but-connected holders
        (a DEAD holder is caught faster, by its connection EOF)."""
        now = time.monotonic()
        for key, lease in list(self._leases.items()):
            if now > lease.deadline:
                del self._leases[key]
                self._counter("ingest_lease_expired_total",
                              "leases revoked on heartbeat expiry "
                              "(wedged holder)").inc()
                obs.add_event("ingest:lease_expired", shard=lease.shard,
                              worker=lease.worker_id)
                job = self._jobs.get(key[0])
                if job is not None:
                    self._requeue(job, key[1])

    def _refresh_lease(self, payload: dict) -> None:
        with self._cond:
            job = self._resolve_job(payload)
            if job is None:
                return
            lease = self._leases.get((job.job_id,
                                      int(payload.get("shard", -1))))
            if lease is not None and lease.lease_id == int(
                    payload.get("lease", -1)):
                lease.deadline = time.monotonic() + self.lease_timeout_s

    def _lease_payload(self, job: _Job, shard: int, lease_id: int) -> dict:
        """Under _cond: the full replayable work description for a shard —
        file list plus everything already committed, so a replacement
        holder re-reads only what is actually missing."""
        st = job.shards[shard]
        files_done = {}
        committed: dict[int, list[int]] = {}
        for fi, _name in st.files:
            nc = job.file_chunks.get(fi)
            done = sorted(c for (f, c) in job.committed if f == fi)
            if nc is not None and len(done) >= nc:
                files_done[fi] = nc
            elif done:
                committed[fi] = done
        payload = {"job": job.job_id, "shard": shard,
                   "n_shards": job.n_shards,
                   "lease": lease_id, "plan": job.plan_fp,
                   "source": job.source.to_wire(),
                   "files": st.files, "files_done": files_done,
                   "committed": committed}
        # cross-process trace propagation: when the coordinator runs under a
        # tracer, every lease carries a TraceContext whose span_id anchors an
        # "ingest:lease" event here — the worker opens its extract span with
        # this id as remote_parent, and the stitch tool joins the two dumps
        tracer = obs.current()
        if tracer is not None:
            anchor = obs.new_span_id()
            obs.add_event("ingest:lease", job=job.job_id, shard=shard,
                          lease=lease_id, span_id=anchor)
            payload["ctx"] = obs.TraceContext(
                trace_id=tracer.trace_id, span_id=anchor).to_wire()
        return payload

    def _grantable(self, job: Optional[_Job]) -> bool:
        return (job is not None and not job.paused and not job.stop
                and job.error is None)

    def _all_jobs_done(self) -> bool:
        """Under _cond (single-epoch mode only): the facade's SHUTDOWN
        condition — the run's one job finished its epoch."""
        return all(j.done() for j in self._jobs.values())

    def _grant_or_idle(self, conn: socket.socket, worker: Optional[_Worker]
                       ) -> None:
        with self._cond:
            if self._crashed:
                # a SIGKILL'd coordinator cannot send frames — an in-process
                # crash must not either. Replying SHUTDOWN here would retire
                # a worker that is supposed to survive the crash and
                # re-adopt into the replacement service.
                raise ConnectionError("chaos: coordinator crashed")
            self._expire_leases()
            granted = None
            if (self._closed or self._stop_requested
                    or (worker is not None and worker.retire)
                    or (self.single_epoch and self._all_jobs_done())):
                reply = (transport.SHUTDOWN, {})
            else:
                for i, (jid, shard) in enumerate(self._pending):
                    job = self._jobs.get(jid)
                    if not self._grantable(job):
                        continue  # parked/failed jobs keep their queue slot
                    del self._pending[i]
                    self._next_lease_id += 1
                    lease_id = self._next_lease_id
                    st = job.shards[shard]
                    if st.granted > 0:
                        self._counter(
                            "ingest_lease_reassigned_total",
                            "shard leases granted after a previous holder "
                            "died, disconnected, or went quiet").inc()
                        obs.add_event(
                            "ingest:lease_reassigned", shard=shard,
                            worker=worker.worker_id if worker else "?")
                    st.granted += 1
                    if st.pending_since is not None:
                        self._reg.histogram(
                            "ingest_queue_wait_seconds",
                            help="seconds a pending shard waited for a "
                                 "holder (the autoscale signal)").observe(
                            time.monotonic() - st.pending_since)
                    st.pending_since = None
                    self._leases[(jid, shard)] = _Lease(
                        job_id=jid, shard=shard, lease_id=lease_id,
                        worker_id=worker.worker_id if worker else "?",
                        deadline=time.monotonic() + self.lease_timeout_s,
                        owner=worker)
                    granted = (transport.LEASE,
                               self._lease_payload(job, shard, lease_id))
                    break
                reply = granted or (transport.IDLE, {"poll_s": self.poll_s})
        self._send(conn, *reply)

    # --- data plane -------------------------------------------------------------------
    def _check_plan(self, job: _Job, payload: dict, what: str) -> None:
        """Every STATE-WRITING frame (BATCH, FILE_DONE, SHARD_DONE) must
        carry its job's plan fingerprint: a stale worker from a previous
        run (same service port reused) must not commit rows, write chunk
        counts emission trusts, or mark shards done it never extracted."""
        if payload.get("plan") != job.plan_fp:
            self._counter("ingest_frame_errors_total",
                          "torn/corrupt/protocol frames on ingest "
                          "connections", kind="plan").inc()
            err = transport.FrameError(
                f"plan fingerprint mismatch on {what}")
            err.counted = True
            raise err

    def _on_batch(self, conn: socket.socket, worker: Optional[_Worker],
                  kind: int, payload: dict) -> None:
        shard = int(payload["shard"])
        seq = int(payload["seq"])
        with self._cond:
            job = self._resolve_job(payload)
        if job is None:
            return  # stale-job frame: dropped, counted in _resolve_job
        self._check_plan(job, payload, f"BATCH shard {shard} seq {seq}")
        if chaos.maybe_coord_kill(job.epoch, seq):
            self._crash()
        fault = chaos.maybe_ingest_fault(shard, seq)
        if fault == "torn":
            self._counter("ingest_frame_errors_total",
                          "torn/corrupt/protocol frames on ingest "
                          "connections", kind="torn").inc()
            err = transport.FrameError(
                f"chaos: torn frame (shard {shard} seq {seq})")
            err.counted = True
            raise err
        if fault == "drop":
            raise ConnectionError(
                f"chaos: connection severed (shard {shard} seq {seq})")
        if kind == transport.COLBATCH:
            # store the columnar payload AS buffers: decode happens on the
            # delivery edge (local stream) or not at all (remote jobs relay
            # the buffers verbatim to the consumer)
            meta = {"fields": payload["fields"], "n": payload["n"],
                    "nulls": payload.get("nulls") or {}}
            if payload.get("compression"):
                # keep the worker's deflated buffers AS-IS: the delivery
                # edge (frames.decode_columns / the sender's negotiation)
                # inflates, so the buffer holds the small form
                meta["compression"] = payload["compression"]
                self._counter("ingest_compressed_batches_total",
                              "zlib-compressed columnar batches crossing "
                              "an ingest wire edge", edge="worker").inc()
            data = (meta, [bytes(b) for b in payload["__buffers__"]])
        else:
            data = payload["rows"]
        self._commit(job, int(payload["file"]), int(payload["chunk"]),
                     data, shard=shard)
        if fault == "kill":
            self._kill_worker(worker, conn)

    def _commit(self, job: _Job, file_index: int, chunk: int, data, *,
                shard: Optional[int] = None) -> None:
        key = (file_index, chunk)
        with self._cond:
            if shard is not None:
                lease = self._leases.get((job.job_id, shard))
                if lease is not None:
                    lease.deadline = time.monotonic() + self.lease_timeout_s
            if key in job.committed:
                self._counter("ingest_duplicate_batches_total",
                              "replayed batches dropped by ordinal dedupe "
                              "(exactly-once enforcement)").inc()
                return
            if job.local:
                # bounded reorder buffer: far-ahead batches wait for the
                # consumer; the NEXT-NEEDED batch is always admitted, so
                # this backpressure can never deadlock emission
                while (len(job.buffer) >= job.max_buffered
                       and key != tuple(job.emit)
                       and not (self._closed or self._stop_requested
                                or job.error or job.stop)):
                    self._cond.wait(0.2)
                    if shard is not None:
                        # a holder parked in backpressure is healthy, not
                        # wedged: keep its lease fresh for the whole wait,
                        # not just the deadline stamped at entry
                        lease = self._leases.get((job.job_id, shard))
                        if lease is not None:
                            lease.deadline = (time.monotonic()
                                              + self.lease_timeout_s)
                if self._closed or self._stop_requested or job.stop:
                    return
            elif (len(job.buffer) >= job.max_buffered
                    and key != tuple(job.emit)):
                # a REMOTE job must never block a SHARED worker thread:
                # shed the far-ahead batch (NOT committed — the SHARD_DONE
                # completeness check requeues the gap once there is room)
                self._counter("ingest_backpressure_shed_total",
                              "far-ahead batches shed by a full per-job "
                              "buffer (slow or detached remote consumer)"
                              ).inc()
                return
            job.committed.add(key)
            job.buffer[key] = data
            self._cond.notify_all()
        # role-labeled edge counters: the federation layer distinguishes the
        # same series pushed by different processes, so the label scheme must
        # exist BEFORE fleet merge lands these under /fleet/metrics
        self._counter("ingest_batches_total",
                      "batches committed from extraction workers",
                      role="coordinator").inc()
        self._counter("ingest_rows_total",
                      "rows committed from extraction workers",
                      role="coordinator").inc(payload_nrows(data))

    def _on_file_done(self, payload: dict) -> None:
        with self._cond:
            job = self._resolve_job(payload)
        if job is None:
            return
        self._check_plan(job, payload,
                         f"FILE_DONE file {payload.get('file')}")
        with self._cond:
            job.file_chunks[int(payload["file"])] = int(payload["chunks"])
            self._cond.notify_all()
        outcome = payload.get("cache")
        if outcome in ("hit", "miss"):
            name = ("ingest_cache_hits_total" if outcome == "hit"
                    else "ingest_cache_misses_total")
            self._counter(name, "materialized-feature cache outcomes (one "
                                "lookup per extracted file)").inc()

    def _on_shard_done(self, payload: dict) -> None:
        with self._cond:
            job = self._resolve_job(payload)
        if job is None:
            return
        self._check_plan(job, payload,
                         f"SHARD_DONE shard {payload.get('shard')}")
        shard = int(payload["shard"])
        stats = payload.get("stats") or {}
        with self._cond:
            lease = self._leases.get((job.job_id, shard))
            if lease is not None and lease.lease_id == int(
                    payload.get("lease", -1)):
                del self._leases[(job.job_id, shard)]
            if job.shard_complete(shard):
                job.shards_done.add(shard)
            else:
                # the holder extracted everything but some of it was SHED
                # (full remote buffer): the shard is NOT done — requeue at
                # the back so replay fills the gaps once there is room
                self._counter("ingest_shard_requeued_total",
                              "shards requeued by the SHARD_DONE "
                              "completeness check (shed gaps)").inc()
                self._requeue(job, shard, front=False)
            self._cond.notify_all()
        obs.add_event("ingest:shard_done", shard=shard,
                      rows=int(stats.get("rows", 0)),
                      cache_hits=int(stats.get("cache_hits", 0)))

    def _on_worker_error(self, payload: dict) -> None:
        with self._cond:
            job = self._resolve_job(payload)
        if job is None:
            return
        self._check_plan(job, payload,
                         f"ERROR shard {payload.get('shard')}")
        shard = int(payload["shard"])
        msg = (f"shard {shard} extraction failed on worker: "
               f"{payload.get('type')}: {payload.get('message')}")
        self._counter("ingest_shard_errors_total",
                      "worker-reported extraction failures").inc()
        with self._cond:
            lease = self._leases.get((job.job_id, shard))
            if lease is not None and lease.lease_id == int(
                    payload.get("lease", -1)):
                del self._leases[(job.job_id, shard)]
            st = job.shards[shard]
            st.errors += 1
            if st.errors >= 2:
                # two independent holders failed: the data is bad, fail the
                # JOB the way the in-process reader would — other jobs are
                # untouched
                job.error = IngestError(msg)
            else:
                self._requeue(job, shard)
            self._cond.notify_all()

    def _kill_worker(self, worker: Optional[_Worker],
                     conn: socket.socket) -> None:
        """Chaos `worker:kill`: SIGKILL the frame's sender (subprocess
        workers; a thread worker cannot be SIGKILLed, so only its connection
        dies — the recovery path under test is identical). The connection is
        ALWAYS severed at the kill ordinal, discarding any frames the dying
        worker had already flushed into the socket buffer: the contract "the
        holder died at batch N, everything after N is re-extracted under the
        reassigned lease" stays deterministic instead of depending on how
        much the kernel had buffered at SIGKILL time."""
        if worker is not None and worker.pid and worker.pid != os.getpid():
            try:
                os.kill(worker.pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass
            else:
                # wait for the death before severing/requeueing: a victim
                # that notices its dead socket in the ms before the signal
                # lands could otherwise reconnect, grab the requeued lease,
                # and orphan it again — recovery still works (a second
                # reassignment), but the event/counter schedule under test
                # must be deterministic
                for p in self._procs:
                    if p.pid == worker.pid:
                        try:
                            p.wait(timeout=10.0)
                        except subprocess.TimeoutExpired:
                            pass
                        break
                else:
                    deadline = time.monotonic() + 10.0
                    while time.monotonic() < deadline:
                        try:
                            os.kill(worker.pid, 0)
                        except ProcessLookupError:
                            break
                        time.sleep(0.01)
        raise ConnectionError("chaos: worker killed at its lease's ordinal; "
                              "connection severed")

    # --- consumer side: remote jobs ---------------------------------------------------
    def _job_open(self, conn: socket.socket, payload: dict) -> Optional[_Job]:
        """Idempotent attach-or-create: a consumer's first JOB_OPEN creates
        the job; a reconnecting (or post-restart) consumer's JOB_OPEN
        attaches to the surviving state and delivery resumes from the
        service's acked frontier (the client dedupes the overlap)."""
        jid = str(payload.get("job", ""))
        if not jid:
            raise transport.FrameError("JOB_OPEN without a job id")
        with self._cond:
            job = self._jobs.get(jid)
            resumed = job is not None
            if job is None:
                if "source" not in payload:
                    self._send(conn, transport.JOB_ERROR,
                               {"job": jid, "type": "KeyError",
                                "message": "unknown job and no source spec "
                                           "to create it from"})
                    return None
                source = source_from_wire(payload["source"])
                files = source.list_files()
                n_given = payload.get("n_shards")
                n_shards = int(n_given) if n_given else max(
                    1, min(_MAX_AUTO_SHARDS, len(files)))
                job = _Job(jid, source,
                           plan_fp=str(payload.get("plan",
                                                   "unfingerprintable")),
                           n_shards=n_shards, files=files, local=False,
                           max_buffered=self.max_buffered,
                           epoch=int(payload.get("epoch", 0)))
                self._jobs[jid] = job
                now = time.monotonic()
                for s in range(job.n_shards):
                    job.shards[s].pending_since = now
                    self._pending.append((jid, s))
                self._jobs_gauge()
            else:
                if job.local:
                    self._send(conn, transport.JOB_ERROR,
                               {"job": jid, "type": "ValueError",
                                "message": "job is in-process (local)"})
                    return None
                old = job.conn
                job.conn_gen += 1
                if old is not None and old is not conn:
                    _sever(old)  # kick a superseded consumer connection
                req_epoch = int(payload.get("epoch", 0))
                if req_epoch > job.epoch:
                    # EPOCH REPLAY: re-stream the SAME frozen file listing
                    # from the start — the listing is NOT re-registered
                    # (source.list_files() ran exactly once, at job
                    # creation), and extraction replays through the
                    # workers' materialized-feature cache, so the second
                    # pass re-parses nothing and is byte-identical to the
                    # first (the cache key is content-addressed, the chunk
                    # ordinals deterministic).
                    job.epoch = req_epoch
                    job.acked = [0, 0]
                    job.emit = [0, 0]
                    job.committed = set()
                    job.buffer = {}
                    job.shards_done = set()
                    job.file_chunks = {}
                    job.eof_sent = False
                    job.error = None
                    self._counter("ingest_epoch_replays_total",
                                  "JOB_OPEN re-attaches that replayed an "
                                  "already-streamed listing as a new "
                                  "epoch").inc()
                    obs.add_event("ingest:job_epoch_replay", job=jid,
                                  epoch=req_epoch)
                # attach-reset: resume delivery from the acked frontier.
                # Anything sent-but-unacked was popped from the buffer and
                # may be lost with the old connection, so the committed set
                # is REBUILT as {below frontier} + {still buffered}; chunks
                # that fall out become gaps, and gap shards requeue.
                job.emit = list(job.acked)
                frontier = tuple(job.acked)
                job.committed = ({k for k in job.committed if k < frontier}
                                 | set(job.buffer))
                job.eof_sent = False
                job.error_sent = False
                for s in list(job.shards_done):
                    if not job.shard_complete(s):
                        job.shards_done.discard(s)
                for s in range(job.n_shards):
                    if (s not in job.shards_done
                            and (jid, s) not in self._pending
                            and (jid, s) not in self._leases
                            and s not in job.self_extracting):
                        self._requeue(job, s, front=False)
            # per-attach option negotiation: compressed JOB_BATCH buffers go
            # only to consumers that asked (old consumers keep plain frames)
            opts = payload.get("options") or {}
            job.wire_compression = ("zlib" if opts.get("compression")
                                    == "zlib" else None)
            job.conn = conn
            gen = job.conn_gen
            self._cond.notify_all()
        obs.add_event("ingest:job_open", job=jid, resumed=resumed,
                      epoch=job.epoch)
        self._send(conn, transport.JOB_READY,
                   {"job": jid, "resumed": resumed,
                    "n_files": len(job.files), "epoch": job.epoch})
        t = threading.Thread(target=self._sender, args=(conn, job, gen),
                             daemon=True, name=f"ingest-send-{jid}")
        t.start()
        self._threads.append(t)
        return job

    def _on_ack(self, payload: dict) -> None:
        with self._cond:
            job = self._jobs.get(str(payload.get("job", "")))
            if job is None:
                return
            cur = (int(payload.get("file", 0)), int(payload.get("chunk", 0)))
            if cur > tuple(job.acked):
                job.acked = list(cur)
                self._cond.notify_all()

    def _job_close(self, payload: dict) -> None:
        jid = str(payload.get("job", ""))
        with self._cond:
            job = self._jobs.pop(jid, None)
            if job is None:
                return
            job.stop = True
            job.conn_gen += 1           # the sender thread exits
            self._pending = [(j, s) for (j, s) in self._pending if j != jid]
            for key in [k for k in self._leases if k[0] == jid]:
                del self._leases[key]
            self._cond.notify_all()
            self._jobs_gauge()
        obs.add_event("ingest:job_close", job=jid)
        if self.state_dir:
            self._checkpoint()

    def _inflight(self, job: _Job) -> int:
        """Under _cond: batches sent but not yet acked = chunk keys in
        [acked, emit). Every intermediate file's chunk count is known (the
        emit cursor only advances past a file once it is), so this is exact
        — and it self-heals to 0 on attach-reset without a counter to
        un-skew."""
        (af, ac), (ef, ec) = tuple(job.acked), tuple(job.emit)
        if (af, ac) >= (ef, ec):
            return 0
        if af == ef:
            return ec - ac
        n = job.file_chunks.get(af, ac) - ac
        for f in range(af + 1, ef):
            n += job.file_chunks.get(f, 0)
        return n + ec

    def _next_send(self, job: _Job):
        """Under _cond: the sender state machine — the next frame to put on
        the consumer connection, or None (wait). The inflight window is
        checked BEFORE popping the buffer so a window-blocked batch is never
        popped-and-parked."""
        if job.error is not None:
            if job.error_sent:
                return None
            job.error_sent = True
            return ("error", type(job.error).__name__, str(job.error))
        ef, ec = job.emit
        while ef < len(job.files):
            nc = job.file_chunks.get(ef)
            if nc is not None and ec >= nc:
                job.emit = [ef + 1, 0]
                return ("file_end", ef, nc)
            if self._inflight(job) >= self.inflight_window:
                return None
            key = (ef, ec)
            if key in job.buffer:
                data = job.buffer.pop(key)
                job.emit = [ef, ec + 1]
                self._cond.notify_all()  # buffer space for parked committers
                return ("batch", ef, ec, data)
            return None
        if not job.eof_sent:
            job.eof_sent = True
            return ("eof",)
        return None

    def _sender(self, conn: socket.socket, job: _Job, gen: int) -> None:
        """Per-attachment delivery thread: drains the job's reorder buffer
        onto the consumer connection in exact (file, chunk) order, under the
        ack-window flow control. Dies silently when superseded (conn_gen
        moved on) — the replacement attachment has its own sender."""
        try:
            while True:
                with self._cond:
                    while True:
                        if (self._closed or job.conn is not conn
                                or job.conn_gen != gen):
                            return
                        act = self._next_send(job)
                        if act is not None:
                            break
                        self._cond.wait(self.poll_s)
                if act[0] == "batch":
                    _, f, c, data = act
                    meta = {"job": job.job_id, "file": f, "chunk": c}
                    if isinstance(data, tuple):
                        cmeta, buffers = data
                        meta.update(fields=cmeta["fields"], n=cmeta["n"],
                                    nulls=cmeta.get("nulls") or {})
                        stored = cmeta.get("compression")
                        want = job.wire_compression
                        if want and not stored:
                            buffers = compress_buffers(buffers)
                        elif stored and not want:
                            buffers = decompress_buffers(buffers)
                        if want:
                            meta["compression"] = want
                            self._counter(
                                "ingest_compressed_batches_total",
                                "zlib-compressed columnar batches crossing "
                                "an ingest wire edge",
                                edge="consumer").inc()
                        self._send(conn, transport.JOB_BATCH, meta, buffers)
                    else:
                        meta["rows"] = data
                        self._send(conn, transport.JOB_BATCH, meta)
                elif act[0] == "file_end":
                    self._send(conn, transport.JOB_FILE_END,
                               {"job": job.job_id, "file": act[1],
                                "chunks": act[2]})
                elif act[0] == "eof":
                    self._send(conn, transport.JOB_EOF, {"job": job.job_id})
                    obs.add_event("ingest:job_eof", job=job.job_id)
                else:  # "error"
                    self._send(conn, transport.JOB_ERROR,
                               {"job": job.job_id, "type": act[1],
                                "message": act[2][:500]})
        except (ConnectionError, OSError):
            with self._cond:
                if job.conn is conn and job.conn_gen == gen:
                    job.conn = None
                    job.conn_gen += 1
                    self._cond.notify_all()

    # --- consumer side: local jobs ----------------------------------------------------
    def _next_ready(self, job: _Job):
        """Under _cond: pop the next in-order payload if present; returns
        (payload,) or None. Advances the emit cursor across completed
        files. () means every file fully emitted."""
        while True:
            if job.emit[0] >= len(job.files):
                return ()
            nc = job.file_chunks.get(job.emit[0])
            if nc is not None and job.emit[1] >= nc:
                job.emit = [job.emit[0] + 1, 0]
                continue
            key = tuple(job.emit)
            if key in job.buffer:
                data = job.buffer.pop(key)
                job.emit = [job.emit[0], job.emit[1] + 1]
                job.acked = list(job.emit)  # local: consumed == acked
                self._cond.notify_all()
                return (data,)
            return None

    def _stalled_shard(self, job: _Job) -> Optional[int]:
        """Under _cond: the shard owning the job's next-needed file, IF it
        has sat pending past the fallback grace period — the signal that
        nobody is coming for it and the service should extract it inline."""
        if job.emit[0] >= len(job.files):
            return None
        shard = job.emit[0] % job.n_shards
        st = job.shards[shard]
        if ((job.job_id, shard) in self._pending
                and st.pending_since is not None
                and time.monotonic() - st.pending_since
                >= self.self_extract_after_s):
            return shard
        return None

    def _start_self_extract(self, job: _Job, shard: int) -> None:
        """Kick off in-process fallback extraction of one shard on its OWN
        thread — never the consumer's: the fallback obeys the same reorder-
        buffer backpressure as any worker, so it needs the consumer free to
        keep draining (running it inline would deadlock the pair)."""
        with self._cond:
            key = (job.job_id, shard)
            if key not in self._pending:
                return
            self._pending.remove(key)
            job.self_extracting.add(shard)
            job.shards[shard].granted += 1
            lease = self._lease_payload(job, shard, lease_id=-1)
        t = threading.Thread(target=self._self_extract,
                             args=(job, shard, lease),
                             daemon=True, name=f"ingest-fallback-{shard}")
        t.start()
        self._threads.append(t)

    def _self_extract(self, job: _Job, shard: int, lease: dict) -> None:
        """Fallback extraction body, through the SAME extract_shard code the
        workers run — ordinals and payload bytes cannot diverge from a
        worker's."""
        self._counter("ingest_self_extracted_shards_total",
                      "shards the coordinator extracted in-process after "
                      "no worker claimed them within the grace period"
                      ).inc()
        obs.add_event("ingest:self_extract", shard=shard, job=job.job_id)
        from .cache import FeatureCache

        cache = FeatureCache(self.cache_dir) if self.cache_dir else None

        def file_done(fi, nc, cache_outcome=None):
            self._on_file_done({"job": job.job_id, "file": fi, "chunks": nc,
                                "plan": job.plan_fp, "cache": cache_outcome})

        try:
            stats = extract_shard(
                job.source, lease,
                lambda seq, fi, ci, rows: self._commit(job, fi, ci, rows),
                file_done, cache=cache)
            self._on_shard_done({"job": job.job_id, "shard": shard,
                                 "lease": -1, "plan": job.plan_fp,
                                 "stats": stats})
        except Exception as e:  # noqa: BLE001 — job-fatal, like in-process
            with self._cond:
                job.error = e
                self._cond.notify_all()
        finally:
            with self._cond:
                job.self_extracting.discard(shard)

    def stream_local(self, job_id: str) -> Iterator[list]:
        """Ordered, exactly-once batch stream for a LOCAL job. Blocks for
        late batches; runs lease expiry and the fallback-extraction check
        from its wait loop (the single-job coordinator contract — prompt
        even without the housekeeping thread)."""
        if self._server is None:
            self.start()
        with self._cond:
            job = self._jobs[job_id]
        while True:
            fallback_shard = None
            with self._cond:
                while True:
                    if job.error is not None:
                        raise job.error
                    if self._crashed:
                        raise ConnectionError("ingest service crashed")
                    if self._closed or self._stop_requested or job.stop:
                        return
                    ready = self._next_ready(job)
                    if ready == ():
                        return  # every file fully emitted
                    if ready is not None:
                        data = ready[0]
                        break
                    self._expire_leases()
                    fallback_shard = self._stalled_shard(job)
                    if fallback_shard is not None:
                        break
                    self._cond.wait(self.poll_s)
            if fallback_shard is not None:
                self._start_self_extract(job, fallback_shard)
                continue
            yield payload_rows(data)

    # --- housekeeping -----------------------------------------------------------------
    def _housekeeping(self) -> None:
        """The service's background beat: lease expiry, stalled-shard
        fallback for REMOTE jobs (local jobs run it from their stream loop),
        autoscaling, the checkpoint cadence, and gauges."""
        while True:
            with self._cond:
                if self._closed or self._crashed:
                    return
                self._expire_leases()
                stalled = []
                for job in self._jobs.values():
                    if (not job.local and not job.paused and not job.stop
                            and job.error is None):
                        s = self._stalled_shard(job)
                        if s is not None:
                            stalled.append((job, s))
                n_live = sum(1 for w in self._workers.values() if w.live)
                crashed = self._crashed
            for job, s in stalled:
                self._start_self_extract(job, s)
            self._autoscale_tick()
            if self.state_dir and not crashed:
                if (self._last_ckpt is None
                        or time.monotonic() - self._last_ckpt
                        >= self.checkpoint_every_s):
                    self._checkpoint()
            self._worker_gauges(n_live)
            with self._cond:
                self._jobs_gauge()
            if self._last_ckpt is not None:
                self._reg.gauge(
                    "ingest_checkpoint_age_seconds",
                    help="seconds since the service state last "
                         "checkpointed").set(
                    round(time.monotonic() - self._last_ckpt, 3))
            time.sleep(self.poll_s)

    def _autoscale_tick(self) -> None:
        cfg = self.autoscale
        if cfg is None:
            return
        now = time.monotonic()
        with self._cond:
            live = [w for w in self._workers.values()
                    if w.live and not w.retire]
            oldest = None
            busy = bool(self._leases)
            for jid, s in self._pending:
                job = self._jobs.get(jid)
                if not self._grantable(job):
                    continue
                busy = True
                since = job.shards[s].pending_since
                if since is not None:
                    age = now - since
                    if oldest is None or age > oldest:
                        oldest = age
        if now - self._as_last < cfg.cooldown_s:
            return
        if (oldest is not None and oldest >= cfg.scale_up_wait_s
                and len(live) < cfg.max_workers):
            self._as_last = now
            self._as_idle_since = None
            self._counter("ingest_autoscale_total",
                          "autoscale actions on the worker fleet",
                          action="spawn").inc()
            obs.add_event("ingest:autoscale", action="spawn",
                          queue_wait_s=round(oldest, 3), workers=len(live))
            try:
                self._spawn_fn(self, 1)
            except Exception as e:  # noqa: BLE001 — degraded, not fatal:
                # self-extraction still finishes every job
                obs.add_event("ingest:autoscale_spawn_failed",
                              error=str(e)[:200])
            return
        if busy:
            self._as_idle_since = None
            return
        if self._as_idle_since is None:
            self._as_idle_since = now
            return
        if (now - self._as_idle_since >= cfg.scale_down_idle_s
                and len(live) > cfg.min_workers):
            victim = live[-1]  # most recently registered
            with self._cond:
                victim.retire = True
            self._as_last = now
            self._as_idle_since = now
            self._counter("ingest_autoscale_total",
                          "autoscale actions on the worker fleet",
                          action="retire").inc()
            obs.add_event("ingest:autoscale", action="retire",
                          worker=victim.worker_id)

    # --- introspection ----------------------------------------------------------------
    def job_stats(self, job_id: str) -> dict:
        with self._cond:
            job = self._jobs[job_id]
            return {
                "n_files": len(job.files),
                "n_shards": job.n_shards,
                "shards_done": len(job.shards_done),
                "pending": [s for (j, s) in self._pending if j == job_id],
                "leases": {s: lease.worker_id
                           for (j, s), lease in self._leases.items()
                           if j == job_id},
                "workers": sorted(self._workers),
                "committed": len(job.committed),
                "buffered": len(job.buffer),
                "acked": list(job.acked),
                "paused": job.paused,
            }

    def service_stats(self) -> dict:
        with self._cond:
            return {
                "restarts": self._restarts,
                "n_jobs": len(self._jobs),
                "jobs": {jid: {"done": job.done(), "paused": job.paused,
                               "acked": list(job.acked),
                               "epoch": job.epoch,
                               "committed": len(job.committed)}
                         for jid, job in self._jobs.items()},
                "workers": sorted(w for w, x in self._workers.items()
                                  if x.live),
                "pending": len(self._pending),
                "leases": len(self._leases),
            }
