from .aot import export_aot, hydrate, read_index
from .autopilot import Autopilot, AutopilotConfig, DriftScenario
from .batcher import MicroBatcher
from .daemon import (
    DaemonClient,
    ServingDaemon,
    fingerprint_model_dir,
    make_http_server,
    serving_buckets,
)
from .feedback import AuditSink, LabelJoiner, QualityPlane, extract_score
from .scoring import ScoreFunction, score_function

__all__ = [
    "AuditSink", "Autopilot", "AutopilotConfig", "DaemonClient",
    "DriftScenario", "LabelJoiner", "MicroBatcher", "QualityPlane",
    "ScoreFunction", "ServingDaemon",
    "export_aot", "extract_score", "fingerprint_model_dir", "hydrate",
    "make_http_server", "read_index", "score_function", "serving_buckets",
]
