from .scoring import ScoreFunction, score_function

__all__ = ["ScoreFunction", "score_function"]
