from .batcher import MicroBatcher
from .daemon import (
    DaemonClient,
    ServingDaemon,
    fingerprint_model_dir,
    make_http_server,
    serving_buckets,
)
from .scoring import ScoreFunction, score_function

__all__ = [
    "DaemonClient", "MicroBatcher", "ScoreFunction", "ServingDaemon",
    "fingerprint_model_dir", "make_http_server", "score_function",
    "serving_buckets",
]
