"""Closed-loop autopilot: drift-triggered retrain, champion/challenger
gating, and zero-downtime hot swap.

The continuous-training story of TensorFlow-at-scale (PAPERS.md arXiv
1605.08695) wired out of pieces this repo already has: the ServingMonitor's
drift gauges (PR 5), content-fingerprint model admission (PR 7), atomic
saves + AOT artifacts (PR 6/8), warm-start refit (this PR), and the seeded
chaos harness (PR 6). The loop:

    observe   the daemon's per-model drift monitor (`serving_js_divergence`
              / `serving_fill_rate` gauges + active DriftAlerts) — a breach
              must SUSTAIN across `breach_checks` consecutive polls before
              anything retrains (one weird batch is not a regime change);
    retrain   a fresh workflow over fresh data (the aggregate/conditional
              readers in production; the seeded DriftScenario here), warm-
              started from the current champion's fitted params where the
              winning family supports it (`Workflow.with_warm_start`);
    gate      lint the candidate (`oplint` via analyze_model), then evaluate
              champion vs challenger on a SHARED holdout: promotion requires
              beating the champion by `promotion_margin` on the configured
              metric — a retrain that fails lint, evaluates worse, or
              crashes is rejected and the champion keeps serving;
    swap      save the candidate bundle (atomic; optional AOT export) and
              hot-swap it into the daemon via ALIAS REPOINT
              (`ServingDaemon.swap`): NAME -> new content fingerprint,
              in-flight work drains on the old entry, the first request on
              the new one hits admission-warmed executables. The previous
              champion stays resident — `rollback()` repoints back in O(1).

Robustness is the contract (docs/robustness.md "Autopilot failure model"):
every step consults the chaos harness (`autopilot:retrain`,
`autopilot:save`, swap-time `serve:dispatch` device faults), and each
failure mode degrades to "the champion keeps serving with zero request
errors". Every decision lands in `Autopilot.events` — a structured log
containing NO wall-clock, uids, or fingerprints, so the same seed + the
same synthetic stream replays the whole loop byte-identically (pinned by
tests/test_autopilot.py).

`op autopilot` runs the loop against an app-provided wiring; bench_extra's
`run_autopilot` lane measures time-to-recover-AuPR on a drifting stream.
"""
from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from .. import obs
from ..resilience import chaos
from ..resilience.lockcheck import make_lock

_logger = logging.getLogger(__name__)


@dataclass
class AutopilotConfig:
    """When the loop acts, and what promotion requires."""

    #: consecutive drifted polls (any active DriftAlert on the served
    #: model's monitor) before a retrain triggers — the sustained-breach
    #: debounce. A failed retrain resets the streak, so the loop re-arms
    #: instead of hot-looping on a persistent failure.
    breach_checks: int = 2
    #: holdout-metric margin the challenger must beat the champion by
    #: (direction-aware). 0.0 = any strict improvement-or-tie promotes.
    promotion_margin: float = 0.0
    #: gate metric: an attribute (or to_json key) of the evaluator's
    #: metrics object — AuPR for the binary default.
    metric: str = "AuPR"
    larger_is_better: bool = True
    #: evaluator problem type for the default evaluator factory
    problem_type: str = "binary"
    #: export AOT deploy artifacts with the candidate bundle (save pays the
    #: compiles; the swap then hydrates instead of compiling). ON by default:
    #: retrain candidates are born with their serving artifacts, so a
    #: promoted challenger's first post-swap score deserializes in
    #: milliseconds with zero compile events. An export failure degrades to
    #: save_failed (champion keeps serving, aot_fallback_total counts it).
    export_aot: bool = True
    #: retire (drain + release) the demoted champion after a swap instead
    #: of keeping it resident as the rollback target
    retire_old: bool = False
    #: retrain under the champion's `op autotune` stamp: re-apply the tuned
    #: mesh shape, kernel knobs, and env for the challenger's train, so a
    #: tuned fleet doesn't silently regress to data-sheet defaults on the
    #: first drift-triggered retrain. A stamp from a different part (or no
    #: stamp at all) degrades to the untuned path.
    use_tuned_config: bool = True
    #: candidate bundles past the newest N are swept from the workdir
    #: (rollback targets stay loadable; disk stays bounded)
    keep_candidates: int = 4
    #: second trigger tier alongside feature drift: active QualityAlerts on
    #: the served model's quality plane (label-feedback AuPR/Brier breaching
    #: the stamped holdout baseline — obs/quality.py) count as a breach and
    #: debounce/retrain exactly like covariate drift. Catches the concept
    #: flip feature monitoring is structurally blind to: labels invert while
    #: every feature marginal stays put. Ignored when the daemon was started
    #: without `quality=`.
    quality_trigger: bool = True
    #: cap on total promotions (None = unbounded): the CLI's safety rail
    max_promotions: Optional[int] = None


def default_evaluator(model, problem_type: str = "binary"):
    """Evaluator over a model's OWN feature names (result-feature names
    carry per-process uids, so champion and challenger each need their own
    evaluator even though they score the same holdout)."""
    from ..evaluators import Evaluators

    resp = next(f.name for f in model.raw_features if f.is_response)
    pred = model.result_features[0].name
    if problem_type == "binary":
        return Evaluators.binary_classification(resp, pred)
    if problem_type == "multiclass":
        return Evaluators.multi_classification(resp, pred)
    return Evaluators.regression(resp, pred)


class Autopilot:
    """The controller behind `op autopilot`.

    Wiring:
      daemon            a ServingDaemon constructed with `monitor=` armed
                        (the loop reads each entry's ServingMonitor)
      name              the serving ALIAS the loop owns (requests resolve
                        through it; promotion repoints it)
      workflow_factory  () -> Workflow with result features set and a reader
                        over FRESH data (aggregate/conditional readers in
                        production). Called once per retrain; the autopilot
                        applies `with_warm_start(champion)` before training.
      holdout           the shared gate set: a Table / DataReader carrying
                        the labeled raw columns, or a callable returning one
                        (called once per gate — both models score the SAME
                        object, so the comparison is apples-to-apples)
      workdir           where candidate bundles are saved
      evaluator_factory optional (model) -> evaluator override; the default
                        builds from config.problem_type over the model's
                        own feature names

    `step()` runs one observe->decide->maybe-act cycle synchronously and
    returns the structured decision; `run()` loops it on a poll interval
    with the retrain/gate/swap pipeline on a background thread, so polling
    (and serving — which lives on the daemon's own threads throughout)
    never blocks on a training run.
    """

    def __init__(self, daemon, name: str, *,
                 workflow_factory: Callable,
                 holdout,
                 workdir: str,
                 config: Optional[AutopilotConfig] = None,
                 evaluator_factory: Optional[Callable] = None,
                 registry=None):
        self._daemon = daemon
        self._name = name
        self._workflow_factory = workflow_factory
        self._holdout = holdout
        self._workdir = os.path.abspath(workdir)
        os.makedirs(self._workdir, exist_ok=True)
        self.config = config or AutopilotConfig()
        self._evaluator_factory = evaluator_factory or (
            lambda model: default_evaluator(model, self.config.problem_type))
        self._registry = (registry if registry is not None
                          else obs.default_registry())
        #: structured, replay-deterministic decision log: tuples of
        #: (step, action, *sorted attrs) — NO wall clock, NO uids, NO
        #: fingerprints (those vary per process; they ride span events and
        #: the history instead). Byte-identical across same-seed replays.
        self.events: list[tuple] = []
        #: promotion history (most recent last): dicts carrying the real
        #: fingerprints/dirs for operators + rollback
        self.history: list[dict] = []
        self.promotions = 0
        self.rollbacks = 0
        self._step_idx = 0
        self._streak = 0
        self._candidates = 0
        self._lock = make_lock("Autopilot._lock")

    # --- bookkeeping ------------------------------------------------------------------
    def _event(self, action: str, **attrs) -> None:
        ev = (self._step_idx, action) + tuple(sorted(attrs.items()))
        with self._lock:
            self.events.append(ev)
        obs.add_event(f"autopilot:{action}", step=self._step_idx, **attrs)

    def _count_retrain(self, outcome: str) -> None:
        self._registry.counter(
            "autopilot_retrains_total",
            help="autopilot retrain attempts by outcome",
            labels={"outcome": outcome}).inc()

    def _entry(self):
        return self._daemon._resolve(self._name)

    def _holdout_kwargs(self) -> dict:
        hold = self._holdout() if callable(self._holdout) else self._holdout
        from ..types import Table

        return {"table": hold} if isinstance(hold, Table) else {"reader": hold}

    def _metric_of(self, metrics) -> float:
        m = getattr(metrics, self.config.metric, None)
        if m is None and hasattr(metrics, "to_json"):
            m = metrics.to_json().get(self.config.metric)
        if m is None:
            raise KeyError(f"metric {self.config.metric!r} not in "
                           f"{type(metrics).__name__}")
        return float(m)

    # --- observe ----------------------------------------------------------------------
    def drift_state(self) -> dict:
        """Current drift picture of the served model: active alert keys +
        the gauges the loop watches. An UNRESOLVABLE alias (the entry was
        evicted by outside admissions) reports `resolvable: False` instead
        of raising — the loop must degrade to observing, never crash its
        own poll thread."""
        try:
            entry = self._entry()
        except KeyError:
            return {"monitored": False, "resolvable": False, "active": [],
                    "features": []}
        mon = entry.score_fn.monitor
        if mon is None:
            return {"monitored": False, "resolvable": True, "active": [],
                    "features": []}
        rep = mon.report()  # runs a threshold check — never stale
        return {"monitored": True, "resolvable": True,
                "active": rep["active_alerts"], "features": rep["features"]}

    def quality_state(self) -> dict:
        """Current label-feedback quality picture of the served model: the
        quality plane's active alert metrics (the second trigger tier).
        Same degrade contract as `drift_state` — an unresolvable alias or
        an entry admitted without a quality plane observes as unmonitored,
        never raises into the poll thread."""
        try:
            entry = self._entry()
        except KeyError:
            return {"monitored": False, "resolvable": False, "active": []}
        plane = getattr(entry, "quality", None)
        if plane is None or not self.config.quality_trigger:
            return {"monitored": False, "resolvable": True, "active": []}
        plane.monitor.check()  # refresh the edge state — never stale
        return {"monitored": True, "resolvable": True,
                "active": list(plane.monitor.active)}

    # --- the loop body ----------------------------------------------------------------
    def _poll(self) -> dict:
        """One observe + debounce decision — THE shared body of step() and
        run() (one copy of the logic; the returned "act" flag says whether
        the breach sustained long enough to retrain). Streak mutations run
        under the lock: run()'s poll thread and its retrain worker (which
        resets the streak in `_retrain_and_gate`) must not lose updates to
        each other."""
        self._step_idx += 1
        state = self.drift_state()
        quality = self.quality_state()
        drift_active = bool(state["active"])
        quality_active = bool(quality["active"])
        drifted = drift_active or quality_active
        #: which tier tripped — the decision log distinguishes a covariate
        #: breach from a label-feedback quality breach (or both at once)
        trigger = ("drift+quality" if drift_active and quality_active
                   else "quality" if quality_active
                   else "drift" if drift_active else "none")
        with self._lock:
            self._streak = self._streak + 1 if drifted else 0
            streak = self._streak
        decision = {"step": self._step_idx, "drifted": drifted,
                    "streak": streak, "action": "observe",
                    "active": list(state["active"]),
                    "quality_active": list(quality["active"]),
                    "trigger": trigger, "act": False}
        if not state.get("resolvable", True):
            # evicted out from under us (outside admissions past
            # max_models): observable, never actionable
            decision["action"] = "alias_unresolved"
            self._event("alias_unresolved")
            return decision
        self._event("observe", drifted=drifted, streak=streak,
                    active=",".join(sorted(state["active"])),
                    quality=",".join(sorted(quality["active"])),
                    trigger=trigger)
        if not drifted or streak < self.config.breach_checks:
            return decision
        if self.config.max_promotions is not None \
                and self.promotions >= self.config.max_promotions:
            decision["action"] = "promotion_cap"
            return decision
        decision["act"] = True
        return decision

    def step(self) -> dict:
        """One observe->decide->maybe-act cycle, synchronous (the unit the
        seeded replay pins). Serving traffic flows on the daemon's threads
        throughout — a retrain inside step() never blocks a request."""
        decision = self._poll()
        if decision.pop("act"):
            decision.update(self._retrain_and_gate())
        return decision

    def _retrain_and_gate(self, parent=None) -> dict:
        """`parent` is the span captured on the SPAWNING thread (run()'s poll
        loop) — span lookup is per-thread, so without it a retrain on the
        worker thread would parent to the tracer root and the stitched fleet
        trace would show the retrain floating free of the drift decision
        that triggered it."""
        cfg = self.config
        try:
            try:
                entry = self._entry()
            except KeyError as e:
                # the alias went unresolvable between the poll and the act
                # (outside eviction): contained like any other step failure
                # — the finally still re-arms the debounce, run()'s worker
                # thread survives
                self._count_retrain("crashed")
                self._event("retrain_failed", error=type(e).__name__)
                return {"action": "retrain_failed",
                        "error": type(e).__name__}
            champion = entry.model
            old_fp = entry.fingerprint
            # -- retrain (chaos site: a crash here must leave the champion
            # serving and the loop re-armed, nothing else)
            try:
                with obs.span("autopilot:retrain", parent=parent):
                    chaos.maybe_site("autopilot:retrain")
                    wf = self._workflow_factory()
                    wf.with_warm_start(champion)
                    # the champion carries its `op autotune` winner: retrain
                    # under the same mesh/knobs/env so the challenger is
                    # measured like-for-like against a tuned incumbent
                    from ..tune import (apply_tuned_config, env_overrides,
                                        tuned_env)

                    env: dict = {}
                    tuned = (getattr(champion, "tuned_config", None)
                             if cfg.use_tuned_config else None)
                    if tuned and apply_tuned_config(wf, tuned):
                        env = tuned_env(tuned)
                        obs.add_event("tuned_config",
                                      label=str(tuned.get("label", "")))
                    with env_overrides(**env):
                        candidate = wf.train()
            except Exception as e:  # noqa: BLE001 — contained by contract
                self._count_retrain("crashed")
                self._event("retrain_failed", error=type(e).__name__)
                _logger.warning("autopilot: retrain failed (%s: %s); "
                                "champion keeps serving", type(e).__name__, e)
                return {"action": "retrain_failed",
                        "error": type(e).__name__}

            # -- gate 1: static lint (a plan the analyzer rejects must not
            # reach the serving path, however well it scored)
            from ..analyze import analyze_model

            report = (candidate.analysis_report
                      if candidate.analysis_report is not None
                      else analyze_model(candidate))
            if report.has_errors:
                self._count_retrain("lint_rejected")
                codes = sorted({d.code for d in report.errors})
                self._event("lint_rejected", codes=",".join(codes))
                return {"action": "lint_rejected", "codes": codes}

            # -- gate 2: champion vs challenger on the SHARED holdout
            try:
                hk = self._holdout_kwargs()
                champ_metric = self._metric_of(champion.evaluate(
                    self._evaluator_factory(champion), **hk))
                chall_metric = self._metric_of(candidate.evaluate(
                    self._evaluator_factory(candidate), **hk))
            except Exception as e:  # noqa: BLE001 — a broken gate must not swap
                self._count_retrain("eval_failed")
                self._event("eval_failed", error=type(e).__name__)
                return {"action": "eval_failed", "error": type(e).__name__}
            if cfg.larger_is_better:
                promote = chall_metric >= champ_metric + cfg.promotion_margin
            else:
                promote = chall_metric <= champ_metric - cfg.promotion_margin
            gate = {"champion": round(champ_metric, 6),
                    "challenger": round(chall_metric, 6),
                    "metric": cfg.metric, "margin": cfg.promotion_margin}
            self._event("gate", champion=round(champ_metric, 6),
                        challenger=round(chall_metric, 6),
                        metric=cfg.metric, promote=promote)
            if not promote:
                self._count_retrain("rejected")
                return {"action": "rejected", "gate": gate}

            # -- save the candidate bundle (atomic publish; the chaos site
            # models a torn save — anything short of a complete manifest
            # must fail the swap, not serve garbage)
            self._candidates += 1
            cand_dir = os.path.join(self._workdir,
                                    f"candidate-{self._candidates:04d}")
            try:
                with obs.span("autopilot:save", parent=parent):
                    os.makedirs(cand_dir, exist_ok=True)
                    chaos.maybe_site("autopilot:save")
                    candidate.save(cand_dir, overwrite=True,
                                   aot=cfg.export_aot)
            except Exception as e:  # noqa: BLE001
                if cfg.export_aot:
                    # a failed AOT export is a containment event, not an
                    # autopilot error: the champion keeps serving and the
                    # degrade is visible on aot_fallback_total{reason=error}
                    from .aot import note_fallback

                    note_fallback("error",
                                  f"candidate save/export: {type(e).__name__}")
                self._count_retrain("save_failed")
                self._event("save_failed", error=type(e).__name__)
                return {"action": "save_failed", "error": type(e).__name__,
                        "gate": gate}

            # -- hot swap: admit + alias repoint. Admission failures (torn
            # bundle on disk, a lost device) raise BEFORE the alias moves.
            try:
                with obs.span("autopilot:swap", parent=parent):
                    new_entry = self._daemon.swap(
                        self._name, cand_dir, retire_old=cfg.retire_old)
            except Exception as e:  # noqa: BLE001
                self._count_retrain("swap_failed")
                self._event("swap_failed", error=type(e).__name__)
                return {"action": "swap_failed", "error": type(e).__name__,
                        "gate": gate}

            # -- promoted: resolve the drift episode on the DEMOTED model's
            # monitor (the pager-visible falling edge — nothing will ever
            # feed that monitor again) and record the rollback token
            old_mon = entry.score_fn.monitor
            if old_mon is not None:
                old_mon.resolve_active(reason="promoted")
            # same falling-edge discipline for the quality tier: the demoted
            # entry's joiner will never see another label, so its breach
            # episode must be resolved here or it latches forever
            old_q = getattr(entry, "quality", None)
            if old_q is not None:
                old_q.monitor.resolve_active(reason="promoted")
            self._count_retrain("promoted")
            self.promotions += 1
            self._event("promoted", challenger=round(chall_metric, 6),
                        champion=round(champ_metric, 6))
            with self._lock:  # vs rollback()'s concurrent read-then-pop
                self.history.append({
                    "step": self._step_idx, "dir": cand_dir,
                    "fingerprint": new_entry.fingerprint,
                    "previous_fingerprint": old_fp, "gate": gate})
            self._sweep_candidates()
            return {"action": "promoted", "gate": gate,
                    "fingerprint": new_entry.fingerprint, "dir": cand_dir}
        finally:
            # acted (or failed): re-arm the debounce — the breach must
            # SUSTAIN again before the next attempt (under the lock, so
            # run()'s concurrent poll thread cannot resurrect a stale streak
            # and hot-loop a failing retrain)
            with self._lock:
                self._streak = 0

    def rollback(self) -> Optional[str]:
        """Demote the current champion: repoint the alias at the PREVIOUS
        champion (which `swap(retire_old=False)` kept resident and warm).
        Returns the fingerprint now serving, or None when there is no
        promotion to roll back. O(alias write) — no load, no compile. A
        failed repoint (the previous entry was retired/evicted) raises and
        LEAVES the history entry in place — the rollback token survives for
        a retry or operator inspection."""
        with self._lock:
            if not self.history:
                return None
            last = self.history[-1]
        prev = last["previous_fingerprint"]
        self._daemon.repoint(self._name, prev)  # may raise: history intact
        with self._lock:
            if self.history and self.history[-1] is last:
                self.history.pop()
        self.rollbacks += 1
        self._registry.counter(
            "autopilot_rollbacks_total",
            help="alias repoints back to a previous champion").inc()
        self._event("rollback")
        return prev

    def _sweep_candidates(self) -> None:
        """Bound workdir growth: keep the newest `keep_candidates` bundles
        plus anything the daemon still serves or the history references."""
        import shutil

        with self._lock:
            tail = self.history[-self.config.keep_candidates:]
        keep = {h["dir"] for h in tail}
        live = {e["path"] for e in
                (self._daemon.models() if hasattr(self._daemon, "models")
                 else [])}
        dirs = sorted(d for d in os.listdir(self._workdir)
                      if d.startswith("candidate-"))
        for d in dirs[:-self.config.keep_candidates or None]:
            full = os.path.join(self._workdir, d)
            if full in keep or full in live:
                continue
            shutil.rmtree(full, ignore_errors=True)

    # --- the wall-clock loop (CLI) ----------------------------------------------------
    def run(self, poll_s: float = 5.0, max_steps: Optional[int] = None,
            stop: Optional[threading.Event] = None,
            log: Optional[Callable] = None) -> dict:
        """Poll on an interval until `stop` (or `max_steps`) — the SAME
        `_poll` body step() uses, with the retrain/gate/swap pipeline on a
        worker thread so drift polling (and the daemon's serving threads)
        keep their cadence during a long train; at most one retrain is in
        flight at a time, and `_retrain_and_gate` resets the streak under
        the lock, so a failing retrain re-arms the full debounce instead of
        hot-looping."""
        stop = stop or threading.Event()
        steps = 0
        acted: list = []  # worker decisions, surfaced on the report

        def _act(parent=None):
            decision = self._retrain_and_gate(parent=parent)
            acted.append(decision)
            if log:
                log(f"autopilot: {decision['action']}")

        worker: Optional[threading.Thread] = None
        while not stop.is_set() and (max_steps is None or steps < max_steps):
            steps += 1
            decision = self._poll()
            if log:
                log(f"autopilot: step {decision['step']} "
                    f"drifted={decision['drifted']} "
                    f"streak={decision['streak']}")
            if decision.pop("act") and (worker is None
                                        or not worker.is_alive()):
                # capture the poll thread's span HERE: the retrain spans on
                # the worker thread nest under the decision that spawned them
                worker = threading.Thread(
                    target=_act, args=(obs.current_span(),), daemon=True,
                    name="autopilot-retrain")
                worker.start()
            stop.wait(poll_s)
        if worker is not None:
            worker.join()
        report = self.report()
        report["acted"] = acted
        return report

    def report(self) -> dict:
        with self._lock:  # one consistent view vs the retrain worker thread
            return {
                "alias": self._name,
                "steps": self._step_idx,
                "promotions": self.promotions,
                "rollbacks": self.rollbacks,
                "history": list(self.history),
                "events": [list(e) for e in self.events],
            }


# --- seeded synthetic drifting scenario -------------------------------------------------
class DriftScenario:
    """Seeded end-to-end drill for the loop: a drifting event stream, the
    retrain data it implies, and the shared holdout — everything the
    autopilot needs, all deterministic in `seed`.

    The world: entities emit events carrying a numeric feature `a` and a
    categorical `cat`; the outcome (label) follows the CURRENT regime's
    decision rule over `a`. `shift_mu()` moves the regime BOTH ways a real
    drift does: covariate shift (`a` recentres at `shift`, so the monitor's
    JS gauge fires against the training baseline) and concept shift (the
    label rule's direction inverts around the new centre, so the pre-drift
    champion's RANKING — hence AuPR — on fresh data collapses; a monotone
    mean shift alone would leave a ranking metric untouched).
    `restore_mu()` drifts it back (the falling-edge/recovery drill).

    Retrain data flows through an AggregateReader (the reference's event-
    reader path): per-entity predictor events aggregate strictly BEFORE the
    cutoff, the outcome event lands AT/AFTER it — the same leakage-safe
    rollup a production event store would feed the loop.
    """

    CUTOFF_MS = 1_000_000

    def __init__(self, seed: int = 0, batch: int = 64, n_train: int = 256,
                 n_holdout: int = 192, shift: float = 4.0,
                 label_noise: float = 0.25):
        self.seed = int(seed)
        self.batch = int(batch)
        self.n_train = int(n_train)
        self.n_holdout = int(n_holdout)
        self.shift = float(shift)
        self.label_noise = float(label_noise)
        self.mu = 0.0
        self.direction = 1.0
        self._entity = 0
        self._serving_rng = np.random.default_rng(self.seed)
        self._train_rng = np.random.default_rng(self.seed + 1)
        self._holdout_rng = np.random.default_rng(self.seed + 2)

    # -- regime control
    def shift_mu(self) -> None:
        self.mu = self.shift
        self.direction = -1.0

    def restore_mu(self) -> None:
        self.mu = 0.0
        self.direction = 1.0

    def flip_concept(self) -> None:
        """CONCEPT-ONLY drift: the label rule inverts while `mu` (and so
        every feature marginal) stays exactly where training left it. The
        covariate monitor sees nothing — by construction — which is the
        blind spot the quality trigger tier exists to cover: only delayed
        label feedback can reveal this regime change."""
        self.direction = -self.direction

    # -- the three data surfaces
    def serving_batch(self, n: Optional[int] = None) -> list:
        """One batch of UNLABELED serving records at the current regime."""
        n = self.batch if n is None else int(n)
        rng = self._serving_rng
        return [{"a": float(rng.normal(self.mu, 1.0)),
                 "cat": "ab"[int(rng.integers(0, 2))]} for _ in range(n)]

    def serving_batch_labeled(self, n: Optional[int] = None,
                              ) -> tuple[list, list]:
        """One serving batch PLUS its ground-truth labels at the current
        regime — the delayed-feedback drill: score the records now, POST
        the labels against the minted prediction ids later. Same rng as
        `serving_batch`, so mixing the two keeps the stream seeded."""
        n = self.batch if n is None else int(n)
        rng = self._serving_rng
        records, labels = [], []
        for _ in range(n):
            a = float(rng.normal(self.mu, 1.0))
            records.append({"a": a, "cat": "ab"[int(rng.integers(0, 2))]})
            labels.append(self._label(a, rng))
        return records, labels

    def _label(self, a: float, rng) -> float:
        return float(self.direction * (a - self.mu)
                     + rng.normal(0.0, self.label_noise) > 0.0)

    def _events(self, n: int, rng) -> list:
        """Per-entity event pairs: one predictor event before the cutoff,
        one outcome event after it (what an event store would hold). Field
        names match the feature names: a LOADED model's features lose their
        extract lambdas (they don't serialize) and fall back to name-keyed
        extraction, and the loop evaluates loaded champions too."""
        out = []
        for _ in range(n):
            self._entity += 1
            key = f"e{self._entity:06d}"
            a = float(rng.normal(self.mu, 1.0))
            out.append({"k": key, "t": int(rng.integers(0, self.CUTOFF_MS)),
                        "a": a, "cat": "ab"[int(rng.integers(0, 2))],
                        "label": None})
            out.append({"k": key, "t": self.CUTOFF_MS + 1, "a": None,
                        "cat": None, "label": self._label(a, rng)})
        return out

    def _aggregate_reader(self, events: list):
        from ..readers import InMemoryReader
        from ..readers.aggregates import AggregateReader
        from ..aggregators import CutOffTime

        return AggregateReader(
            InMemoryReader(events, key_fn=lambda r: r["k"]),
            key_fn=lambda r: r["k"],
            timestamp_fn=lambda r: r["t"],
            cutoff=CutOffTime.unix_epoch(self.CUTOFF_MS))

    def make_workflow(self):
        """Fresh single-LR workflow over FRESH current-regime events (the
        autopilot's `workflow_factory`). A new feature graph every call —
        features are single-use wiring."""
        from ..graph import FeatureBuilder
        from ..stages.feature import transmogrify
        from ..stages.model import LogisticRegression
        from ..workflow import Workflow

        a = FeatureBuilder("a", "Real").extract(
            lambda r: r.get("a")).as_predictor()
        cat = FeatureBuilder("cat", "PickList").extract(
            lambda r: r.get("cat")).as_predictor()
        label = FeatureBuilder("label", "Real").extract(
            lambda r: r.get("label")).as_response()
        pred = LogisticRegression(l2=0.01)(label, transmogrify([a, cat]))
        wf = Workflow().set_result_features(pred)
        wf.set_reader(self._aggregate_reader(
            self._events(self.n_train, self._train_rng)))
        return wf

    def holdout_reader(self):
        """Fresh labeled holdout at the CURRENT regime, through the same
        aggregate-reader path (the autopilot's shared gate set)."""
        return self._aggregate_reader(
            self._events(self.n_holdout, self._holdout_rng))

    def train_champion(self):
        """The initial (pre-drift) champion, trained at mu=0."""
        return self.make_workflow().train()
