"""Adaptive micro-batcher: coalesce concurrent serving requests into device batches.

BENCH_r05's sore spot is the shape of per-call serving, not the kernels:
single-row device scoring pays the full dispatch round trip (~101 ms
tunneled) per request, so N concurrent single-row callers pay it N times —
serialized. The fix is the tf.data-service discipline (PAPERS.md arXiv
2210.14826) applied to the scoring side: decouple request arrival from device
dispatch with a queue, and coalesce whatever is waiting into ONE pow2-padded
batch per dispatch. N concurrent single-row requests then cost ~one dispatch,
and the responses demultiplex back to their callers bit-identically to
per-row scoring.

Mechanics — everything downstream of the queue is the EXISTING serving stack,
not a parallel one:

* requests land in a `ClosableQueue` (readers/pipeline.py) as
  (records, Future) pairs;
* a coalescing generator drains it into windows: the first request opens a
  window, further requests join until the **max-wait deadline** fires or the
  window reaches `max_batch` rows. The window is ADAPTIVE: an EMA of recent
  window sizes tracks client concurrency, and once the current window has
  caught up to it with an idle queue, it dispatches EARLY — steady closed-loop
  traffic pays arrival spread, not the full deadline, and a lone steady
  client (EMA ~1) pays ~zero added latency. The deadline stays the hard
  bound for ramp-up and thinning traffic;
* coalesced windows flow through `ScoreFunction.stream()` — the shared input
  executor's `Prefetcher(place=)` path — so the host-side table build (and
  under a mesh the per-shard device placement) of window k+1 overlaps the
  fused dispatch of window k, and `pad_to` pow2 bucketing bounds the compiled
  program count;
* routing stays the ScoreFunction's: a lone window below the measured
  crossover (`auto_threshold()`) degrades to the in-process CPU plan instead
  of stalling on a device round trip; big coalesced windows take the device.

Every decision lands on the metrics registry: `serve_queue_wait_seconds{model}`
(enqueue -> dispatch-start per request), `serve_coalesced_batch_size{model}`
(rows per dispatch, pow2 buckets), plus a `serve:coalesce` span event — the
`serve_latency_seconds{backend,model}` SLO histograms come from the
ScoreFunction underneath.

Failure containment: arm the handle with a `FaultPolicy(quarantine_dir=...)`
(the daemon does by default) and poison rows are row-bisect quarantined by
the PR-6 machinery — the affected positions come back as None, the stream
never dies. Without quarantine, an unexpected stream error fails every
in-flight Future and the worker restarts a fresh stream; requests a
torn-down stream's producer had already taken are handed back to the
replacement via `put_front`, so nothing is silently dropped.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from queue import Empty, Full
from typing import Optional, Sequence

from .. import obs
from ..readers.pipeline import ClosableQueue
from ..readers.streaming import StreamClosed

#: pow2 exposition buckets for the coalesced-batch-size histogram (1..4096)
_SIZE_BUCKETS = tuple(float(1 << i) for i in range(13))

#: short poll quantum for the coalescing waits: bounds both deadline
#: overshoot and how long a torn-down stream's producer can linger
_POLL_S = 0.05


class Overloaded(RuntimeError):
    """The batcher's bounded request queue is full: this submission was SHED
    (never enqueued, never silently dropped). The daemon maps it to HTTP 429
    — an overloaded replica answers fast with "try elsewhere/later" instead
    of growing an unbounded queue whose every occupant times out anyway.
    Counted on `serve_shed_total{model}`."""


class _Pending:
    """One queued request: its records, the caller's Future, the enqueue
    timestamp feeding `serve_queue_wait_seconds`, and the submitting
    thread's span (span lookup is per-thread — the coalescer's producer
    thread needs the captured parent to nest its dispatch span under the
    request that opened the window)."""

    __slots__ = ("records", "future", "t_enqueue", "span")

    def __init__(self, records, future, t_enqueue, span=None):
        self.records = records
        self.future = future
        self.t_enqueue = t_enqueue
        self.span = span


class _CoalescedSource:
    """The stream() source object: iterating it runs the batcher's
    coalescing generator; `on_pipeline_close` (the Prefetcher teardown hook)
    flags the generation torn so an idle-blocked producer exits within one
    poll quantum instead of timing out the close join — and without taking
    any request the REPLACEMENT stream should serve."""

    def __init__(self, batcher: "MicroBatcher", gen: int):
        self._batcher = batcher
        self._gen = gen

    def __iter__(self):
        return self._batcher._coalesced(self._gen)

    def on_pipeline_close(self) -> None:
        self._batcher._torn.set()


class MicroBatcher:
    """Request-coalescing front end over one ScoreFunction.

    `submit(records)` returns a Future resolving to the same list
    `score_fn.batch(records)` would return (None entries mark quarantined
    rows when the handle's policy arms quarantine). `score()` is the
    blocking convenience. `close()` stops intake, drains every queued
    request through the pipeline, and joins the worker — shutdown
    mid-flight loses nothing.
    """

    def __init__(self, score_fn, *, max_batch: int = 256,
                 max_wait_ms: float = 2.0, prefetch: int = 2,
                 queue_depth: int = 4096,
                 model_label: Optional[str] = None, registry=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._fn = score_fn
        self._max_batch = int(max_batch)
        self._max_wait_s = float(max_wait_ms) / 1e3
        self._prefetch = int(prefetch)
        self.model_label = str(
            model_label or getattr(score_fn, "_model_label", "model"))
        self._requests = ClosableQueue(maxsize=queue_depth)
        #: FIFO of (generation, demux group), appended by the coalescer
        #: BEFORE it yields a window and popped by the worker as results
        #: arrive — stream() is strictly ordered, so the head always matches
        #: the next result; the generation tag lets the worker discard any
        #: entry a torn-down producer managed to append post-restart instead
        #: of demuxing another window's results to its callers
        self._inflight: deque = deque()
        #: stream generation: bumped on restart so a torn-down stream's
        #: producer (briefly still polling) steps aside instead of stealing
        self._gen = 0
        #: set by Prefetcher.close() via _CoalescedSource.on_pipeline_close:
        #: the signal an idle-blocked producer CAN see before the worker
        #: learns of the teardown (the gen bump necessarily comes later)
        self._torn = threading.Event()
        #: EMA of window request counts — the concurrency estimate behind
        #: early dispatch (None until the first window completes, so ramp-up
        #: always grants the full deadline)
        self._ema_group: Optional[float] = None
        #: totals (read by daemon stats / tests; GIL-atomic int bumps)
        self.dispatches = 0
        self.coalesced_requests = 0
        self.coalesced_rows = 0
        reg = registry if registry is not None else obs.default_registry()
        self._wait_hist = reg.histogram(
            "serve_queue_wait_seconds",
            help="request time from enqueue to coalesced dispatch start",
            labels={"model": self.model_label})
        self._size_hist = reg.histogram(
            "serve_coalesced_batch_size",
            help="rows per coalesced serving dispatch",
            labels={"model": self.model_label}, buckets=_SIZE_BUCKETS)
        self._shed_counter = reg.counter(
            "serve_shed_total",
            help="requests shed (HTTP 429) because the bounded request "
                 "queue was full",
            labels={"model": self.model_label})
        self._worker = threading.Thread(
            target=self._run, daemon=True,
            name=f"serve-batcher-{self.model_label}")
        self._worker.start()

    # --- client surface ---------------------------------------------------------------
    def submit(self, records: Sequence) -> Future:
        """Enqueue one request (a list of record dicts); raises StreamClosed
        after close(), ValueError past `max_batch` rows (an oversized
        request would dispatch at an unwarmed, unpadded shape — callers
        split bulk work, or use `score_fn.batch`/`.stream` directly, which
        is the right tool for it), and `Overloaded` when the bounded
        request queue (`queue_depth`) is full — the overload guard: beyond
        the bound the daemon sheds with 429 + `serve_shed_total{model}`
        rather than queueing without limit. The Future resolves to the
        per-record result list."""
        records = list(records)
        if len(records) > self._max_batch:
            raise ValueError(
                f"request of {len(records)} rows exceeds max_batch="
                f"{self._max_batch}; split it or use score_fn.batch()")
        f: Future = Future()
        if not records:
            f.set_result([])
            return f
        try:
            self._requests.put(
                _Pending(records, f, time.perf_counter(),
                         span=obs.current_span()),
                timeout=0.0)
        except Full:
            self._shed_counter.inc()
            obs.add_event("serve:shed", model=self.model_label,
                          pending=self._requests.qsize())
            raise Overloaded(
                f"model {self.model_label!r}: request queue full "
                f"({self._requests.qsize()} pending); shedding") from None
        return f

    def score(self, records: Sequence, timeout: Optional[float] = None):
        return self.submit(records).result(timeout)

    def close(self, timeout: float = 30.0) -> None:
        """Stop intake, drain queued requests, join the worker (idempotent)."""
        self._requests.close()
        self._worker.join(timeout)

    @property
    def closed(self) -> bool:
        return self._requests.closed

    def stats(self) -> dict:
        d = self.dispatches
        return {
            "dispatches": d,
            "coalesced_requests": self.coalesced_requests,
            "coalesced_rows": self.coalesced_rows,
            "mean_rows_per_dispatch": round(self.coalesced_rows / d, 3) if d
            else None,
            "pending": self._requests.qsize(),
        }

    # --- coalescer (runs on the Prefetcher's producer thread) -------------------------
    def _early_dispatch(self, group) -> bool:
        """True once the window has caught up to the measured concurrency
        (>= 80% of the window-size EMA) with nothing left queued: every
        client of a steady closed loop has checked in, so waiting out the
        deadline would only add latency."""
        ema = self._ema_group
        return (ema is not None and len(group) >= 0.8 * ema
                and self._requests.empty())

    def _stale(self, gen: int) -> bool:
        """This generation's stream is (being) torn down: either the worker
        already bumped the generation, or Prefetcher.close() flagged the
        teardown via the source hook (which happens BEFORE the worker can
        bump — an idle producer must see it to exit within a poll quantum
        instead of timing out the close join)."""
        return self._gen != gen or self._torn.is_set()

    def _coalesced(self, gen: int):
        """Generator of coalesced record lists — the stream() source. Every
        blocking wait is a short poll so a stale generation exits promptly."""
        while True:
            try:
                first = self._requests.get(timeout=_POLL_S)
            except Empty:
                if self._stale(gen):
                    return
                continue
            except StreamClosed:
                return
            group = [first]
            rows = len(first.records)
            deadline = time.perf_counter() + self._max_wait_s
            while rows < self._max_batch and not self._early_dispatch(group):
                remaining = deadline - time.perf_counter()
                try:
                    if remaining > 0:
                        nxt = self._requests.get(
                            timeout=min(remaining, _POLL_S))
                    else:
                        nxt = self._requests.get_nowait()
                except StreamClosed:
                    break  # drain: dispatch what the window holds
                except Empty:
                    if remaining <= 0 or self._stale(gen):
                        break
                    continue
                if rows + len(nxt.records) > self._max_batch:
                    # would overshoot the ceiling (= the largest warmed
                    # bucket): hand it back head-of-queue for the next
                    # window rather than dispatch an unwarmed shape
                    self._requests.put_front(nxt)
                    break
                group.append(nxt)
                rows += len(nxt.records)
            if self._stale(gen):
                # torn down mid-window: hand admitted work to the live
                # producer, head-of-queue, in arrival order
                for p in reversed(group):
                    self._requests.put_front(p)
                return
            ema = self._ema_group
            self._ema_group = (float(len(group)) if ema is None
                               else 0.5 * ema + 0.5 * len(group))
            now = time.perf_counter()
            for p in group:
                self._wait_hist.observe(now - p.t_enqueue)
            self._size_hist.observe(rows)
            self.dispatches += 1
            self.coalesced_requests += len(group)
            self.coalesced_rows += rows
            # the dispatch span nests under the span of the request that
            # OPENED the window (captured at submit time): a stitched fleet
            # trace shows client -> daemon handler -> coalesced dispatch as
            # one chain even though this runs on the producer thread
            with obs.span("serve:dispatch", parent=group[0].span):
                obs.add_event(
                    "serve:coalesce", requests=len(group), rows=int(rows),
                    waited_ms=round((now - group[0].t_enqueue) * 1e3, 3))
            self._inflight.append((gen, group))
            yield [r for p in group for r in p.records]

    # --- worker -----------------------------------------------------------------------
    def _demux(self, group, rows, error) -> None:
        if error is not None:
            for p in group:
                p.future.set_exception(error)
            return
        i = 0
        for p in group:
            n = len(p.records)
            p.future.set_result(rows[i:i + n])
            i += n

    def _pop_inflight(self, gen: int, error):
        """Head inflight group of the CURRENT generation. Entries a
        torn-down producer appended after the restart drain carry the old
        generation tag — they are failed here, never aligned against the new
        stream's results (the demux-misalignment guard)."""
        while self._inflight and self._inflight[0][0] != gen:
            _, stale_group = self._inflight.popleft()
            self._demux(stale_group, None,
                        error or RuntimeError("serving stream restarted"))
        _, group = self._inflight.popleft()
        return group

    def _run(self) -> None:
        last_error = None
        while True:
            gen = self._gen
            self._torn.clear()
            try:
                # the SOURCE OBJECT (not a bare generator) rides into the
                # Prefetcher so close() can reach on_pipeline_close
                for rows in self._fn.stream(_CoalescedSource(self, gen),
                                            prefetch=self._prefetch):
                    self._demux(self._pop_inflight(gen, last_error), rows,
                                None)
            except BaseException as e:  # noqa: BLE001 — contained per policy
                # unexpected stream death (quarantine-armed handles absorb
                # data poison before it gets here): fail every in-flight
                # request explicitly — a hung Future is worse than an error —
                # and restart a fresh stream for the survivors in the queue.
                # The torn stream's producer saw the teardown via the
                # on_pipeline_close hook, so it exited without stealing
                # queued requests; anything it had mid-window came back via
                # put_front.
                self._gen += 1
                last_error = e
                obs.add_event("serve:batcher_restart",
                              error=f"{type(e).__name__}: {e}"[:200])
                obs.default_registry().counter(
                    "serve_batcher_restarts_total",
                    help="micro-batcher stream restarts after an unexpected "
                         "scoring error",
                    labels={"model": self.model_label}).inc()
                while self._inflight:
                    _, group = self._inflight.popleft()
                    self._demux(group, None, e)
                if self._requests.closed and self._requests.empty():
                    return
                continue
            return  # clean drain: queue closed and empty
