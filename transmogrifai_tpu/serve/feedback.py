"""Prediction audit log + delayed label-feedback join — the serving side of
the model-quality plane.

Scoring is fast and labels are slow: the click, the chargeback, the churn
event arrive minutes-to-days after the score that should have predicted
them. Closing the quality loop therefore needs three pieces that this
module provides, all off the scoring hot path:

  AuditSink     every score (sampled) becomes one bounded JSONL record —
                prediction id, model fingerprint, score — queued to a drain
                thread and published in ATOMIC segments (temp +
                `os.replace`, the QuarantineWriter/workflow.save
                discipline). A full queue DROPS and counts
                (`audit_dropped_total`): audit must never apply
                backpressure to scoring. Deterministic mode strips
                wall-clock fields and derives stable ids, so chaos-replayed
                runs produce byte-identical segments.
  LabelJoiner   a TTL-bounded pending map from prediction id -> score.
                `POST /v1/feedback` / `op feedback` resolve ids to (score,
                label) pairs; duplicates are idempotent (a bounded done-set
                remembers joined ids), expiry is LOGICAL (join attempts,
                not wall-clock — deterministic under replay). The state is
                a checkpointable monoid: `to_json`/`from_json` round-trip
                and `merge` folds two joiners (pending union, done union,
                counters add).
  QualityPlane  the per-model composition the daemon arms at `admit()`:
                id allocation -> audit emit -> pending note on the score
                path; join -> `QualityMonitor.observe_pair` on the feedback
                path. One object per ModelEntry, one call site each way.

Prediction ids are `<trace16>-<seq08>`: 16 hex of trace identity (the PR-16
trace context when one is live, a process-random trace otherwise; a stable
crc32-derived stamp in deterministic mode) plus a monotone per-sink
sequence — collision-safe across the fleet without coordination, stable
under replay when determinism is armed.
"""
from __future__ import annotations

import json
import os
import queue
import threading
import zlib
from collections.abc import Mapping as _MappingABC
from typing import Any, Mapping, Optional, Sequence

from .. import obs
from ..obs.quality import QualityMonitor, QualityThresholds
from ..resilience.lockcheck import make_lock

__all__ = [
    "AuditSink", "LabelJoiner", "QualityPlane", "extract_score",
]

#: serialized audit records past this many chars are truncated (repr-style,
#: like QuarantineWriter): one hostile mega-row must not bloat a segment
_MAX_RECORD_CHARS = 2048


def _trace16(deterministic: bool, label: str) -> str:
    """The 16-hex trace half of a prediction id. Live trace context wins
    (ids then JOIN to the distributed trace in `op trace-merge`); otherwise
    a per-sink random stamp — or, deterministically, crc32 of the model
    label twice over, so replayed runs mint identical ids."""
    if deterministic:
        c = zlib.crc32(label.encode("utf-8"))
        return f"{c:08x}{c:08x}"
    ctx = obs.current_trace_context()
    if ctx is not None and len(ctx.trace_id) >= 16:
        return ctx.trace_id[:16]
    from ..obs.context import new_trace_id

    return new_trace_id()[:16]


class AuditSink:
    """Async bounded prediction-audit writer with atomic segment rotation.

    `emit()` is the only hot-path surface: allocate an id, enqueue a record,
    return. A background drain thread serializes and appends; every
    `segment_records` records (or on `flush`/`close`) the open segment is
    PUBLISHED — written complete to `audit-<label>-<nnnn>.jsonl.tmp.<pid>`
    and `os.replace`d into place, so a reader (or a crash) never sees a torn
    segment. Queue overflow drops the record and counts it; scoring never
    blocks on audit I/O.
    """

    def __init__(self, out_dir: str, model_label: str = "serve", *,
                 fingerprint: str = "", sample_every: int = 1,
                 max_queue: int = 4096, segment_records: int = 512,
                 deterministic: Optional[bool] = None, registry=None):
        from ..obs.metrics import default_registry

        self.out_dir = os.path.abspath(out_dir)
        os.makedirs(self.out_dir, exist_ok=True)
        self.model_label = str(model_label)
        self.fingerprint = str(fingerprint)
        self.sample_every = max(1, int(sample_every))
        self.segment_records = max(1, int(segment_records))
        if deterministic is None:
            deterministic = bool(os.environ.get("TT_AUDIT_DETERMINISTIC"))
        self.deterministic = bool(deterministic)
        self.registry = (registry if registry is not None
                         else default_registry())
        self._labels = {"model": self.model_label}
        self._records_c = self.registry.counter(
            "audit_records_total",
            help="prediction audit records accepted into the sink",
            labels=self._labels)
        self._dropped_c = self.registry.counter(
            "audit_dropped_total",
            help="audit records dropped on queue overflow (audit never "
                 "backpressures scoring)",
            labels=self._labels)
        self._segments_c = self.registry.counter(
            "audit_segments_total",
            help="audit segments atomically published",
            labels=self._labels)
        self._q: "queue.Queue[Optional[dict]]" = queue.Queue(
            maxsize=max(1, int(max_queue)))
        self._lock = make_lock("AuditSink._lock")
        self._trace = _trace16(self.deterministic, self.model_label)
        self._seq = 0
        self._seen = 0
        self._segment_idx = 0
        self._pending: list[str] = []  # serialized lines awaiting publish
        self._closed = False
        self._drain = threading.Thread(target=self._drain_loop, daemon=True,
                                       name=f"audit-{self.model_label}")
        self._drain.start()

    # --- hot path -----------------------------------------------------------------------
    def next_id(self) -> str:
        return self.next_ids(1)[0]

    def next_ids(self, n: int) -> list[str]:
        """Allocate a contiguous id block under one lock (batch scoring)."""
        with self._lock:
            start = self._seq + 1
            self._seq += n
        return [f"{self._trace}-{s:08d}" for s in range(start, start + n)]

    def emit(self, prediction_id: str, score: float,
             extra: Optional[Mapping] = None) -> bool:
        """Queue one audit record; True when accepted, False when sampled
        out or dropped on overflow. Never blocks, never raises."""
        try:
            with self._lock:
                self._seen += 1
                sampled = (self._seen - 1) % self.sample_every == 0
            if not sampled:
                return False
            rec: dict[str, Any] = {"id": prediction_id,
                                   "model": self.model_label,
                                   "fingerprint": self.fingerprint,
                                   "score": round(float(score), 9)}
            if extra:
                rec.update(extra)
            if not self.deterministic:
                import time

                rec["ts"] = round(time.time(), 6)
            try:
                self._q.put_nowait(rec)
            except queue.Full:
                self._dropped_c.inc()
                return False
            self._records_c.inc()
            return True
        except Exception:
            return False

    # --- drain thread -------------------------------------------------------------------
    def _drain_loop(self) -> None:
        while True:
            rec = self._q.get()
            if rec is None:
                self._publish()
                return
            try:
                line = json.dumps(rec, sort_keys=True, default=str)
                if len(line) > _MAX_RECORD_CHARS:
                    line = json.dumps(
                        {"id": rec.get("id"), "model": rec.get("model"),
                         "truncated": True}, sort_keys=True)
                self._pending.append(line)
                if len(self._pending) >= self.segment_records:
                    self._publish()
            except Exception:
                self._dropped_c.inc()

    def _publish(self) -> Optional[str]:
        """Atomically land the open segment: the temp file carries every
        line, `os.replace` is the single publish point (the workflow.save /
        QuarantineWriter discipline) — a crash mid-write leaves only a temp
        no reader follows."""
        if not self._pending:
            return None
        self._segment_idx += 1
        path = os.path.join(
            self.out_dir,
            f"audit-{self.model_label}-{self._segment_idx:04d}.jsonl")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            fh.write("\n".join(self._pending) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        self._pending = []
        self._segments_c.inc()
        obs.add_event("audit:segment", model=self.model_label,
                      path=os.path.basename(path))
        return path

    # --- lifecycle ----------------------------------------------------------------------
    def flush(self, timeout: float = 5.0) -> None:
        """Drain the queue and publish the open segment (tests, shutdown).
        Waits for the queue to empty, then publishes directly: `_pending`
        is only touched by the drain thread between `get()`s, so once the
        queue is empty (drain blocked in `get`) a publish from here cannot
        race it."""
        import time

        deadline = time.monotonic() + timeout
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.005)
        time.sleep(0.01)  # let the drain thread finish its in-flight record
        self._publish()

    def close(self, timeout: float = 5.0) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._q.put(None, timeout=timeout)
            self._drain.join(timeout=timeout)
        except Exception:
            pass

    def segments(self) -> list[str]:
        return sorted(
            os.path.join(self.out_dir, f) for f in os.listdir(self.out_dir)
            if f.startswith(f"audit-{self.model_label}-")
            and f.endswith(".jsonl"))


class LabelJoiner:
    """TTL-bounded prediction->label join with idempotent duplicates.

    `note(id, score)` registers a scored prediction; `feedback(id, label)`
    resolves it to a (score, label) pair exactly once. Three bounded
    structures, all deterministic:

      pending   id -> (score, age) ordered dict, FIFO-capped at
                `max_pending` (oldest evicted = expired) and aged by JOIN
                ATTEMPTS (`ttl_notes`: a pending id expires after that many
                subsequent notes) — logical time, so replays age identically
      done      ids already joined, bounded FIFO — a duplicate feedback is
                counted and IGNORED (idempotence), not re-folded
      counters  received/joined/duplicate/unmatched/expired — monoid-added
                by `merge`

    The whole state round-trips through `to_json`/`from_json` and `merge`
    folds two joiners — the checkpointable monoid the ISSUE's online-
    learning consumer needs (a restarted replica restores its window; two
    replicas' windows fold into one).
    """

    def __init__(self, *, ttl_notes: int = 65536, max_pending: int = 16384,
                 max_done: int = 65536, registry=None,
                 model_label: str = "serve"):
        from ..obs.metrics import default_registry

        self.ttl_notes = max(1, int(ttl_notes))
        self.max_pending = max(1, int(max_pending))
        self.max_done = max(1, int(max_done))
        self.model_label = str(model_label)
        self.registry = (registry if registry is not None
                         else default_registry())
        self._labels = {"model": self.model_label}
        self._lock = make_lock("LabelJoiner._lock")
        self._pending: dict[str, tuple[float, int]] = {}  # id -> (score, note_seq)
        self._done: dict[str, None] = {}  # insertion-ordered set
        self._note_seq = 0
        self.counters = {"received": 0, "joined": 0, "duplicate": 0,
                         "unmatched": 0, "expired": 0}
        self._c = {k: self.registry.counter(
            f"feedback_{k}_total",
            help=f"feedback events: {k}", labels=self._labels)
            for k in self.counters}
        self._pending_g = self.registry.gauge(
            "feedback_pending",
            help="predictions awaiting a label in the join window",
            labels=self._labels)

    # --- score path ---------------------------------------------------------------------
    def note(self, prediction_id: str, score: float) -> None:
        self.note_many([(prediction_id, score)])

    def note_many(self, pairs: Sequence[tuple]) -> None:
        """Register a batch of scored predictions under ONE lock acquisition
        (the scoring hot path calls this once per result batch). The final
        state is identical to noting one-by-one: pending is FIFO by note
        sequence, so a single eviction sweep at the batch's final sequence
        drops exactly the entries the incremental sweeps would have."""
        with self._lock:
            seq = self._note_seq
            pend = self._pending
            for pid, score in pairs:
                seq += 1
                pend[pid if type(pid) is str else str(pid)] = (
                    score if type(score) is float else float(score), seq)
            self._note_seq = seq
            expired = 0
            # logical TTL: drop pendings noted more than ttl_notes notes ago
            while self._pending:
                pid, (_, seq) = next(iter(self._pending.items()))
                if self._note_seq - seq < self.ttl_notes \
                        and len(self._pending) <= self.max_pending:
                    break
                del self._pending[pid]
                expired += 1
            if expired:
                self.counters["expired"] += expired
            depth = len(self._pending)
        if expired:
            self._c["expired"].inc(expired)
        self._pending_g.set(depth)

    # --- feedback path ------------------------------------------------------------------
    def feedback(self, prediction_id: str, label: float,
                 ) -> tuple[str, Optional[tuple[float, float]]]:
        """Resolve one delayed label. Returns (status, pair) where status is
        "joined" | "duplicate" | "unmatched" and pair is the (score, label)
        tuple on a join (None otherwise)."""
        counts, pairs = self.feedback_many([(prediction_id, label)])
        status = next(k for k, v in counts.items() if v)
        return status, (pairs[0] if pairs else None)

    def feedback_many(self, items: Sequence[tuple],
                      ) -> tuple[dict, list[tuple[float, float]]]:
        """Resolve a batch of delayed labels under ONE lock acquisition.
        Returns ({"joined", "duplicate", "unmatched"} counts, the joined
        (score, label) pairs in input order)."""
        joined = duplicate = unmatched = 0
        pairs: list[tuple[float, float]] = []
        with self._lock:
            # hot-loop locals: a joined id can never still be pending (join
            # pops it; merge() evicts pendings for done ids), so pop-with-
            # default resolves the common joined case in one dict op
            pend_pop = self._pending.pop
            done = self._done
            append = pairs.append
            for pid, label in items:
                if type(pid) is not str:
                    pid = str(pid)
                hit = pend_pop(pid, None)
                if hit is not None:
                    done[pid] = None
                    joined += 1
                    append((hit[0], float(label)))
                elif pid in done:
                    duplicate += 1
                else:
                    unmatched += 1
            # batch-final done trim pops the same FIFO heads the per-join
            # trims would have
            while len(done) > self.max_done:
                done.pop(next(iter(done)))
            self.counters["received"] += len(items)
            self.counters["joined"] += joined
            self.counters["duplicate"] += duplicate
            self.counters["unmatched"] += unmatched
            depth = len(self._pending)
        counts = {"joined": joined, "duplicate": duplicate,
                  "unmatched": unmatched}
        if items:
            self._c["received"].inc(len(items))
            for k, v in counts.items():
                if v:
                    self._c[k].inc(v)
            self._pending_g.set(depth)
        return counts, pairs

    # --- introspection ------------------------------------------------------------------
    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def stats(self) -> dict:
        with self._lock:
            return {"pending": len(self._pending), "done": len(self._done),
                    **dict(self.counters)}

    # --- checkpointable monoid ----------------------------------------------------------
    def to_json(self) -> dict:
        with self._lock:
            return {
                "version": 1,
                "ttl_notes": self.ttl_notes,
                "max_pending": self.max_pending,
                "max_done": self.max_done,
                "note_seq": self._note_seq,
                "pending": [[pid, s, seq]
                            for pid, (s, seq) in self._pending.items()],
                "done": list(self._done),
                "counters": dict(self.counters),
            }

    @classmethod
    def from_json(cls, doc: Mapping, registry=None,
                  model_label: Optional[str] = None) -> "LabelJoiner":
        j = cls(ttl_notes=int(doc.get("ttl_notes", 65536)),
                max_pending=int(doc.get("max_pending", 16384)),
                max_done=int(doc.get("max_done", 65536)),
                registry=registry,
                model_label=model_label or "serve")
        j._note_seq = int(doc.get("note_seq", 0))
        for pid, s, seq in doc.get("pending", []):
            j._pending[str(pid)] = (float(s), int(seq))
        for pid in doc.get("done", []):
            j._done[str(pid)] = None
        for k, v in (doc.get("counters") or {}).items():
            if k in j.counters:
                j.counters[k] = int(v)
        return j

    def merge(self, other: "LabelJoiner") -> None:
        """Monoid fold: pending union (an id both sides hold keeps OURS —
        same id means same score, the sequence differs only by local note
        order), done union, counters add. Ids joined on EITHER side leave
        pending, so a merged joiner never double-joins."""
        with other._lock:
            o_pending = dict(other._pending)
            o_done = list(other._done)
            o_counters = dict(other.counters)
            o_seq = other._note_seq
        with self._lock:
            for pid in o_done:
                self._done[pid] = None
                self._pending.pop(pid, None)
            for pid, (s, seq) in o_pending.items():
                if pid not in self._done and pid not in self._pending:
                    self._pending[pid] = (s, seq)
            while len(self._done) > self.max_done:
                self._done.pop(next(iter(self._done)))
            while len(self._pending) > self.max_pending:
                self._pending.pop(next(iter(self._pending)))
            for k, v in o_counters.items():
                if k in self.counters:
                    self.counters[k] += int(v)
            self._note_seq = max(self._note_seq, o_seq)


# --- score extraction ---------------------------------------------------------------------
def extract_score(row: Mapping) -> Optional[float]:
    """A scalar [0, 1] score from one result row (dict of result-feature
    name -> value). Prediction values are dicts for classifiers
    ({"prediction": .., "probability": [..]} shapes) and floats for
    regressors; the quality plane wants P(positive). Returns None for rows
    it cannot read — the caller skips those (audit must never guess)."""
    for v in row.values():
        # `type(v) is dict` first: typing.Mapping isinstance is ~10x the
        # cost and this runs once per scored row
        if type(v) is dict or isinstance(v, _MappingABC):
            prob = v.get("probability")
            if isinstance(prob, (list, tuple)) and prob:
                try:
                    return float(prob[-1])
                except (TypeError, ValueError):
                    pass
            for key in ("prob_1", "p1", "score", "prediction"):
                p = v.get(key)
                if isinstance(p, (int, float)):
                    return min(1.0, max(0.0, float(p)))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            return min(1.0, max(0.0, float(v)))
    return None


class QualityPlane:
    """Per-model composition of sink + joiner + monitor — ONE object the
    daemon hangs off a ModelEntry and `op run --audit-dir` arms on a
    ScoreFunction.

    Score path:    ids = plane.on_scored(rows) — allocates ids, audits,
                   notes pendings, returns ids positionally (None where the
                   row carried no readable score).
    Feedback path: plane.on_feedback(id, label) — joins, folds the pair
                   into the QualityMonitor, returns the join status.
    """

    def __init__(self, model_label: str, *, audit_dir: Optional[str] = None,
                 baseline: Optional[Mapping] = None,
                 fingerprint: str = "",
                 thresholds: Optional[QualityThresholds] = None,
                 sample_every: int = 1,
                 window_pairs: Optional[int] = 4096,
                 check_every: int = 64,
                 ttl_notes: int = 65536, max_pending: int = 16384,
                 deterministic: Optional[bool] = None, registry=None):
        self.model_label = str(model_label)
        self.sink = (AuditSink(audit_dir, model_label,
                               fingerprint=fingerprint,
                               sample_every=sample_every,
                               deterministic=deterministic,
                               registry=registry)
                     if audit_dir else None)
        self.joiner = LabelJoiner(ttl_notes=ttl_notes,
                                  max_pending=max_pending,
                                  registry=registry,
                                  model_label=model_label)
        self.monitor = QualityMonitor(baseline, thresholds=thresholds,
                                      registry=registry, source=model_label,
                                      window_pairs=window_pairs,
                                      check_every=check_every)
        self._seq = 0
        self._lock = make_lock("QualityPlane._lock")
        self._trace = _trace16(
            bool(deterministic
                 or (deterministic is None
                     and os.environ.get("TT_AUDIT_DETERMINISTIC"))),
            self.model_label)

    def _next_id(self) -> str:
        return self._next_ids(1)[0]

    def _next_ids(self, n: int) -> list[str]:
        if self.sink is not None:
            return self.sink.next_ids(n)
        with self._lock:
            start = self._seq + 1
            self._seq += n
        return [f"{self._trace}-{s:08d}" for s in range(start, start + n)]

    # --- score path ---------------------------------------------------------------------
    def on_scored(self, rows: Sequence[Mapping],
                  scores: Optional[Sequence[Optional[float]]] = None,
                  ) -> list[Optional[str]]:
        """Audit + pending-note a batch of result rows; returns one
        prediction id (or None) per row, positionally. Never raises into
        the scoring path — and takes each lock ONCE per batch, not per row
        (id block allocation + `note_many`)."""
        ids: list[Optional[str]] = [None] * len(rows)
        try:
            idx: list[int] = []
            vals: list[float] = []
            for i, row in enumerate(rows):
                score = (scores[i] if scores is not None
                         else extract_score(row))
                if score is not None:
                    idx.append(i)
                    vals.append(score)
            if not idx:
                return ids
            pids = self._next_ids(len(idx))
            for j, i in enumerate(idx):
                ids[i] = pids[j]
            if self.sink is not None:
                for pid, score in zip(pids, vals):
                    self.sink.emit(pid, score)
            self.joiner.note_many(list(zip(pids, vals)))
        except Exception:
            pass
        return ids

    # --- feedback path ------------------------------------------------------------------
    def on_feedback(self, prediction_id: str, label: float) -> str:
        status, pair = self.joiner.feedback(prediction_id, label)
        if pair is not None:
            self.monitor.observe_pair(*pair)
        return status

    def on_feedback_many(self, labels: Sequence[Mapping]) -> dict:
        """Batch form for the HTTP route: [{"id": .., "label": ..}, ...] ->
        status counts. Malformed entries count as `invalid` instead of
        failing the whole POST; everything well-formed joins and folds
        under one joiner lock + one monitor lock."""
        out = {"joined": 0, "duplicate": 0, "unmatched": 0, "invalid": 0}
        try:
            # fast path: one comprehension when every entry is well-formed
            items = [(item["id"], float(item["label"])) for item in labels]
        except (KeyError, TypeError, ValueError):
            items = []
            for item in labels:
                try:
                    items.append((item["id"], float(item["label"])))
                except (KeyError, TypeError, ValueError):
                    out["invalid"] += 1
        counts, pairs = self.joiner.feedback_many(items)
        for k, v in counts.items():
            out[k] += v
        if pairs:
            self.monitor.observe_pairs(pairs)
        return out

    # --- introspection / lifecycle ------------------------------------------------------
    def stats(self) -> dict:
        m = self.monitor.report()
        return {
            "model": self.model_label,
            "join": self.joiner.stats(),
            "window": {k: (round(v, 6) if isinstance(v, float) else v)
                       for k, v in m["window"].items()
                       if k != "calibration"},
            "baseline": m["baseline"],
            "active_alerts": m["active_alerts"],
            "audit_segments": (len(self.sink.segments())
                               if self.sink is not None else 0),
        }

    def close(self) -> None:
        if self.sink is not None:
            self.sink.close()
