"""Persistent serving daemon: a multi-model cache + adaptive micro-batching.

The reference's L5 serving story (PAPER.md: Spark-free `scoreFunction` on a
plain JVM) taken to real-traffic scale: BENCH_r05 shows every fresh scoring
process paying ~16.5 s of warmup and every per-call device dispatch ~101 ms —
costs a long-lived process amortizes once. `op serve` is that process:

* **multi-model cache** — an LRU of loaded `WorkflowModel`s plus their warmed
  `ScoreFunction` handles and `MicroBatcher`s, keyed by the model DIRECTORY'S
  CONTENT FINGERPRINT (sha256 of model.json + array sidecars), so re-admitting
  an unchanged dir is a cache hit and a resaved model is a different entry.
  Eviction closes the entry's batcher (drains in-flight work) and quarantine
  sidecar. Each entry carries its own per-model circuit breaker
  (`serve_device:<label>` series — the PR-6 failover machinery) and its own
  `serve_latency_seconds{backend,model}` SLO histograms.
* **admission pre-warm** — `ScoreFunction.warm()` compiles every pow2 pad_to
  bucket on every routable lane at admit time (throwaway synthetic buffers),
  so the first coalesced dispatch compiles nothing and `auto_threshold()`
  starts from measured warm latencies, not the cold constant.
* **adaptive micro-batching** — serve/batcher.py coalesces concurrent
  requests into pow2-bucketed device batches through the shared input
  executor (`Prefetcher(place=)`), with a max-wait deadline so a lone
  request degrades to the in-process CPU plan.

Surfaces: `DaemonClient` (in-process, the test/bench interface) and a
stdlib-only HTTP/JSON endpoint (`make_http_server` / `op serve`):

    POST /v1/score   {"model": NAME?, "records": [{...}, ...]}
                     -> {"model": NAME, "results": [{...}|null, ...]}
                        (null = row quarantined as poison)
    POST /v1/feedback {"model": NAME?, "labels": [{"id", "label"}, ...]}
                     -> join-status counts (delayed ground truth keyed by
                        the prediction_id minted on the score path)
    POST /v1/models  {"path": DIR, "name": NAME?}      admit/refresh a model
    GET  /v1/models                                    cache contents
    GET  /healthz                                      daemon + breaker state
    GET  /metrics                                      Prometheus exposition

See docs/serving.md for the lifecycle and SLO metric families.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from collections import OrderedDict
from typing import Optional

from .. import obs
from ..resilience.lockcheck import make_lock
from .batcher import MicroBatcher, Overloaded
from .scoring import score_function


def fingerprint_model_dir(path: str) -> str:
    """Content fingerprint of a saved model bundle: sha256 over the manifest
    bytes plus the name and BYTES of every arrays sidecar (names alone are
    not enough: an external sync can drop different same-size arrays into an
    existing dir without touching model.json). The cache identity — a resave
    with different fitted params is a different model, the same dir
    re-admitted is a hit. Admission already pays seconds of warm compile, so
    hashing the sidecars is noise."""
    h = hashlib.sha256()
    with open(os.path.join(path, "model.json"), "rb") as fh:
        h.update(fh.read())
    for fname in sorted(os.listdir(path)):
        if fname.endswith(".npz"):
            h.update(fname.encode("utf-8"))
            with open(os.path.join(path, fname), "rb") as fh:
                for chunk in iter(lambda: fh.read(1 << 20), b""):
                    h.update(chunk)
    return h.hexdigest()


def resolve_buckets(buckets=None, floor: int = 1,
                    max_batch: int = 256) -> list[int]:
    """THE bucket-ladder resolution: an explicit ladder is sorted+deduped,
    else `serving_buckets(floor, max_batch)`. Shared by admission warm,
    `op warmup --serving`, and AOT export so the three can never derive
    different ladders for the same knobs."""
    if buckets:
        return sorted({int(b) for b in buckets})
    return serving_buckets(floor, max_batch)


def serving_buckets(floor: int = 1, max_batch: int = 256) -> list[int]:
    """The pow2 pad_to ladder serving coalesces into: floor, 2*floor, ...,
    max_batch (both ends rounded up to powers of two — `pow2_bucket` is the
    same policy the streaming runner uses, so warmed serving shapes and
    streamed-scoring shapes coincide)."""
    from ..types.table import pow2_bucket

    lo = pow2_bucket(max(1, int(floor)))
    hi = pow2_bucket(max(lo, int(max_batch)))
    out = []
    b = lo
    while b <= hi:
        out.append(b)
        b <<= 1
    return out


class ModelEntry:
    """One admitted model: loaded weights + warmed handle + its batcher."""

    __slots__ = ("name", "fingerprint", "path", "model", "score_fn",
                 "batcher", "admitted_at", "warm_report", "last_used",
                 "quality")

    def __init__(self, name, fingerprint, path, model, score_fn, batcher,
                 warm_report, quality=None):
        self.name = name
        self.fingerprint = fingerprint
        self.path = path
        self.model = model
        self.score_fn = score_fn
        self.batcher = batcher
        self.warm_report = warm_report
        self.quality = quality  # QualityPlane or None (quality plane off)
        self.admitted_at = time.monotonic()
        self.last_used = self.admitted_at

    def info(self) -> dict:
        # read-without-create lookup: an idle model must not materialize
        # empty series just by being health-checked
        wait_h = obs.default_registry().find(
            "serve_queue_wait_seconds", labels={"model": self.name})
        wait_p50 = wait_h.percentile(50) if wait_h is not None else None
        aot = self.score_fn.aot_status()
        return {
            "name": self.name,
            "fingerprint": self.fingerprint,
            "path": self.path,
            "breaker": self.score_fn.breaker_state(),
            # rollout tooling verifies a replica actually hydrated: status
            # ("hydrated"/"partial"/"fallback"), which pow2 buckets came
            # from artifacts, and how many dispatches missed them since
            "aot": ({"status": aot.get("status"),
                     "buckets_hydrated": aot.get("buckets_hydrated", []),
                     "fallback_compiles": aot.get("fallback_compiles", 0)}
                    if aot else None),
            "auto_threshold": self.score_fn.auto_threshold(),
            "queue_wait_p50_ms": (round(wait_p50 * 1e3, 3)
                                  if wait_p50 is not None else None),
            "admitted_s": round(time.monotonic() - self.admitted_at, 3),
            "warm": self.warm_report,
            "batcher": self.batcher.stats(),
            "quality": (self.quality.stats()
                        if self.quality is not None else None),
        }


class ServingDaemon:
    """The long-lived scoring process behind `op serve`.

    Thread-safe: the HTTP server's handler threads, the per-model batcher
    workers, and in-process `DaemonClient` callers all go through here. The
    cache lock covers only dict operations; model load + bucket warm (seconds
    of compile) run under a separate admission lock so admitting model B
    never blocks traffic already flowing to model A.
    """

    def __init__(self, *, max_models: int = 4, max_wait_ms: float = 2.0,
                 max_batch: int = 256, bucket_floor: int = 1,
                 backend: Optional[str] = "auto", mesh=None, policy=None,
                 warm: bool = True, prefetch: int = 2,
                 quarantine_root: Optional[str] = "auto", aot: bool = True,
                 queue_depth: int = 4096, monitor=False, quality=False):
        if max_models < 1:
            raise ValueError(f"max_models must be >= 1, got {max_models}")
        self._max_models = int(max_models)
        self._max_wait_ms = float(max_wait_ms)
        self._max_batch = int(max_batch)
        #: per-model request-queue bound: past it the daemon SHEDS (HTTP
        #: 429 + serve_shed_total{model}) instead of queueing unboundedly
        self._queue_depth = int(queue_depth)
        self._buckets = serving_buckets(bucket_floor, max_batch)
        self._backend = backend
        self._mesh = mesh
        self._policy = policy
        self._warm = bool(warm)
        #: consult the bundle's AOT artifact store at admission (serve/
        #: aot.py): compatible pre-compiled executables hydrate in
        #: milliseconds with zero XLA work; False forces the compile path
        self._aot = "auto" if aot else False
        self._prefetch = int(prefetch)
        #: "auto" = a fresh temp dir per daemon: poison rows are quarantined
        #: (request keeps flowing, bad rows come back None) instead of
        #: killing the shared stream. None disables; a path pins it.
        self._quarantine_root = quarantine_root
        #: drift monitoring per admitted model: False (off), True (default
        #: ServingMonitor thresholds), or a dict of ServingMonitor.for_model
        #: kwargs (thresholds / window_batches / check_every — the autopilot
        #: arms a windowed monitor this way). Models saved without a
        #: serving_baseline admit un-monitored either way.
        self._monitor = monitor
        #: model-quality plane per admitted model (serve/feedback.py): False
        #: (off), True (defaults: join-only, no audit dir), or a dict of
        #: QualityPlane kwargs — "audit_dir" lands sampled prediction-audit
        #: segments, "thresholds"/"window_pairs"/"check_every" tune the
        #: online QualityMonitor. Armed entries mint a `prediction_id` per
        #: result row and accept delayed labels on POST /v1/feedback.
        self._quality = quality
        self._lock = make_lock("ServingDaemon._lock")
        self._admit_lock = make_lock("ServingDaemon._admit_lock")
        self._cache: "OrderedDict[str, ModelEntry]" = OrderedDict()
        self._names: dict[str, str] = {}  # alias (name or abspath) -> fp
        self._started = time.monotonic()
        self._closed = False
        reg = obs.default_registry()
        #: federation point for the serving side of the fleet: the daemon's
        #: own registry is one pull source ("serve" role), and remote
        #: replicas/sidecars POST {role, process, snapshot} to
        #: /fleet/metrics. GET /fleet/metrics and `op top --daemon` read the
        #: merged view.
        self.fleet = obs.FleetAggregator()
        self.fleet.attach_local(
            obs.process_role(default="serve"), os.getpid(),
            lambda: reg.snapshot(samples=True))
        self._g_loaded = reg.gauge(
            "serve_models_loaded", help="models resident in the daemon cache")
        self._c_evicted = reg.counter(
            "serve_model_evictions_total",
            help="models evicted from the daemon LRU cache")
        self._c_admitted = reg.counter(
            "serve_model_admissions_total",
            help="model admissions (cache misses) into the daemon")

    def _evict_over_capacity_locked(self, protect: frozenset) -> list:
        """Pop LRU entries past `max_models`, SKIPPING protected
        fingerprints (a swap protects the alias's current target so
        admitting the replacement can never strand the alias mid-swap).
        When every remaining victim is protected the cache briefly
        overshoots capacity instead — the swap re-runs this after the
        repoint, when nothing needs protecting. Caller holds the lock and
        retires the returned entries OUTSIDE it."""
        evicted = []
        while len(self._cache) > self._max_models:
            victim_fp = next((fp for fp in self._cache
                              if fp not in protect), None)
            if victim_fp is None:
                break  # everything resident is protected: tolerate overshoot
            old = self._cache.pop(victim_fp)
            self._names = {k: v for k, v in self._names.items()
                           if v != old.fingerprint}
            evicted.append(old)
        self._g_loaded.set(len(self._cache))
        return evicted

    # --- admission --------------------------------------------------------------------
    def admit(self, model_dir: str, name: Optional[str] = None,
              _protect: frozenset = frozenset()) -> ModelEntry:
        """Load, warm, and cache a saved model (idempotent per content
        fingerprint). Returns the live entry; evicts LRU entries past
        `max_models` — eviction drains the victim's batcher first."""
        path = os.path.abspath(model_dir)
        fp = fingerprint_model_dir(path)
        with self._lock:
            if self._closed:
                raise RuntimeError("daemon is closed")
            entry = self._cache.get(fp)
            if entry is not None:
                self._cache.move_to_end(fp)
                entry.last_used = time.monotonic()
                if name:
                    self._names[name] = fp
                return entry
        with self._admit_lock:
            with self._lock:  # lost the admit race? the winner's entry serves
                if self._closed:  # close() may have landed since the fast path
                    raise RuntimeError("daemon is closed")
                entry = self._cache.get(fp)
                if entry is not None:
                    self._cache.move_to_end(fp)
                    if name:
                        self._names[name] = fp
                    return entry
            from ..workflow.workflow import WorkflowModel

            label = name or f"m_{fp[:12]}"
            with obs.span(f"serve:admit:{label}"):
                model = WorkflowModel.load(path)
                rm = getattr(model, "resource_model", None)
                if rm:
                    # surface the bundle's train-time `op explain` prediction
                    # on the admit span: operators see the model's expected
                    # per-device HBM / collective bytes before the first score
                    t = rm.get("totals") or {}
                    obs.add_event(
                        "explain", source="bundle",
                        mesh="%sx%s" % tuple(rm.get("mesh_shape", (1, 1))),
                        peak_stage=t.get("peak_stage_uid"),
                        peak_resident_bytes=t.get("peak_resident_bytes"),
                        collective_bytes=t.get("collective_bytes"))
                policy = self._policy
                if policy is None and self._quarantine_root is not None:
                    from ..resilience import FaultPolicy

                    root = self._quarantine_root
                    if root == "auto":
                        import tempfile

                        root = tempfile.mkdtemp(prefix="op_serve_q_")
                        self._quarantine_root = root
                    policy = FaultPolicy(
                        quarantine_dir=os.path.join(root, label))
                mon = None
                if self._monitor and getattr(model, "serving_baseline", None):
                    from ..obs.monitor import ServingMonitor

                    mon_kw = {"source": label,
                              **(self._monitor
                                 if isinstance(self._monitor, dict) else {})}
                    mon = ServingMonitor.for_model(model, **mon_kw)
                plane = None
                if self._quality:
                    from .feedback import QualityPlane

                    q_kw = (dict(self._quality)
                            if isinstance(self._quality, dict) else {})
                    q_kw.setdefault(
                        "baseline", getattr(model, "quality_baseline", None))
                    plane = QualityPlane(label, fingerprint=fp, **q_kw)
                # a bundle tuned by `op autotune` carries its searched
                # serving bucket floor; the load() gate already dropped the
                # stamp if this host is a different part, so a surviving
                # floor is measured truth for THIS device class
                buckets = self._buckets
                tc = getattr(model, "tuned_config", None) or {}
                tuned_floor = int((tc.get("config") or {})
                                  .get("serve_floor", 0) or 0)
                if tuned_floor > 0:
                    buckets = serving_buckets(tuned_floor, self._max_batch)
                    obs.add_event("tuned_config", source="bundle",
                                  serve_floor=tuned_floor)
                fn = score_function(
                    model, pad_to=buckets, backend=self._backend,
                    mesh=self._mesh, policy=policy, model_label=label,
                    monitor=mon, quality=plane)
                # the SAME ladder-warm helper `op warmup --serving` uses:
                # consult the bundle's AOT artifacts first, compile only
                # what hydration did not cover — a cold DAEMON PROCESS
                # admitting an AOT bundle reaches first score in ms
                from ..workflow.warmup import warm_serving_handle

                warm_report = (warm_serving_handle(
                    fn, buckets=buckets, aot=self._aot)
                    if self._warm else None)
                batcher = MicroBatcher(
                    fn, max_batch=self._max_batch,
                    max_wait_ms=self._max_wait_ms, prefetch=self._prefetch,
                    queue_depth=self._queue_depth, model_label=label)
            entry = ModelEntry(label, fp, path, model, fn, batcher,
                               warm_report, quality=plane)
            evicted: list[ModelEntry] = []
            with self._lock:
                closed = self._closed
                if not closed:
                    self._cache[fp] = entry
                    self._names[label] = fp
                    self._names[path] = fp
                    evicted = self._evict_over_capacity_locked(
                        frozenset({fp}) | _protect)
            if closed:
                # close() ran while this admission was mid-warm: the cache
                # is already drained, so inserting now would leak a live
                # batcher worker (and its quarantine sidecar) past
                # close()/__exit__ — drain the fresh entry and refuse
                entry.batcher.close()
                entry.score_fn.close()
                if entry.quality is not None:
                    entry.quality.close()
                raise RuntimeError("daemon closed during admission")
            self._c_admitted.inc()
            for old in evicted:
                self._retire(old)
            return entry

    def _retire(self, entry: ModelEntry) -> None:
        self._c_evicted.inc()
        obs.add_event("serve:evict", model=entry.name,
                      fingerprint=entry.fingerprint[:12])
        entry.batcher.close()
        entry.score_fn.close()
        if entry.quality is not None:
            entry.quality.close()

    # --- hot swap (alias indirection) -------------------------------------------------
    def aliases(self) -> dict:
        """Snapshot of the alias table: {name or abspath: fingerprint}."""
        with self._lock:
            return dict(self._names)

    def repoint(self, name: str, fingerprint: str) -> Optional[str]:
        """Atomically repoint alias `name` at an ALREADY-ADMITTED entry
        (by fingerprint, or by any alias resolving to one). Returns the
        fingerprint `name` previously resolved to (None if unbound) — the
        rollback token. Raises KeyError when the target is not resident:
        an alias must never dangle, so traffic always reaches a warmed
        model."""
        with self._lock:
            fp = self._names.get(fingerprint, fingerprint)
            if fp not in self._cache:
                raise KeyError(f"no admitted model with fingerprint "
                               f"{fingerprint!r} to repoint {name!r} at")
            prev = self._names.get(name)
            self._names[name] = fp
            self._cache.move_to_end(fp)
        obs.add_event("serve:repoint", alias=name, to=fp[:12],
                      prev=(prev or "")[:12])
        return prev

    def swap(self, name: str, model_dir: str,
             retire_old: bool = False) -> ModelEntry:
        """Zero-downtime hot swap: admit (load + full bucket warm / AOT
        hydrate) the bundle at `model_dir`, then atomically repoint alias
        `name` at its fingerprint. Requests keep resolving through the alias
        the whole time — in-flight and queued work on the previous model
        drains through ITS batcher untouched; only submissions AFTER the
        repoint land on the new entry, and the first of them hits warmed
        executables (no unwarmed-shape compiles on the hot path).

        The previous entry stays resident by default — the demotion/rollback
        target (`repoint(name, old_fp)` restores it instantly). Admission
        failures (torn bundle, lint-invalid manifest, dead path) raise
        BEFORE the alias is touched, so a failed swap leaves the champion
        serving, untouched. `retire_old=True` drains and releases the
        previous entry once the repoint lands.

        The alias's CURRENT target is PROTECTED from LRU eviction while the
        replacement admits (at capacity the victim is the next-LRU entry
        instead; with nothing else evictable the cache briefly overshoots,
        re-trimmed right after the repoint) — requests resolving the alias
        mid-swap must always find a live entry. Note the post-repoint trim
        can claim the demoted champion when it is the LRU entry of a full
        cache: zero-downtime is unconditional, rollback-target residency is
        subject to `max_models` pressure like any other entry."""
        with self._lock:
            protect = self._names.get(name)
        entry = self.admit(  # may raise: alias untouched
            model_dir,
            _protect=frozenset({protect} if protect else ()))
        old_fp = None
        retired: list[ModelEntry] = []
        with self._lock:
            old_fp = self._names.get(name)
            self._names[name] = entry.fingerprint
            # the alias IS the serving name now: entry.info()/metrics keep
            # the admission label, resolution works through either
            if retire_old and old_fp and old_fp != entry.fingerprint \
                    and old_fp in self._cache:
                old = self._cache.pop(old_fp)
                # same discipline as LRU eviction: every alias of the
                # retired entry goes with it
                self._names = {k: v for k, v in self._names.items()
                               if v != old_fp}
                self._g_loaded.set(len(self._cache))
                retired.append(old)
            # the admission-time protection may have left an overshoot:
            # trim now that the alias points at the new entry (only it
            # needs protecting)
            retired.extend(self._evict_over_capacity_locked(
                frozenset({entry.fingerprint})))
        obs.add_event("serve:swap", alias=name, to=entry.fingerprint[:12],
                      prev=(old_fp or "")[:12], retired=bool(retired))
        obs.default_registry().counter(
            "serve_swaps_total",
            help="alias repoints onto a newly admitted model (hot swaps)",
            labels={"model": name}).inc()
        for old in retired:
            # drain AFTER the repoint: close() blocks until the victim's
            # queued + in-flight futures resolve, and new traffic is already
            # routing to the replacement
            self._retire(old)
        return entry

    # --- scoring ----------------------------------------------------------------------
    def _resolve(self, model: Optional[str]) -> ModelEntry:
        with self._lock:
            if model is None:
                if len(self._cache) == 1:
                    entry = next(iter(self._cache.values()))
                    entry.last_used = time.monotonic()
                    return entry
                raise KeyError(
                    "model name required (daemon holds "
                    f"{len(self._cache)} models)")
            fp = self._names.get(model) or self._names.get(
                os.path.abspath(model)) or model
            entry = self._cache.get(fp)
            if entry is None:
                raise KeyError(f"model {model!r} not admitted")
            self._cache.move_to_end(fp)  # LRU touch
            entry.last_used = time.monotonic()
            return entry

    def submit(self, model: Optional[str], records):
        """Enqueue a request on the named model's batcher -> Future."""
        return self._resolve(model).batcher.submit(records)

    def score(self, model: Optional[str], records,
              timeout: Optional[float] = 60.0):
        return self.submit(model, records).result(timeout)

    # --- label feedback (model-quality plane) -----------------------------------------
    def feedback(self, model: Optional[str], labels) -> dict:
        """Resolve delayed ground-truth labels against the named model's
        quality plane: `labels` is [{"id": PREDICTION_ID, "label": 0|1},
        ...]; joined pairs fold into the model's online QualityMonitor.
        Returns join-status counts ({"joined", "duplicate", "unmatched",
        "invalid"}). KeyError for an unknown model; ValueError when the
        model was admitted without a quality plane (daemon started with
        quality=False)."""
        entry = self._resolve(model)
        if entry.quality is None:
            raise ValueError(
                f"model {entry.name!r} has no quality plane "
                "(daemon started with quality=False)")
        counts = entry.quality.on_feedback_many(labels)
        obs.add_event("serve:feedback", model=entry.name, **counts)
        return {"model": entry.name, **counts}

    # --- introspection / lifecycle ----------------------------------------------------
    def models(self) -> list[dict]:
        with self._lock:
            entries = list(self._cache.values())
        return [e.info() for e in entries]

    def stats(self) -> dict:
        models = self.models()
        return {
            "status": "ok",
            "uptime_s": round(time.monotonic() - self._started, 3),
            "max_models": self._max_models,
            "max_batch": self._max_batch,
            "max_wait_ms": self._max_wait_ms,
            "buckets": self._buckets,
            "models": models,
        }

    def close(self) -> None:
        """Drain every batcher and release every handle (idempotent)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            entries = list(self._cache.values())
            self._cache.clear()
            self._names.clear()
            self._g_loaded.set(0)
        for e in entries:
            e.batcher.close()
            e.score_fn.close()
            if e.quality is not None:
                e.quality.close()

    def __enter__(self) -> "ServingDaemon":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class DaemonClient:
    """In-process client with the HTTP surface's semantics — tests and the
    bench drive the daemon through this without sockets."""

    def __init__(self, daemon: ServingDaemon):
        self._daemon = daemon

    def admit(self, path: str, name: Optional[str] = None) -> dict:
        return self._daemon.admit(path, name=name).info()

    def score(self, records, model: Optional[str] = None,
              timeout: Optional[float] = 60.0) -> list:
        return self._daemon.score(model, records, timeout=timeout)

    def submit(self, records, model: Optional[str] = None):
        return self._daemon.submit(model, records)

    def feedback(self, labels, model: Optional[str] = None) -> dict:
        return self._daemon.feedback(model, labels)

    def models(self) -> list[dict]:
        return self._daemon.models()

    def healthz(self) -> dict:
        return self._daemon.stats()

    def metrics(self) -> str:
        return obs.default_registry().to_prometheus()

    def fleet_metrics(self) -> str:
        """Aggregated exposition across every process the daemon's
        FleetAggregator knows about (role/process labels on each series)."""
        return self._daemon.fleet.to_prometheus()


# --- HTTP surface (stdlib only) -------------------------------------------------------
#: default POST body ceiling: generous for real scoring traffic (a full
#: max_batch of fat records is well under 1 MiB) while bounding what one
#: request can make the daemon buffer in RAM
MAX_BODY_BYTES = 8 << 20


def make_http_server(daemon: ServingDaemon, host: str = "127.0.0.1",
                     port: int = 8000,
                     max_body_bytes: int = MAX_BODY_BYTES):
    """Build (not start) a ThreadingHTTPServer over the daemon. Callers run
    `server.serve_forever()` (blocking) or on a thread; `server.shutdown()`
    from another thread stops it. Port 0 binds an ephemeral port —
    `server.server_address[1]` is the real one.

    `max_body_bytes` caps what a POST may carry: an oversized (or
    missing/absurd Content-Length) body is answered 413 WITHOUT reading it —
    `rfile.read(attacker-chosen length)` would otherwise buffer an arbitrary
    payload in RAM per handler thread. Rejections land on
    `serve_rejected_total{reason}`."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    max_body = int(max_body_bytes)

    def _rejected(reason: str):
        return obs.default_registry().counter(
            "serve_rejected_total",
            help="HTTP requests rejected before scoring (oversized or "
                 "malformed bodies)",
            labels={"reason": reason})

    class Server(ThreadingHTTPServer):
        #: stdlib default listen backlog is 5 — a burst of concurrent
        #: clients (the daemon's whole reason to exist) overflows it and
        #: gets connection resets; match the batcher's appetite instead
        request_queue_size = 128

    class Handler(BaseHTTPRequestHandler):
        server_version = "op-serve"
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # traffic rides the metrics, not stderr
            pass

        def _send(self, code: int, body: bytes,
                  ctype: str = "application/json") -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _json(self, code: int, payload) -> None:
            self._send(code, json.dumps(payload, default=str).encode("utf-8"))

        def _error(self, code: int, message: str) -> None:
            self._json(code, {"error": message})

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            try:
                if self.path == "/healthz":
                    self._json(200, daemon.stats())
                elif self.path == "/metrics":
                    self._send(200,
                               obs.default_registry().to_prometheus()
                               .encode("utf-8"),
                               ctype="text/plain; version=0.0.4")
                elif self.path.split("?", 1)[0] == "/fleet/metrics":
                    # merged view across the daemon's own registry plus every
                    # snapshot POSTed by remote replicas; ?format=json returns
                    # the raw per-process snapshots for `op top --daemon`
                    if "format=json" in (self.path.split("?", 1) + [""])[1]:
                        self._json(200,
                                   {"snapshots":
                                    daemon.fleet.raw_snapshots()})
                    else:
                        self._send(200,
                                   daemon.fleet.to_prometheus()
                                   .encode("utf-8"),
                                   ctype="text/plain; version=0.0.4")
                elif self.path == "/v1/models":
                    self._json(200, {"models": daemon.models()})
                else:
                    self._error(404, f"no route {self.path}")
            except Exception as e:  # noqa: BLE001 — a handler must answer
                self._error(500, f"{type(e).__name__}: {e}"[:500])

        def do_POST(self):  # noqa: N802
            try:
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                except ValueError:
                    _rejected("bad_length").inc()
                    self.close_connection = True  # body length unknown: can't reuse
                    return self._error(411, "Content-Length is not an integer")
                if length < 0:
                    _rejected("bad_length").inc()
                    self.close_connection = True
                    return self._error(411, "negative Content-Length")
                if length > max_body:
                    # answered WITHOUT reading the body: the cap exists so a
                    # single oversized /v1/score cannot balloon daemon RSS
                    _rejected("too_large").inc()
                    self.close_connection = True  # unread body poisons keep-alive
                    return self._error(
                        413, f"body of {length} bytes exceeds the "
                             f"{max_body}-byte limit")
                raw = self.rfile.read(length) if length else b"{}"
                try:
                    body = json.loads(raw.decode("utf-8") or "{}")
                except ValueError:
                    return self._error(400, "body is not valid JSON")
                if not isinstance(body, dict):
                    return self._error(400, "body must be a JSON object")
                if self.path == "/v1/models":
                    if "path" not in body:
                        return self._error(400, 'missing "path"')
                    info = daemon.admit(body["path"],
                                        name=body.get("name")).info()
                    return self._json(200, info)
                if self.path == "/fleet/metrics":
                    # push leg of metrics federation: a replica/sidecar posts
                    # its registry snapshot (the METRICS-frame payload shape)
                    role = body.get("role")
                    snap = body.get("snapshot")
                    if not role or not isinstance(snap, dict):
                        return self._error(
                            400, 'missing "role" or "snapshot" object')
                    daemon.fleet.ingest(str(role),
                                        str(body.get("process") or "remote"),
                                        snap)
                    return self._json(200, {"ok": True})
                if self.path in ("/v1/score", "/score"):
                    records = body.get("records")
                    if records is None and "record" in body:
                        records = [body["record"]]
                    if not isinstance(records, list):
                        return self._error(400, 'missing "records" list')
                    entry = daemon._resolve(body.get("model"))
                    # W3C trace propagation: a caller-sent traceparent header
                    # adopts the caller's trace_id onto this process's tracer
                    # and parents the scoring span under the caller's span,
                    # so `op trace-merge` stitches client -> daemon end to end
                    ctx = obs.TraceContext.from_traceparent(
                        self.headers.get("traceparent"))
                    t = obs.current()
                    if ctx is not None and t is not None:
                        t.adopt_trace_id(ctx.trace_id)
                    with obs.span(
                            f"serve:http_score:{entry.name}",
                            remote_parent=(ctx.span_id if ctx else None)):
                        obs.add_event("serve:http_score", model=entry.name,
                                      n=len(records))
                        results = entry.batcher.score(records, timeout=60.0)
                    return self._json(200, {"model": entry.name,
                                            "results": results})
                if self.path == "/v1/feedback":
                    # delayed ground truth keyed by prediction id: joined
                    # pairs feed the model's online quality metrics
                    labels = body.get("labels")
                    if labels is None and "id" in body:
                        labels = [{"id": body["id"],
                                   "label": body.get("label")}]
                    if not isinstance(labels, list):
                        return self._error(400, 'missing "labels" list')
                    return self._json(
                        200, daemon.feedback(body.get("model"), labels))
                return self._error(404, f"no route {self.path}")
            except KeyError as e:
                self._error(404, str(e))
            except Overloaded as e:
                # the overload guard: a full request queue answers FAST with
                # "try later", it does not make every queued caller slow
                self._error(429, str(e)[:500])
            except (ValueError, TypeError) as e:
                self._error(400, f"{type(e).__name__}: {e}"[:500])
            except Exception as e:  # noqa: BLE001 — a handler must answer
                self._error(500, f"{type(e).__name__}: {e}"[:500])

    return Server((host, port), Handler)
