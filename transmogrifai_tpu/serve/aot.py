"""AOT deploy artifacts: millisecond cold start for model load + first score.

The serving daemon (PR 7) made STEADY-STATE serving compile-free, but a fresh
PROCESS still pays seconds of trace+lower+compile per model shape before its
first score — which makes fleet rollout of N autoscaled replicas O(minutes)
each. This module extends the saved model bundle with an ahead-of-time
artifact set so a cold process reaches its first score in milliseconds:

* **Tier 1 — exact executables.** Every fused device step of the serving
  `LocalPlan`, for every routable lane (device / CPU failover) x pow2 pad_to
  bucket, is lowered, compiled, and serialized with
  `jax.experimental.serialize_executable` into `<model_dir>/aot/`. A fresh
  process deserializes (~tens of ms for a whole ladder) and scores with ZERO
  XLA work — no trace, no lower, no compile (`retrace_budget(0)`-clean from
  the very first request).
* **Tier 2 — persistent-cache priming.** Export runs with the persistent
  compilation cache enabled, so every exported program is also a cache entry:
  a process that cannot use the exact executables (e.g. jax upgraded) pays
  tracing + cache reads instead of full compiles.
* **Tier 3 — the warm path.** Anything stale or missing degrades to today's
  `ScoreFunction.warm` compile loop with a structured span event and an
  `aot_fallback_total{reason}` counter — never an error.

Artifacts are keyed by the SAME per-stage trace fingerprints the analyzer's
retrace rules (OP201-203) and the fused-run program cache use
(`analyze.plan_fingerprint`), plus a compatibility stamp (jax + jaxlib
versions, backend platform, device kind, device count, package code hash).
An edited
npz, a resave with different weights, a jax upgrade, or a different
accelerator all change the key and fall back gracefully.

Trust note: tier-1 blobs deserialize via pickle (jax's serialize_executable
wire format). Load artifacts only from bundles you would already trust to
`WorkflowModel.load` — a model bundle is code, not data.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from typing import TYPE_CHECKING, Optional, Sequence

from .. import obs

if TYPE_CHECKING:  # pragma: no cover
    from ..workflow.workflow import WorkflowModel
    from .scoring import ScoreFunction

#: bundle subdirectory holding the artifact set
AOT_DIR = "aot"
#: the artifact index (fingerprint, stamp, entries, lane windows)
AOT_INDEX = "aot_index.json"
AOT_VERSION = 1

#: bounded label set for aot_fallback_total (cardinality hygiene)
_FALLBACK_REASONS = ("absent", "corrupt_index", "mesh", "stamp",
                     "fingerprint", "deserialize", "unfingerprintable",
                     "error")

_CODE_FP: Optional[str] = None


def code_fingerprint() -> str:
    """sha256 over the CONTENT bytes of every package .py file (not mtimes —
    deploy replicas check out identical code with arbitrary timestamps, and
    the stamp must match across them). A code edit changes the hash and
    invalidates every artifact: a tier-1 blob silently replaying old stage
    semantics is the one failure mode this module must never have."""
    global _CODE_FP
    if _CODE_FP is not None:
        return _CODE_FP
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    h = hashlib.sha256()
    for dirpath, _dirnames, filenames in sorted(os.walk(root)):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            try:
                h.update(os.path.relpath(p, root).encode("utf-8"))
                with open(p, "rb") as fh:
                    h.update(fh.read())
            except OSError:
                pass
    _CODE_FP = h.hexdigest()[:16]
    return _CODE_FP


def compat_stamp() -> dict:
    """The environment an exact executable is valid in. Serialized compiled
    programs are bound to (jax/jaxlib wire version, backend, device kind) and
    to the package source that built the plan; device COUNT matters because a
    program compiled in a 1-device process carries a different device
    assignment than one from a forced-8-device test env."""
    import jax

    try:
        import jaxlib

        jaxlib_version = jaxlib.__version__
    except Exception:  # pragma: no cover — jaxlib always ships with jax
        jaxlib_version = ""
    try:
        dev = jax.devices()[0]
        platform, kind = dev.platform, getattr(dev, "device_kind", "")
    except Exception:  # pragma: no cover — no live backend
        platform, kind = "unknown", ""
    return {
        "jax": jax.__version__,
        # the wire format of a serialized executable is versioned by
        # jaxlib/XLA, which upgrades independently of the pure-python jax
        # package — same jax + newer jaxlib must still read as stale
        "jaxlib": jaxlib_version,
        "platform": platform,
        "device_kind": str(kind),
        "device_count": int(jax.device_count()),
        "code": code_fingerprint(),
    }


def _stamp_mismatch(stamp: dict) -> Optional[str]:
    """First mismatched stamp field against the live process, or None."""
    live = compat_stamp()
    for k in ("jax", "jaxlib", "platform", "device_kind", "device_count",
              "code"):
        if stamp.get(k) != live.get(k):
            return k
    return None


def index_path(model_dir: str) -> str:
    return os.path.join(model_dir, AOT_DIR, AOT_INDEX)


def read_index(model_dir: str) -> Optional[dict]:
    """The bundle's artifact index, or None when absent/unreadable."""
    try:
        with open(index_path(model_dir)) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _lanes_of(fn: "ScoreFunction") -> list[Optional[str]]:
    """THE routable-lane derivation: `ScoreFunction.warm`, export, and
    hydrate all call this one helper, so what warm compiles, what export
    serializes, and what hydration judges coverage against can never
    drift apart."""
    import jax

    if fn._backend == "auto":
        lanes: list[Optional[str]] = [None]
        if jax.devices()[0].platform != "cpu":
            lanes.append("cpu")
        return lanes
    return [fn._backend]


def _blob_name(lane_label: str, bucket: int, step: int) -> str:
    return f"{lane_label}_b{bucket}_s{step}.exec"


def note_fallback(reason: str, detail: str = "", log=None, *,
                  count_metric: bool = True) -> None:
    """ONE fallback occurrence: counter + span event + optional log — the
    single emission site every degrade path (hydrate, per-blob deserialize,
    warm's validation retirement) goes through, so the metric name, help
    text, and reason vocabulary cannot drift apart. `count_metric=False`
    keeps the event/log but skips the counter for callers whose occurrences
    were already counted one by one (per-blob deserialize failures)."""
    if reason not in _FALLBACK_REASONS:
        reason = "error"
    if count_metric:
        obs.default_registry().counter(
            "aot_fallback_total",
            help="AOT hydration attempts that degraded to the warm compile path",
            labels={"reason": reason}).inc()
    obs.add_event("aot:fallback", reason=reason, detail=detail[:200])
    if log is not None:
        log(f"serving aot: fallback ({reason}{': ' + detail if detail else ''})")


def _fallback(reason: str, detail: str = "", log=None, *,
              count_metric: bool = True) -> dict:
    note_fallback(reason, detail, log, count_metric=count_metric)
    # "covered" is a list of [lane_label, bucket] pairs (NOT a set): the
    # report is part of the public serve API and must json.dumps cleanly
    return {"status": "fallback", "reason": reason, "detail": detail,
            "buckets_hydrated": [], "executables": 0, "covered": []}


# --- export ---------------------------------------------------------------------------
def publish_aot(path: str, staging: str) -> None:
    """Swap a staged export into place as `<path>/aot/` — the LAST step of
    an artifact publish, once the bundle it belongs to is durable (for
    `WorkflowModel.save(aot=True)`: after the manifest's atomic replace).
    Until this runs, the previous artifact generation stays intact."""
    import shutil

    adir = os.path.join(path, AOT_DIR)
    shutil.rmtree(adir, ignore_errors=True)
    os.replace(staging, adir)


def export_aot(model: "WorkflowModel", path: str, *,
               buckets: Optional[Sequence[int]] = None, floor: int = 1,
               max_batch: int = 256, backend: Optional[str] = "auto",
               log=None, _defer_publish: bool = False) -> dict:
    """Write the AOT artifact set for `model` into `<path>/aot/`.

    For every routable serving lane x pow2 pad_to bucket, every fused device
    step of the serving plan is lowered+compiled at the bucket's exact
    shapes (the same synthetic placeholder buffers `warm` uses — shapes
    depend only on the fitted schema and the row count, never on values) and
    serialized. The compiled programs are installed into the handle
    in-process and each bucket gets one timed pass, so the report carries
    measured per-lane (latency, rows) windows — the routing-crossover seed a
    hydrated replica starts from. Export also primes the persistent
    compilation cache (tier 2).

    The artifact set is built in a staging dir and swapped into place only
    when complete (`publish_aot`) — a crash mid-export leaves any previous
    generation untouched. `_defer_publish=True` (the `save(aot=True)` path)
    skips the swap and returns the staging dir under "staging": the caller
    publishes after its own durability point, so the old bundle's manifest
    and its matching artifacts never part ways.

    Returns {status, fingerprint, stamp, lanes, buckets, executables,
    bytes, lane_windows, wall_s}. Plans whose stages have no stable trace
    fingerprint (OP201) cannot key an artifact cache: status "skipped",
    nothing written (an immediate-publish skip still sweeps the previous
    generation — the new plan invalidated it).
    """
    import shutil

    import jax
    from jax.experimental import serialize_executable as _se

    from ..analyze import plan_fingerprint
    from ..utils.compile_cache import enable_compile_cache
    from .daemon import resolve_buckets
    from .scoring import _placeholder

    t0 = time.perf_counter()
    try:
        fingerprint = plan_fingerprint(model.stages)
    except TypeError as e:
        if not _defer_publish:
            # the new plan cannot carry artifacts, so any previous
            # generation is stale; a deferring caller sweeps at its own
            # durability point instead
            shutil.rmtree(os.path.join(path, AOT_DIR), ignore_errors=True)
        if log is not None:
            log(f"serving aot: export skipped (unfingerprintable plan: {e})")
        return {"status": "skipped", "reason": "unfingerprintable",
                "detail": str(e)[:200]}
    enable_compile_cache()  # tier 2: every export is also a cache entry
    buckets = resolve_buckets(buckets, floor, max_batch)
    fn = model.score_fn(pad_to=buckets, backend=backend)
    adir = os.path.join(path, f".{AOT_DIR}.staging.{os.getpid()}")
    # sweep staging debris from CRASHED earlier exports: only dirs whose
    # owning pid is gone — a concurrent live export into the same bundle
    # keeps its staging (the pid suffix exists to tell generations apart)
    try:
        for fname in os.listdir(path):
            if not fname.startswith(f".{AOT_DIR}.staging."):
                continue
            try:
                owner = int(fname.rsplit(".", 1)[-1])
                if owner != os.getpid():
                    os.kill(owner, 0)
                    continue  # owner alive: not debris
            except ValueError:
                pass  # malformed suffix: treat as debris
            except PermissionError:
                continue  # pid exists under another uid: owner alive
            except OSError:
                pass  # no such pid: debris
            shutil.rmtree(os.path.join(path, fname), ignore_errors=True)
    except OSError:
        pass
    os.makedirs(adir, exist_ok=True)
    rec = {f.name: _placeholder(f.kind) for f in fn._predictors}
    entries: list[dict] = []
    skipped: dict[tuple, str] = {}  # (lane_label, bucket) -> reason
    total_bytes = 0
    lanes = _lanes_of(fn)
    try:
        for lane in lanes:
            plan = fn._plan_for(lane)
            label = lane or "device"
            for b in buckets:
                table = fn._build_table([dict(rec)] * b)

                def on_device(idx, jit_fn, args, _label=label, _b=b,
                              _plan=plan):
                    nonlocal total_bytes
                    comp = jit_fn.lower(args).compile()
                    blob = pickle.dumps(_se.serialize(comp))
                    # round-trip check: some programs serialize but cannot
                    # be relinked (XLA-CPU "Symbols not found" on certain
                    # tiny-shape fusions, seen on save->load->resave
                    # programs). A blob that cannot round-trip HERE can
                    # never hydrate anywhere — it must not be advertised,
                    # or a compatible replica reads "hydrated" in the index
                    # yet degrades at admission.
                    try:
                        _se.deserialize_and_load(*pickle.loads(blob))
                    except Exception as ex:  # noqa: BLE001 — skip the bucket
                        skipped[(_label, _b)] = (
                            f"step {idx}: {type(ex).__name__}: {ex}"[:200])
                    else:
                        fname = _blob_name(_label, _b, idx)
                        with open(os.path.join(adir, fname), "wb") as fh:
                            fh.write(blob)
                        entries.append({"lane": _label, "bucket": _b,
                                        "step": idx, "file": fname,
                                        "bytes": len(blob)})
                        total_bytes += len(blob)
                    # the freshly compiled program IS the hydrated
                    # executable: install it so the timed passes below (and
                    # any scoring this process does next) run the exact
                    # tier-1 path
                    _plan.aot_dispatch(idx, on_fallback=fn._aot_on_fallback
                                       ).install(_b, comp)
                    return comp(args)

                out = plan.walk_device_steps(table, on_device)
                jax.block_until_ready(
                    [c.values for c in out.values() if c.is_device])
                if log is not None:
                    log(f"serving aot: exported lane={label} rows={b}")
            # one steady timed pass per bucket seeds the measured routing
            # windows the bundle ships (satellite: a hydrated replica's
            # auto_threshold starts measured, not from the cold constant)
            for b in buckets:
                fn._timed_run(plan, fn._build_table([dict(rec)] * b), lane)
        if skipped:
            # a (lane, bucket) needs EVERY step's blob to hydrate: sweep the
            # sibling blobs of any skipped pair so the index stays an exact
            # statement of what a replica can load
            kept = []
            for e in entries:
                if (e["lane"], e["bucket"]) in skipped:
                    total_bytes -= e["bytes"]
                    try:
                        os.unlink(os.path.join(adir, e["file"]))
                    except OSError:
                        pass
                else:
                    kept.append(e)
            entries = kept
            if log is not None:
                for (lab, b), why in sorted(skipped.items()):
                    log(f"serving aot: export skipped lane={lab} rows={b} "
                        f"(blob failed round-trip: {why})")
        index = {
            "version": AOT_VERSION,
            "model_uid": getattr(model, "uid", None),
            "plan_fingerprint": fingerprint,
            "stamp": compat_stamp(),
            "backend": backend,
            "lanes": [lane or "device" for lane in lanes],
            "buckets": list(buckets),
            "entries": entries,
            "skipped": [{"lane": lab, "bucket": b, "detail": why}
                        for (lab, b), why in sorted(skipped.items())],
            "lane_windows": fn.lane_windows(),
        }
        with open(os.path.join(adir, AOT_INDEX), "w") as fh:
            json.dump(index, fh, indent=1)
    except BaseException:
        # a failed export must not leave staging debris in the bundle;
        # the previous generation (if any) was never touched
        shutil.rmtree(adir, ignore_errors=True)
        raise
    if not _defer_publish:
        publish_aot(path, adir)
    wall = time.perf_counter() - t0
    reg = obs.default_registry()
    reg.counter("aot_exports_total",
                help="AOT artifact sets exported").inc()
    reg.histogram("aot_export_seconds",
                  help="wall time of AOT artifact export").observe(wall)
    obs.add_event("aot:export", fingerprint=fingerprint[:16],
                  executables=len(entries), skipped=len(skipped),
                  bytes=total_bytes, wall_s=round(wall, 3))
    report = {"status": "exported", "fingerprint": fingerprint,
              "stamp": index["stamp"], "lanes": index["lanes"],
              "buckets": list(buckets), "executables": len(entries),
              "skipped": index["skipped"], "bytes": total_bytes,
              "lane_windows": index["lane_windows"],
              "wall_s": round(wall, 3)}
    if _defer_publish:
        report["staging"] = adir
    return report


# --- hydrate --------------------------------------------------------------------------
def hydrate(fn: "ScoreFunction", model_dir: Optional[str] = None, *,
            buckets: Optional[Sequence[int]] = None, log=None) -> dict:
    """Install the bundle's AOT executables into a serving handle instead of
    tracing+compiling. Never raises: every failure class (no artifacts,
    stamp or fingerprint mismatch, corrupt blob) returns a structured
    fallback report and increments `aot_fallback_total{reason}` — the caller
    (`ScoreFunction.warm`) compiles whatever hydration did not cover.

    Returns {status: hydrated|partial|fallback, buckets_hydrated,
    executables, covered: {(lane_label, bucket), ...}, wall_s, ...}; also
    seeds the handle's routing-crossover windows from the bundle when the
    handle has no measurements of its own yet.
    """
    t0 = time.perf_counter()
    model_dir = model_dir or getattr(fn._model, "_bundle_path", None)
    if model_dir is None:
        return _fallback("absent", "handle's model has no bundle path", log=log)
    if fn._mesh is not None:
        # exported programs are single-device; sharded handles keep the
        # compile path (a partitioned program is a different executable)
        return _fallback("mesh", log=log)
    if not os.path.isdir(os.path.join(model_dir, AOT_DIR)):
        return _fallback("absent", log=log)
    index = read_index(model_dir)
    if index is None or not isinstance(index.get("entries"), list):
        return _fallback("corrupt_index", log=log)
    mismatch = _stamp_mismatch(index.get("stamp") or {})
    if mismatch is not None:
        return _fallback("stamp", mismatch, log=log)
    from ..analyze import plan_fingerprint

    try:
        live_fp = plan_fingerprint(fn._model.stages)
    except TypeError as e:
        return _fallback("unfingerprintable", str(e)[:200], log=log)
    if live_fp != index.get("plan_fingerprint"):
        return _fallback("fingerprint",
                         "artifacts were built for a different plan", log=log)

    from jax.experimental import serialize_executable as _se

    want_buckets = (sorted({int(b) for b in buckets}) if buckets
                    else [int(b) for b in index.get("buckets", [])])
    by_key = {(e["lane"], int(e["bucket"]), int(e["step"])): e
              for e in index["entries"]}
    lanes = _lanes_of(fn)
    # artifacts are keyed by lane LABELS, but validity is decided by the
    # compiled TARGET: the auto backend's primary lane is labeled "device"
    # while an explicit backend names its platform ("cpu"), yet on a host
    # whose default platform IS cpu both label the same compiled programs.
    # The stamp check above pinned the live default platform to the export
    # host's, so "device" on either side resolves to stamp["platform"] and
    # an explicit-cpu handle hydrates an auto export (and vice versa).
    stamp_platform = str((index.get("stamp") or {}).get("platform", ""))

    def _target(lbl: str) -> str:
        return stamp_platform if lbl == "device" else lbl

    index_labels = [str(lbl) for lbl in index.get("lanes", [])]
    by_target: dict[str, str] = {}
    for lbl in index_labels:
        by_target.setdefault(_target(lbl), lbl)
    covered: set = set()
    loaded_by_lane: dict[str, int] = {}
    installed: list = []  # (plan, bucket) pairs to unwind on a late error
    n_loaded = 0
    n_failed = 0
    try:
        for lane in lanes:
            label = lane or "device"
            src = (label if label in index_labels
                   else by_target.get(_target(label)))
            if src is None:
                continue
            plan = fn._plan_for(lane)
            dsteps = plan.device_step_indices()
            for b in want_buckets:
                loaded: list = []
                ok = True
                for idx in dsteps:
                    e = by_key.get((src, b, idx))
                    if e is None:
                        ok = False
                        break
                    try:
                        with open(os.path.join(model_dir, AOT_DIR,
                                               e["file"]), "rb") as fh:
                            loaded.append(
                                (idx, _se.deserialize_and_load(
                                    *pickle.loads(fh.read()))))
                    except Exception as ex:  # noqa: BLE001 — degrade per bucket
                        n_failed += 1
                        note_fallback("deserialize", f"{e['file']}: {ex}")
                        ok = False
                        break
                if not ok:
                    continue
                for idx, ex in loaded:
                    plan.aot_dispatch(
                        idx, on_fallback=fn._aot_on_fallback).install(b, ex)
                installed.append((plan, b))
                n_loaded += len(loaded)
                loaded_by_lane[label] = (loaded_by_lane.get(label, 0)
                                         + len(loaded))
                covered.add((label, b))
    except Exception as e:  # noqa: BLE001 — hydration must never kill serving
        # the report says nothing is covered, so nothing may STAY installed:
        # warm's compile path would otherwise dispatch through unvalidated
        # blobs (outside the admission guard, where an async failure raises
        # out of admission) — retire every bucket installed before the error
        for plan_, b_ in installed:
            try:
                plan_.retire_aot(b_)
            except Exception:  # noqa: BLE001 — unwind is best-effort
                pass
        return _fallback("error", f"{type(e).__name__}: {e}"[:200], log=log)

    # routing-crossover seed: a hydrated replica starts from the bundle's
    # measured per-lane windows instead of the cold static constant (only
    # when the handle has no live measurements of its own)
    if index.get("lane_windows") and not fn._lane_obs:
        fn.seed_lane_windows(index["lane_windows"])

    # coverage is judged against the LIVE routable lanes, not the lanes the
    # index happens to carry: a bundle exported for one lane admitted on a
    # host that routes two must read "partial" (warm still compiles the
    # missing lane), and a bucket counts as hydrated only when EVERY
    # routable lane loaded it — rollout tooling must never be told a bucket
    # is covered while device-lane dispatches there pay compiles
    expected_labels = [lane or "device" for lane in lanes]
    want = {(lab, b) for lab in expected_labels for b in want_buckets}
    hydrated_buckets = sorted(
        b for b in want_buckets
        if expected_labels and all((lab, b) in covered
                                   for lab in expected_labels))
    if covered and want and covered >= want:
        status = "hydrated"
    elif covered:
        status = "partial"
    elif n_failed:
        # every per-blob failure already ticked aot_fallback_total{reason=
        # "deserialize"} in the loop above — emit the event, skip the counter
        return _fallback("deserialize", "no bucket fully hydrated", log=log,
                         count_metric=False)
    else:
        return _fallback("absent", "no bucket fully hydrated", log=log)
    wall = time.perf_counter() - t0
    reg = obs.default_registry()
    for label, n in sorted(loaded_by_lane.items()):
        reg.counter(
            "aot_hydrated_total",
            help="AOT executables installed from bundle artifacts",
            labels={"lane": label}).inc(n)
    reg.histogram("aot_hydrate_seconds",
                  help="wall time of AOT artifact hydration").observe(wall)
    obs.add_event("aot:hydrate", status=status,
                  buckets=len(hydrated_buckets), executables=n_loaded,
                  wall_s=round(wall, 4))
    if log is not None:
        log(f"serving aot: {status} ({n_loaded} executables, "
            f"buckets {hydrated_buckets}, {wall * 1e3:.1f} ms)")
    return {"status": status, "fingerprint": live_fp,
            "buckets_hydrated": hydrated_buckets,
            "lanes": sorted({lab for lab, _ in covered}),
            "executables": n_loaded,
            # list of [lane_label, bucket] pairs, json-serializable (the
            # report is part of the public serve API)
            "covered": sorted([lab, b] for lab, b in covered),
            "wall_s": round(wall, 4)}
