"""Spark-free serving: `model.score_fn()` — dict in, dict out.

TPU-native analog of OpWorkflowModelLocal.scoreFunction (reference local/src/main/scala/
com/salesforce/op/local/OpWorkflowModelLocal.scala:54-154, runner
OpWorkflowRunnerLocal.scala:42). The reference needs a whole MLeap conversion layer
because its training stages are Spark-bound; here the SAME stage kernels serve — the
fitted workflow's transform plan is re-grouped into a latency-optimized LocalPlan
(serve/local.py) with the device portions jit-compiled and cached across calls.

Three serving shapes:
- `score_fn(row_dict)` — one record. With `backend="cpu"` the plan is pinned to
  host CPU-JAX in-process (no device round trip): sub-ms after warmup, the
  analog of the reference's local JVM scoring.
- `score_fn.batch(rows)` — a list of records in one fused pass.
- `score_fn.table(table)` — columnar in, columnar out: the high-throughput
  device path (no per-row dict churn; one fused result fetch via `to_list`).
- `score_fn.stream(batches)` — pipelined micro-batch scoring: host table
  build of the next batch overlaps the fused device pass of the current one
  (the shared input executor, readers/pipeline.py).
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Any, Mapping, Optional, Sequence

from ..types import Column, Table

if TYPE_CHECKING:  # pragma: no cover
    from ..workflow.workflow import WorkflowModel


class ScoreFunction:
    """Callable serving handle for a fitted WorkflowModel.

    backend: None = the process default (TPU when present); "cpu" = pin every
    jit + intermediate to host CPU-JAX in this process (`jax.default_device`),
    the low-latency single-record deployment mode.
    """

    def __init__(self, model: "WorkflowModel", result_names: Optional[Sequence[str]] = None,
                 pad_to: Optional[Sequence[int]] = None, backend: Optional[str] = None):
        self._model = model
        self._result_names = list(result_names) if result_names else [
            f.name for f in model.result_features
        ]
        self._predictors = [f for f in model.raw_features if not f.is_response]
        self._responses = [f for f in model.raw_features if f.is_response]
        #: pad batches up to these sizes to bound XLA recompilation (one compiled
        #: program per bucket, analog of serving-side shape bucketing)
        self._pad_to = sorted(pad_to) if pad_to else None
        self._backend = backend
        self._plan = None

    def _local_plan(self):
        if self._plan is None:
            from .local import LocalPlan

            device = None
            if self._backend is not None:
                import jax

                device = jax.devices(self._backend)[0]
            self._plan = LocalPlan(self._model.stages, self._result_names,
                                   device=device)
        return self._plan

    # --- single record ------------------------------------------------------------------
    def __call__(self, record: Mapping[str, Any]) -> dict[str, Any]:
        return self.batch([record])[0]

    # --- batch --------------------------------------------------------------------------
    def batch(self, records: Sequence[Mapping[str, Any]]) -> list[dict[str, Any]]:
        n = len(records)
        if n == 0:
            return []
        padded = self._pad(records)
        out = self._local_plan().run(self._build_table(padded))
        return self._rows_out(out, n)

    def _rows_out(self, out: Mapping[str, Column], n: int) -> list[dict[str, Any]]:
        results: list[dict[str, Any]] = [{} for _ in range(n)]
        for name in self._result_names:
            for i, v in enumerate(out[name].to_list()[:n]):
                results[i][name] = v
        return results

    # --- streaming ----------------------------------------------------------------------
    def stream(self, batches, *, prefetch: int = 2):
        """Pipelined batch scoring over an iterable of record batches: the
        host-side table build (+ padding) of batch k+1 runs on a producer
        thread while the fused LocalPlan program scores batch k — the serving
        face of the shared input executor (readers/pipeline.py). Yields one
        `batch()`-shaped result list per input batch, in order; results are
        bit-identical to mapping `batch()` over the same stream. `prefetch=0`
        degrades to the synchronous loop."""
        if prefetch <= 0:
            for records in batches:
                yield self.batch(records)
            return
        from ..readers.pipeline import Prefetcher

        plan = self._local_plan()  # build once, outside the timed overlap

        def prep(records):
            n = len(records)
            if n == 0:
                return 0, None
            return n, self._build_table(self._pad(records))

        with Prefetcher(batches, prep, depth=prefetch, name="serve_build") as pf:
            for n, table in pf:
                yield [] if n == 0 else self._rows_out(plan.run(table), n)

    # --- columnar -----------------------------------------------------------------------
    def table(self, table: Table) -> Table:
        """Columnar scoring: a Table holding the raw predictor columns (responses
        optional — serving is unlabeled) -> a Table of the result columns. The
        throughput path: no per-row dict building, results fetched lazily (call
        `.to_list()` on a result column for one fused device_get)."""
        cols = {f.name: table[f.name] for f in self._predictors}
        n = table.nrows
        for f in self._responses:
            if f.name in table.columns:
                cols[f.name] = table[f.name]
            else:
                cols[f.name] = Column.build(f.kind, [_placeholder(f.kind)] * n, device=False)
        out = self._local_plan().run(cols)
        return Table({n_: out[n_] for n_ in self._result_names})

    def _pad(self, records: Sequence[Mapping[str, Any]]):
        if not self._pad_to or len(records) >= self._pad_to[-1]:
            return list(records)
        target = next(b for b in self._pad_to if b >= len(records))
        filler = dict(records[0])
        return list(records) + [filler] * (target - len(records))

    def _build_table(self, records: Sequence[Mapping[str, Any]]) -> Table:
        cols = {}
        for f in self._predictors:
            try:
                vals = [r[f.name] for r in records]
            except KeyError as e:
                raise KeyError(
                    f"serving record missing predictor {f.name!r}"
                ) from e
            cols[f.name] = Column.build(f.kind, vals, device=False)
        for f in self._responses:  # placeholder labels (serving is unlabeled)
            default = _placeholder(f.kind)
            vals = [r.get(f.name, default) for r in records]
            vals = [default if v is None else v for v in vals]
            cols[f.name] = Column.build(f.kind, vals, device=False)
        return Table(cols)


def _placeholder(kind) -> Any:
    """Kind-appropriate missing-label placeholder: numerics get 0, host object kinds
    (text/lists/maps) get their natural empty value — fabricating int 0 into a Text
    column would crash downstream string stages."""
    from ..types import Storage

    st = kind.storage
    if st is Storage.TEXT:
        return None
    if st in (Storage.TEXT_LIST, Storage.DATE_LIST):
        return []
    if st is Storage.TEXT_SET:
        return frozenset()
    if st is Storage.MAP:
        return {}
    return 0


def score_function(model: "WorkflowModel", result_names: Optional[Sequence[str]] = None,
                  pad_to: Optional[Sequence[int]] = None,
                  backend: Optional[str] = None) -> ScoreFunction:
    """Build the serving callable (analog of `model.scoreFunction`)."""
    return ScoreFunction(model, result_names=result_names, pad_to=pad_to,
                         backend=backend)
