"""Spark-free serving: `model.score_fn()` — dict in, dict out.

TPU-native analog of OpWorkflowModelLocal.scoreFunction (reference local/src/main/scala/
com/salesforce/op/local/OpWorkflowModelLocal.scala:54-154, runner
OpWorkflowRunnerLocal.scala:42). The reference needs a whole MLeap conversion layer
because its training stages are Spark-bound; here the SAME stage kernels serve — the
fitted workflow's transform plan is re-grouped into a latency-optimized LocalPlan
(serve/local.py) with the device portions jit-compiled and cached across calls.

Three serving shapes:
- `score_fn(row_dict)` — one record. With `backend="cpu"` the plan is pinned to
  host CPU-JAX in-process (no device round trip): sub-ms after warmup, the
  analog of the reference's local JVM scoring.
- `score_fn.batch(rows)` — a list of records in one fused pass.
- `score_fn.table(table)` — columnar in, columnar out: the high-throughput
  device path (no per-row dict churn; one fused result fetch via `to_list`).
- `score_fn.stream(batches)` — pipelined micro-batch scoring: host table
  build of the next batch overlaps the fused device pass of the current one
  (the shared input executor, readers/pipeline.py).
"""
from __future__ import annotations

from collections import deque
from typing import TYPE_CHECKING, Any, Mapping, Optional, Sequence

from ..resilience.lockcheck import make_rlock
from ..types import Column, Table

if TYPE_CHECKING:  # pragma: no cover
    from ..workflow.workflow import WorkflowModel


#: batches strictly below this row count route to the CPU columnar plan under
#: backend="auto": BENCH_r05 measured 101.55 ms single-row on the (tunneled)
#: device vs 0.307 ms on host CPU-JAX — a device round trip only pays for
#: itself when the batch amortizes it. This constant is only the COLD
#: fallback: once both lanes carry `CROSSOVER_MIN_OBS` measured latencies the
#: router derives the crossover from them (`ScoreFunction.auto_threshold`).
AUTO_CPU_THRESHOLD = 256

#: observations per lane before the measured crossover replaces the constant
CROSSOVER_MIN_OBS = 8

#: handle-local (latency, rows) window per lane feeding the crossover — kept
#: on the handle, NOT read back from the registry, so one model's routing
#: never keys off another model's (or another test's) numbers
_LANE_WINDOW = 128


class ScoreFunction:
    """Callable serving handle for a fitted WorkflowModel.

    backend: "auto" (default) = route by batch size — batches below
    `auto_cpu_threshold` rows run on the in-process host CPU-JAX plan (the
    sub-ms single-record path), larger ones on the process-default device;
    each decision is recorded as a `serve:routing` event on the active trace
    span. None = always the process default (TPU when present); "cpu" = pin
    every jit + intermediate to host CPU-JAX (`jax.default_device`). Explicit
    values are always respected — no routing happens unless backend="auto".

    mesh: optional device mesh — batches whose rows divide its data axis (and
    that routed to the device plan) are placed row-sharded before the fused
    pass, so the scoring program partitions across chips.
    """

    def __init__(self, model: "WorkflowModel", result_names: Optional[Sequence[str]] = None,
                 pad_to: Optional[Sequence[int]] = None,
                 backend: Optional[str] = "auto",
                 auto_cpu_threshold: int = AUTO_CPU_THRESHOLD,
                 mesh=None, monitor=None, policy=None,
                 model_label: Optional[str] = None, quality=None):
        self._model = model
        self._result_names = list(result_names) if result_names else [
            f.name for f in model.result_features
        ]
        self._predictors = [f for f in model.raw_features if not f.is_response]
        self._responses = [f for f in model.raw_features if f.is_response]
        #: pad batches up to these sizes to bound XLA recompilation (one compiled
        #: program per bucket, analog of serving-side shape bucketing)
        self._pad_to = sorted(pad_to) if pad_to else None
        self._backend = backend
        self._auto_cpu_threshold = int(auto_cpu_threshold)
        self._mesh = mesh
        #: drift monitor (obs/monitor.py). monitor=True builds one from the
        #: model's stamped serving_baseline; a ServingMonitor instance is used
        #: as-is; None/False disables. Batches fold into its streaming
        #: sketches BEFORE padding (filler rows must not skew fill rates).
        if monitor is True:
            from ..obs.monitor import ServingMonitor

            monitor = ServingMonitor.for_model(model)
        self.monitor = monitor or None
        #: model-quality plane (serve/feedback.QualityPlane). When armed,
        #: every result row from batch()/_rows_out gains a "prediction_id"
        #: key and is audited + pending-noted for the label-feedback join.
        #: None (the default) leaves result rows byte-identical to before —
        #: the plane is strictly opt-in.
        self.quality = quality or None
        #: metric label for this handle's model: daemon admissions pass the
        #: served model name; the default is the model uid (one bounded
        #: series per served model)
        self._model_label = str(model_label or getattr(model, "uid", "model"))
        self._plans: dict = {}  # backend key -> LocalPlan
        #: guards every lazily-built structure on the handle (plans, cached
        #: instruments, lane latency windows, the quarantine writer):
        #: concurrent callers — the serving daemon's batcher worker plus any
        #: direct batch()/table() traffic — must not race the get-or-create
        #: paths into duplicate LocalPlans (= duplicate jit programs)
        self._lock = make_rlock("ScoreFunction._lock")
        #: registry instruments cached per backend lane: get-or-create
        #: freezes/sorts labels under the registry lock — measurable at
        #: per-record serving frequency (same policy as ServingMonitor._gauge)
        self._route_counters: dict = {}
        self._lat_hists: dict = {}
        #: handle-local crossover inputs: {lane: deque[(latency_s, rows)]},
        #: monotone observation counts, and the cached derived threshold
        self._lane_lat: dict = {}
        self._lane_obs: dict = {}
        self._thr_cache: tuple = (None, 0)
        #: resilience.FaultPolicy: deadline_s arms per-dispatch deadlines on
        #: the device lane, breaker_threshold/cooldown configure the circuit
        #: breaker, quarantine_dir enables poison-row quarantine in stream().
        #: None = defaults (breaker on under "auto", everything else off).
        self._policy = policy
        #: device circuit breaker — only under backend="auto", the one mode
        #: with an in-process CPU plan to fail over to. Consecutive device-
        #: lane failures (or deadline breaches) trip it OPEN: every batch
        #: routes to the CPU columnar plan (decided="breaker") until a
        #: half-open probe succeeds. Explicitly pinned backends are never
        #: silently rerouted. Tests may swap in a breaker with a fake clock.
        self._breaker = None
        if backend == "auto":
            from ..resilience import CircuitBreaker, FaultPolicy

            pol = policy if policy is not None else FaultPolicy()
            # per-MODEL metric series: handles for different models must not
            # merge their failures/transitions into one "serve_device" line
            # (bounded cardinality — one series per served model)
            self._breaker = CircuitBreaker(
                threshold=pol.breaker_threshold,
                cooldown_s=pol.breaker_cooldown_s,
                name=f"serve_device:{getattr(model, 'uid', 'model')}")
        self._qwriter = None
        import itertools

        #: stream() batch ordinal, monotone across calls on this handle (the
        #: quarantine sidecar's "batch" field must be unambiguous)
        self._stream_counter = itertools.count()
        #: AOT hydration state (serve/aot.py): set by warm(aot=)/hydrate;
        #: None until a hydration was attempted. fallback_compiles counts
        #: dispatches that missed the hydrated executable table and fell
        #: back to the jit path (an unwarmed shape on the hot path).
        self._aot: Optional[dict] = None
        # routing-crossover seed: a model bundle carrying measured per-lane
        # (latency, rows) windows (WorkflowModel.save) hands them to every
        # new handle, so auto_threshold() is measured-quality from request #1
        persisted = getattr(model, "serving_lane_windows", None)
        if persisted:
            self.seed_lane_windows(persisted)

    def _plan_for(self, backend: Optional[str]):
        key = backend or "default"
        with self._lock:
            plan = self._plans.get(key)
            if plan is None:
                from .local import LocalPlan

                device = None
                if backend is not None:
                    import jax

                    device = jax.devices(backend)[0]
                plan = self._plans[key] = LocalPlan(
                    self._model.stages, self._result_names, device=device)
        return plan

    def _route(self, n_rows: int):
        """-> (LocalPlan, backend label). Under "auto", small batches take the
        CPU columnar path; the decision lands on the score trace span AND the
        metrics registry (`serve_routing_total{backend,decided}`)."""
        from .. import obs

        if self._backend != "auto":
            backend = self._backend
            decided = "explicit"
        else:
            import jax

            default_is_cpu = jax.devices()[0].platform == "cpu"
            backend = ("cpu" if not default_is_cpu
                       and n_rows < self.auto_threshold() else None)
            decided = "auto"
            if (backend is None and self._breaker is not None
                    and not self._breaker.allow()):
                # breaker open: the device lane is failing — the whole stream
                # of traffic takes the in-process CPU plan until a half-open
                # probe restores the device path
                backend = "cpu"
                decided = "breaker"
        obs.add_event("serve:routing", backend=backend or "device",
                      rows=int(n_rows), decided=decided)
        key = (backend or "device", decided)
        with self._lock:
            c = self._route_counters.get(key)
            if c is None:
                c = self._route_counters[key] = obs.default_registry().counter(
                    "serve_routing_total",
                    help="serving batches routed per backend lane",
                    labels={"backend": key[0], "decided": decided})
        c.inc()
        return self._plan_for(backend), backend

    def lane_windows(self) -> dict:
        """JSON-able snapshot of the handle-local (latency_s, rows) windows
        feeding `auto_threshold()` — what `WorkflowModel.save` persists into
        the bundle so a hydrated replica starts with measured routing."""
        with self._lock:
            return {lane: [[float(d), int(r)] for d, r in win]
                    for lane, win in self._lane_lat.items() if win}

    def seed_lane_windows(self, windows: Optional[Mapping]) -> None:
        """Pre-populate the per-lane latency windows (the inverse of
        `lane_windows`): a bundle's persisted measurements — or a previous
        handle's — become this handle's crossover inputs, so routing
        decisions are measured-quality before the first live dispatch."""
        if not windows:
            return
        with self._lock:
            for lane, win in windows.items():
                if not win:
                    continue
                lane = str(lane)
                dq = self._lane_lat.get(lane)
                if dq is None:
                    dq = self._lane_lat[lane] = deque(maxlen=_LANE_WINDOW)
                for d, r in win:
                    dq.append((float(d), int(r)))
                self._lane_obs[lane] = self._lane_obs.get(lane, 0) + len(win)
            self._thr_cache = (None, 0)

    def _aot_on_fallback(self, rows: int) -> None:
        """A dispatch missed the hydrated executable table (unwarmed shape
        or a retired blob) and took the jit path — count it so rollout
        tooling can tell a fully-hydrated replica from a limping one."""
        from .. import obs

        with self._lock:
            if self._aot is not None:
                self._aot["fallback_compiles"] = (
                    self._aot.get("fallback_compiles", 0) + 1)
        obs.default_registry().counter(
            "aot_fallback_compiles_total",
            help="serving dispatches that missed the hydrated AOT "
                 "executable table and fell back to the jit path").inc()

    def aot_status(self) -> Optional[dict]:
        """Hydration summary for health surfaces: {status, buckets_hydrated,
        fallback_compiles, ...} once a hydration was attempted, else None."""
        with self._lock:
            return dict(self._aot) if self._aot is not None else None

    def auto_threshold(self) -> int:
        """The routing crossover in rows: batches below it take the CPU plan
        under backend="auto". Derived from this handle's MEASURED lane
        latencies — device-lane p50 divided by the CPU lane's per-row cost
        over a bounded recent window — once both lanes carry
        `CROSSOVER_MIN_OBS` observations; until then (and whenever the
        measurements degenerate) the static `auto_cpu_threshold` constant
        holds. Cached and recomputed every 16 device-lane observations so the
        per-record routing path never sorts the window."""
        import math

        with self._lock:
            dev = self._lane_lat.get("device")
            cpu = self._lane_lat.get("cpu")
            if (dev is None or cpu is None or len(dev) < CROSSOVER_MIN_OBS
                    or len(cpu) < CROSSOVER_MIN_OBS):
                return self._auto_cpu_threshold
            thr, at_obs = self._thr_cache
            n_dev = self._lane_obs.get("device", 0)
            if thr is not None and n_dev - at_obs < 16:
                return thr
            cpu_s = sum(d for d, _ in cpu)
            cpu_rows = sum(r for _, r in cpu)
            if cpu_s <= 0.0 or cpu_rows <= 0:
                return self._auto_cpu_threshold
            per_row = cpu_s / cpu_rows
            dev_sorted = sorted(d for d, _ in dev)
            dev_p50 = dev_sorted[len(dev_sorted) // 2]
            # a warmed device lane pulls the crossover DOWN (coalesced
            # micro-batches start paying for the device); a cold/tunneled
            # one pushes it up past the static default
            thr = max(1, min(1 << 16, int(math.ceil(dev_p50 / per_row))))
            self._thr_cache = (thr, n_dev)
            return thr

    def _timed_run(self, plan, table, backend: Optional[str]):
        """plan.run with the per-backend latency histogram
        (`serve_latency_seconds{backend,model}`: log buckets + exact
        p50/p95/p99). The observe is a few µs under one lock — noise against
        even the sub-ms CPU single-record path. On the device lane this is
        also where the chaos harness's dispatch faults land and where a
        configured per-dispatch deadline is enforced. Each pass also lands in
        the handle-local lane window that feeds `auto_threshold()`."""
        import time

        from .. import obs

        t0 = time.perf_counter()
        out = self._dispatch(plan, table, backend)
        dt = time.perf_counter() - t0
        key = backend or "device"
        with self._lock:
            h = self._lat_hists.get(key)
            if h is None:
                h = self._lat_hists[key] = obs.default_registry().histogram(
                    "serve_latency_seconds",
                    help="LocalPlan scoring latency per backend lane and "
                         "served model",
                    labels={"backend": key, "model": self._model_label})
            lane = self._lane_lat.get(key)
            if lane is None:
                lane = self._lane_lat[key] = deque(maxlen=_LANE_WINDOW)
            lane.append((dt, _n_rows_of(table)))
            self._lane_obs[key] = self._lane_obs.get(key, 0) + 1
        h.observe(dt)
        return out

    def _dispatch(self, plan, table, backend: Optional[str]):
        """One plan.run on its lane. The device lane (backend != "cpu")
        consults the chaos injector and, when `policy.deadline_s` is set,
        runs under a per-dispatch deadline: the result fetch is forced on a
        worker thread and a breach raises DeadlineExceeded (counted as a
        breaker failure by the caller) instead of wedging the replica."""
        if backend != "cpu":
            from ..resilience import chaos

            chaos.maybe_device("serve:dispatch")
        pol = self._policy
        if pol is not None and pol.deadline_s and backend != "cpu":
            import jax

            from ..resilience.policy import call_with_deadline

            def run_and_block():
                out = plan.run(table)
                # the deadline covers EXECUTION, not just the async enqueue:
                # block on the result buffers inside the guarded thread
                jax.block_until_ready([c.values for c in out.values()])
                return out

            return call_with_deadline(run_and_block,
                                      deadline_s=pol.deadline_s,
                                      site="serve:dispatch")
        return plan.run(table)

    def _release_probe(self, backend: Optional[str]) -> None:
        """Idempotent: clear a half-open probe slot this request may hold on
        the device lane (no-op for cpu-routed requests or without a
        breaker)."""
        if backend != "cpu" and self._breaker is not None:
            self._breaker.abort_probe()

    def _run_with_failover(self, plan, table, backend: Optional[str],
                           fallback_table=None):
        """_timed_run plus breaker bookkeeping and CPU failover.

        Only the auto-routed device lane is protected: a failure there
        (dispatch error, deadline breach) records on the breaker and the
        batch transparently re-runs on the in-process CPU plan — the request
        succeeds, availability is preserved, and once `breaker_threshold`
        consecutive failures accumulate the breaker opens and routing stops
        even trying the device. Explicit backends keep fail-fast semantics.
        DATA errors (ValueError/KeyError/... — a poison row) are re-raised
        untouched: they would fail identically on the CPU plan, and counting
        them on the breaker would let bad client data evict a healthy device
        (the transient-vs-data rule of resilience/policy.py).
        """
        protected = (backend != "cpu" and self._breaker is not None
                     and self._backend == "auto")
        try:
            out = self._timed_run(plan, table, backend)
        except Exception as e:  # noqa: BLE001 — classified below
            if not protected:
                raise
            if isinstance(e, (ValueError, KeyError, TypeError, IndexError)):
                # inconclusive for the LANE: if this batch was the half-open
                # probe, release the probe slot so the breaker cannot wedge
                # in HALF_OPEN on poison data
                self._breaker.abort_probe()
                raise
            self._breaker.record_failure()
            from .. import obs

            obs.add_event("serve:failover", error=f"{type(e).__name__}: {e}"[:200])
            obs.default_registry().counter(
                "serve_failover_total",
                help="device-lane batches re-run on the CPU plan after a "
                     "dispatch failure or deadline breach").inc()
            cpu_plan = self._plan_for("cpu")
            t = fallback_table if fallback_table is not None else table
            return self._timed_run(cpu_plan, t, "cpu")
        if protected:
            self._breaker.record_success()
        return out

    def _observe(self, table_or_cols, n: int) -> None:
        """Fold a scoring batch into the drift monitor (no-op without one;
        never raises — the monitor owns its error counter)."""
        if self.monitor is None:
            return
        if isinstance(table_or_cols, Table):
            self.monitor.observe_table(table_or_cols, n=n)
        else:
            self.monitor.observe_columns(table_or_cols, n=n)

    def _local_plan(self):
        # back-compat surface (tests/tools introspect it): the device-lane plan
        return self._plan_for(None if self._backend == "auto" else self._backend)

    def _maybe_shard(self, table_or_cols, n_rows: int, backend: Optional[str]):
        """Row-shard numeric columns over the mesh data axis for large
        device-lane batches (pre-sharded inputs partition the fused pass)."""
        if self._mesh is None or backend is not None:
            return table_or_cols
        from ..mesh import DATA_AXIS

        n_data = int(self._mesh.shape[DATA_AXIS])
        if n_data <= 1 or n_rows < n_data or n_rows % n_data != 0:
            return table_or_cols
        from ..workflow.runner import shard_table_rows

        if isinstance(table_or_cols, Table):
            return shard_table_rows(self._mesh, table_or_cols)
        sharded = shard_table_rows(self._mesh, Table(dict(table_or_cols)))
        return {n: sharded[n] for n in sharded.names()}

    # --- warmup -------------------------------------------------------------------------
    def warm(self, buckets: Optional[Sequence[int]] = None,
             observe: bool = True, log=None, aot: object = "auto") -> dict:
        """Make every per-bucket serving executable on every routable lane
        ready, so the first real dispatch at any warmed shape compiles
        nothing (`retrace_budget(0)`-clean steady state from request one).
        `op warmup --serving` and daemon model admission both land here (via
        `warmup.warm_serving_handle`) — the SAME helper, so a deploy-time
        warmup primes exactly the executables admission will build.

        AOT-first: with `aot` enabled (default "auto") and the handle's model
        carrying a saved bundle with compatible artifacts (serve/aot.py), the
        pre-compiled executables are DESERIALIZED instead of built —
        milliseconds instead of seconds, zero XLA work — and the bundle's
        persisted routing windows seed `auto_threshold()`. Buckets/lanes the
        artifacts do not cover (and every bucket when artifacts are stale,
        incompatible, or absent) degrade to the compile loop: a cold pass
        that traces+compiles against throwaway synthetic buffers
        (kind-appropriate placeholder values — shapes depend only on the row
        count and the fitted schema, never on values), then — with
        `observe=True` — a steady timed pass through the latency histograms,
        seeding the measured crossover with warm per-lane numbers.
        Returns {buckets, lanes, programs, wall_s} plus "aot" when a
        hydration was attempted ("programs" counts COMPILED buckets only —
        0 on a fully hydrated handle)."""
        import time

        import jax

        import numpy as _np

        t0 = time.perf_counter()
        buckets = sorted({int(b) for b in (buckets or self._pad_to or (1,))})
        rec = {f.name: _placeholder(f.kind) for f in self._predictors}
        # one synthetic table at the largest bucket, sliced per bucket: the
        # row-wise python table build is measurable against a hydrated warm
        # (every pass is milliseconds) and identical rows slice exactly
        big = self._build_table([dict(rec)] * buckets[-1])

        def synth(b: int):
            return big if b == buckets[-1] else big.slice(_np.arange(b))

        from .aot import _lanes_of

        lanes = _lanes_of(self)  # shared with export/hydrate: never drifts
        covered: set = set()
        hyd = None
        if aot and getattr(self._model, "_bundle_path", None):
            # meshed handles land in hydrate's own "mesh" fallback — the
            # degrade is counted and surfaces in the report//healthz instead
            # of hydration silently never being attempted
            from .aot import hydrate

            hyd = hydrate(self, buckets=buckets, log=log)
            covered = {(lab, int(b))
                       for lab, b in hyd.pop("covered", [])}
            with self._lock:
                self._aot = {k: v for k, v in hyd.items()}
                self._aot.setdefault("fallback_compiles", 0)
        programs = 0
        for lane in lanes:
            plan = self._plan_for(lane)
            for b in buckets:
                label = lane or "device"
                if (label, b) in covered:
                    # hydrated bucket: one validation pass exercises every
                    # installed executable end to end BEFORE traffic arrives
                    # (a blob that deserialized but fails at call time is
                    # retired here, at admission, not on the first live
                    # request) and — timed — populates the latency
                    # histograms/windows with numbers from THIS host. The
                    # programs are pre-compiled, so this is milliseconds.
                    # block_until_ready: on an async backend the failure
                    # surfaces at the fetch, not the enqueue — validation
                    # must materialize the results or it validates nothing.
                    # The admission guard reroutes sync call-time failures
                    # (caught+retired inside _AotDispatch) away from the
                    # hot-path "limping replica" counter into `vfails`.
                    try:
                        with plan.aot_admission_guard() as vfails:
                            if observe:
                                out = self._timed_run(plan, synth(b), lane)
                            else:
                                out = plan.run(synth(b))
                            jax.block_until_ready(
                                [c.values for c in out.values()
                                 if c.is_device])
                        if vfails:
                            raise RuntimeError(
                                "executable retired at call time")
                        continue
                    except Exception as e:  # noqa: BLE001 — retire, recompile
                        # an executable that deserialized but cannot RUN
                        # (async failures land here via the fetch; sync ones
                        # via the guard): retire the bucket's blobs, demote
                        # the handle's status, and fall through to the
                        # compile path — warm never raises over a bad
                        # artifact, and /healthz must not keep calling the
                        # bucket hydrated. Retire on EVERY routable lane,
                        # not just the failing one: the demotion below is
                        # handle-wide, and no lane may keep serving this
                        # bucket from blobs while the report says it is not
                        # hydrated.
                        for lane2 in lanes:
                            label2 = lane2 or "device"
                            if lane2 != lane and (label2, b) not in covered:
                                continue
                            plan2 = self._plan_for(lane2)
                            plan2.retire_aot(b)
                            covered.discard((label2, b))
                            if lanes.index(lane2) < lanes.index(lane):
                                # that lane's loop already passed: re-cover
                                # via the compile path now, or its first
                                # live dispatch at b pays (and counts) a
                                # hot-path compile
                                plan2.mark_warmed(b)
                                out2 = plan2.run(synth(b))
                                jax.block_until_ready(
                                    [c.values for c in out2.values()])
                                programs += 1
                        with self._lock:
                            if self._aot is not None:
                                bh = self._aot.get("buckets_hydrated") or []
                                bh = [x for x in bh if x != b]
                                self._aot["buckets_hydrated"] = bh
                                if not bh:
                                    # every hydrated bucket retired: the
                                    # replica is 100% on the compile path
                                    # and must not read as partially covered
                                    self._aot["status"] = "fallback"
                                    self._aot.setdefault("reason", "error")
                                elif self._aot.get("status") == "hydrated":
                                    self._aot["status"] = "partial"
                        from .aot import note_fallback

                        note_fallback(
                            "error",
                            f"validation lane={label} rows={b}: "
                            f"{type(e).__name__}: {e}")
                        if log is not None:
                            log(f"serving aot: retired lane={label} rows={b} "
                                f"(validation failed: {type(e).__name__})")
                # compiled-not-hydrated shapes are healthy steady state: on a
                # partially hydrated plan they must not tick the
                # fallback-compile ("limping replica") counter per dispatch
                plan.mark_warmed(b)
                out = plan.run(synth(b))
                jax.block_until_ready([c.values for c in out.values()])
                programs += 1
                if observe:
                    self._timed_run(plan, synth(b), lane)
                if log is not None:
                    log(f"serving warm: lane={lane or 'device'} rows={b}")
        report = {"buckets": buckets,
                  "lanes": [lane or "device" for lane in lanes],
                  "programs": programs,
                  "wall_s": round(time.perf_counter() - t0, 3)}
        if hyd is not None:
            # the live status, not the raw hydrate report: a bucket retired
            # by the validation passes above must not read as hydrated
            report["aot"] = self.aot_status() or hyd
        return report

    def breaker_state(self) -> Optional[str]:
        """Circuit-breaker state of the device lane ("closed"/"open"/
        "half_open"), or None when no breaker is armed (explicit backends)."""
        return self._breaker.state if self._breaker is not None else None

    # --- single record ------------------------------------------------------------------
    def __call__(self, record: Mapping[str, Any]) -> dict[str, Any]:
        return self.batch([record])[0]

    # --- batch --------------------------------------------------------------------------
    def batch(self, records: Sequence[Mapping[str, Any]]) -> list[dict[str, Any]]:
        n = len(records)
        if n == 0:
            return []
        padded = self._pad(records)
        # route on the REAL row count: pad_to bucketing must not flip a
        # 4-row request onto the device lane just because its bucket is big
        plan, backend = self._route(n)
        try:
            table = self._build_table(padded)
            self._observe(table, n)
            sharded = self._maybe_shard(table, len(padded), backend)
            # failover re-runs on the CPU plan with the UNSHARDED table: a
            # batch spread over a failing mesh must not be handed back to it
            out = self._run_with_failover(plan, sharded, backend,
                                          fallback_table=table)
        except BaseException:
            # anything that dies after routing — a parse error in the table
            # build included — must release a half-open probe slot this
            # request may hold, or the breaker wedges in HALF_OPEN
            self._release_probe(backend)
            raise
        return self._rows_out(out, n)

    def _rows_out(self, out: Mapping[str, Column], n: int) -> list[dict[str, Any]]:
        results: list[dict[str, Any]] = [{} for _ in range(n)]
        for name in self._result_names:
            for i, v in enumerate(out[name].to_list()[:n]):
                results[i][name] = v
        if self.quality is not None:
            # ids ride IN the row dicts, so they survive the MicroBatcher's
            # demux slicing and reach each caller positionally intact
            ids = self.quality.on_scored(results)
            for row, pid in zip(results, ids):
                if pid is not None:
                    row["prediction_id"] = pid
        return results

    # --- streaming ----------------------------------------------------------------------
    def _quarantine_writer(self):
        """Lazy QuarantineWriter when the policy carries a quarantine_dir
        (shared across stream() calls on this handle), else None."""
        pol = self._policy
        if pol is None or not pol.quarantine_dir:
            return None
        with self._lock:
            if self._qwriter is None:
                from ..resilience import QuarantineWriter

                self._qwriter = QuarantineWriter(pol.quarantine_dir)
        return self._qwriter

    def quarantine_summary(self) -> Optional[dict]:
        """Partial-success summary of rows shed by stream() (None when
        quarantine is off or nothing was quarantined)."""
        with self._lock:  # vs the lazy create in _quarantine_writer
            qw = self._qwriter
        return qw.summary() if qw is not None else None

    def close(self) -> None:
        """Release the handle's quarantine sidecar file handle (idempotent;
        records already written are flushed per write, so close is about
        descriptor hygiene in long-lived serving processes, not durability).
        """
        with self._lock:
            qw = self._qwriter
        if qw is not None:
            qw.close()

    def stream(self, batches, *, prefetch: int = 2):
        """Pipelined batch scoring over an iterable of record batches: the
        host-side table build (+ padding) of batch k+1 runs on a producer
        thread while the fused LocalPlan program scores batch k — the serving
        face of the shared input executor (readers/pipeline.py). Yields one
        `batch()`-shaped result list per input batch, in order; results are
        bit-identical to mapping `batch()` over the same stream. `prefetch=0`
        degrades to the synchronous loop.

        With a `FaultPolicy(quarantine_dir=...)` on the handle, a poison
        batch no longer kills the stream: rows that fail parse/cast or
        scoring are isolated by row-bisect and written to the sidecar, rows
        whose scores come back non-finite are shed likewise, and the yielded
        list keeps ONE entry per input row — quarantined positions hold None
        (explicitly absent, never silently dropped). `quarantine_summary()`
        reports the partial-success totals."""
        from ..resilience.chaos import corrupt_batch

        qw = self._quarantine_writer()
        # the batch ordinal is PER HANDLE, not per stream() call: the
        # quarantine sidecar (and its distinct-batch accounting) is shared
        # across calls, so "batch" fields must stay unique across them
        counter = self._stream_counter

        # items flow prep -> place -> score as a FIXED 5-tuple
        # (n, table, route, ctx, fallback): place replaces `table` with its
        # sharded form and fills `fallback` with the unsharded original, so
        # no stage sniffs tuple arity to learn what ran before it
        def prep(records):
            bidx = next(counter)
            records = corrupt_batch(list(records), bidx)
            n = len(records)
            if n == 0:
                return 0, None, None, None, None
            keep = None
            try:
                padded = self._pad(records)
                table = self._build_table(padded)
            except Exception:  # noqa: BLE001 — quarantine or re-raise
                if qw is None:
                    raise
                from ..resilience import isolate_failing

                good, bad = isolate_failing(
                    n, lambda idx: self._build_table([records[i] for i in idx]))
                qw.quarantine_rows([records[i] for i, _ in bad],
                                   batch_index=bidx, stage="parse",
                                   errors=[err for _, err in bad],
                                   row_indices=[i for i, _ in bad])
                keep = good
                records = [records[i] for i in good]
                if not records:
                    return 0, None, None, {"orig_n": n, "keep": [],
                                           "batch": bidx, "records": []}, None
                orig_n, n = n, len(records)
                padded = self._pad(records)
                table = self._build_table(padded)
            plan, backend = self._route(n)  # real rows, not the pad bucket
            # drift sketches fold on the PRODUCER thread: the numpy histogram
            # pass overlaps the device scoring of the previous batch instead
            # of extending the critical path
            self._observe(table, n)
            ctx = None
            if qw is not None:
                ctx = {"orig_n": n if keep is None else orig_n, "keep": keep,
                       "batch": bidx, "records": records}
            return n, table, (plan, backend, len(padded)), ctx, None

        def place(item):
            # producer-thread device placement: under a mesh, device-lane
            # batches land PRE-SHARDED over the data axis while the fused
            # pass still scores the previous batch. The UNSHARDED table rides
            # along as the failover fallback: a batch spread over a failing
            # mesh must not be handed back to it on the CPU lane.
            n, table, route, ctx, _ = item
            if route is None:
                return item
            plan, backend, n_padded = route
            return (n, self._maybe_shard(table, n_padded, backend), route,
                    ctx, table)

        def score(item):
            n, table, route, ctx, fallback = item
            if n == 0:
                return ([None] * ctx["orig_n"]) if ctx is not None else []
            plan, backend, n_padded = route
            try:
                rows = self._rows_out(
                    self._run_with_failover(plan, table, backend,
                                            fallback_table=fallback), n)
                positions = (ctx["keep"] if ctx is not None
                             and ctx["keep"] is not None else list(range(n)))
            except Exception:  # noqa: BLE001 — quarantine or re-raise
                if ctx is None:
                    raise
                rows, positions = self._bisect_score(ctx, qw)
            if ctx is None:
                return rows
            return self._shed_nonfinite(rows, positions, ctx, qw)

        # plans build once, outside the timed overlap (the CPU failover lane
        # only pre-builds when it differs from the default lane — on a
        # CPU-only host it would be a duplicate plan; the breaker path still
        # builds it lazily on demand via _plan_for's cache)
        if self._backend == "auto":
            self._plan_for(None)
            import jax

            if jax.devices()[0].platform != "cpu":
                self._plan_for("cpu")
        else:
            self._local_plan()

        try:
            if prefetch <= 0:
                # the sync path runs the SAME three stages — place() for mesh
                # sharding, plus the shared producer-stage wrapper (chaos
                # slow hook + policy retry) — so prefetch=0 and the pipelined
                # path diverge in nothing but threading
                from ..resilience.policy import resilient_prepare

                for index, records in enumerate(batches):
                    yield score(place(resilient_prepare(
                        prep, records, index, self._policy,
                        "pipeline:serve_build")))
                return
            from ..readers.pipeline import Prefetcher

            with Prefetcher(batches, prep, depth=prefetch, name="serve_build",
                            place=place, policy=self._policy) as pf:
                # serving's pipeline series carry role="serve" in the fleet
                # view regardless of this process's TT_ROLE (a daemon also
                # hosts training pipelines whose series keep the process role)
                pf.stats.role = "serve"
                for item in pf:
                    # bare-Prefetcher use: the consumer owns the batch count
                    # (run_pipeline's loop does this for the runner), so
                    # close()-time stats.publish() has real totals to fold
                    pf.stats.batches += 1
                    yield score(item)
        finally:
            # a stream torn down between prep()'s routing (which may have
            # been admitted as the half-open probe) and its score() must not
            # strand the probe slot — idempotent release on ANY exit. If a
            # concurrent batch() on this handle holds the probe, this may
            # re-admit a second prober — a rare, self-correcting flap,
            # strictly better than the permanent HALF_OPEN wedge.
            self._release_probe(None)

    def _bisect_score(self, ctx: dict, qw):
        """Score-time poison isolation: probe row subsets, quarantine the
        minimal failing rows, score the survivors once as a clean batch.
        Under backend="auto" probes run on the CPU plan (failover already
        failed to get here, which means data poison, and poison reproduces
        anywhere); an explicitly pinned backend keeps its own lane — pinned
        handles are never silently rerouted, so survivors' numerics come
        from the lane the caller chose."""
        from ..resilience import isolate_failing

        recs = ctx["records"]
        plan = self._plan_for("cpu" if self._backend == "auto"
                              else self._backend)

        def probe(idx):
            # probes pad like real traffic: O(bad*log n) novel row counts
            # must not each compile a fresh program on a pad_to-bucketed
            # handle (the runner-side bisect pads for the same reason)
            plan.run(self._build_table(self._pad([recs[i] for i in idx])))

        good, bad = isolate_failing(len(recs), probe)
        base = (ctx["keep"] if ctx["keep"] is not None
                else list(range(len(recs))))
        qw.quarantine_rows([recs[i] for i, _ in bad], batch_index=ctx["batch"],
                           stage="score", errors=[err for _, err in bad],
                           row_indices=[base[i] for i, _ in bad])
        if not good:
            return [], []
        out = plan.run(self._build_table(self._pad(
            [recs[i] for i in good])))
        return self._rows_out(out, len(good)), [base[i] for i in good]

    def _shed_nonfinite(self, rows: list, positions: list, ctx: dict, qw
                        ) -> list:
        """Assemble the per-input-row result list: scored rows land at their
        original positions, quarantined positions hold None. Rows whose
        scores are non-finite (NaN/Inf anywhere in the result payload — a
        poison row that parsed fine but produced garbage) are shed here."""
        finite_rows, finite_pos, bad = [], [], []
        for row, pos in zip(rows, positions):
            if _row_nonfinite(row):
                bad.append(pos)
            else:
                finite_rows.append(row)
                finite_pos.append(pos)
        if bad:
            recs = ctx["records"]
            base = (ctx["keep"] if ctx["keep"] is not None
                    else list(range(len(recs))))
            back = {p: i for i, p in enumerate(base)}
            qw.quarantine_rows([recs[back[p]] for p in bad],
                               batch_index=ctx["batch"], stage="nonfinite",
                               row_indices=list(bad))
        out: list = [None] * ctx["orig_n"]
        for row, pos in zip(finite_rows, finite_pos):
            out[pos] = row
        return out

    # --- columnar -----------------------------------------------------------------------
    def table(self, table: Table) -> Table:
        """Columnar scoring: a Table holding the raw predictor columns (responses
        optional — serving is unlabeled) -> a Table of the result columns. The
        throughput path: no per-row dict building, results fetched lazily (call
        `.to_list()` on a result column for one fused device_get)."""
        cols = {f.name: table[f.name] for f in self._predictors}
        n = table.nrows
        for f in self._responses:
            if f.name in table.columns:
                cols[f.name] = table[f.name]
            else:
                cols[f.name] = Column.build(f.kind, [_placeholder(f.kind)] * n, device=False)
        plan, backend = self._route(n)
        try:
            self._observe(cols, n)
            sharded = self._maybe_shard(cols, n, backend)
            out = self._run_with_failover(plan, sharded, backend,
                                          fallback_table=cols)
        except BaseException:
            self._release_probe(backend)
            raise
        return Table({n_: out[n_] for n_ in self._result_names})

    def _pad(self, records: Sequence[Mapping[str, Any]]):
        if not self._pad_to or len(records) >= self._pad_to[-1]:
            return list(records)
        target = next(b for b in self._pad_to if b >= len(records))
        filler = dict(records[0])
        return list(records) + [filler] * (target - len(records))

    def _build_table(self, records: Sequence[Mapping[str, Any]]) -> Table:
        cols = {}
        for f in self._predictors:
            try:
                vals = [r[f.name] for r in records]
            except KeyError as e:
                raise KeyError(
                    f"serving record missing predictor {f.name!r}"
                ) from e
            cols[f.name] = Column.build(f.kind, vals, device=False)
        for f in self._responses:  # placeholder labels (serving is unlabeled)
            default = _placeholder(f.kind)
            vals = [r.get(f.name, default) for r in records]
            vals = [default if v is None else v for v in vals]
            cols[f.name] = Column.build(f.kind, vals, device=False)
        return Table(cols)


def _n_rows_of(table_or_cols) -> int:
    """Row count of a Table or a {name: Column} mapping (the padded count the
    dispatch actually computed — the honest denominator for per-row cost)."""
    if isinstance(table_or_cols, Table):
        return int(table_or_cols.nrows)
    try:
        return len(next(iter(table_or_cols.values())))
    except (StopIteration, AttributeError, TypeError):
        return 0


def _row_nonfinite(row: Mapping[str, Any]) -> bool:
    """True when any float in a result row (including nested prediction
    payloads: prediction scalar, rawPrediction/probability lists) is NaN or
    ±Inf — the signature of a poison row that parsed but produced garbage."""
    import math

    def bad(v) -> bool:
        if isinstance(v, float):
            return not math.isfinite(v)
        if isinstance(v, dict):
            return any(bad(x) for x in v.values())
        if isinstance(v, (list, tuple)):
            return any(bad(x) for x in v)
        return False

    return any(bad(v) for v in row.values())


def _placeholder(kind) -> Any:
    """Kind-appropriate missing-label placeholder: numerics get 0, host object kinds
    (text/lists/maps) get their natural empty value — fabricating int 0 into a Text
    column would crash downstream string stages."""
    from ..types import Storage

    st = kind.storage
    if st is Storage.TEXT:
        return None
    if st in (Storage.TEXT_LIST, Storage.DATE_LIST):
        return []
    if st is Storage.TEXT_SET:
        return frozenset()
    if st is Storage.MAP:
        return {}
    return 0


def score_function(model: "WorkflowModel", result_names: Optional[Sequence[str]] = None,
                  pad_to: Optional[Sequence[int]] = None,
                  backend: Optional[str] = "auto",
                  auto_cpu_threshold: int = AUTO_CPU_THRESHOLD,
                  mesh=None, monitor=None, policy=None,
                  model_label: Optional[str] = None,
                  quality=None) -> ScoreFunction:
    """Build the serving callable (analog of `model.scoreFunction`)."""
    return ScoreFunction(model, result_names=result_names, pad_to=pad_to,
                         backend=backend, auto_cpu_threshold=auto_cpu_threshold,
                         mesh=mesh, monitor=monitor, policy=policy,
                         model_label=model_label, quality=quality)
