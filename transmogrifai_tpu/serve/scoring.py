"""Spark-free serving: `model.score_fn()` — dict in, dict out.

TPU-native analog of OpWorkflowModelLocal.scoreFunction (reference local/src/main/scala/
com/salesforce/op/local/OpWorkflowModelLocal.scala:54-154, runner
OpWorkflowRunnerLocal.scala:42). The reference needs a whole MLeap conversion layer
because its training stages are Spark-bound; here the SAME stage kernels serve — the
fitted workflow's transform plan is re-grouped into a latency-optimized LocalPlan
(serve/local.py) with the device portions jit-compiled and cached across calls.

Three serving shapes:
- `score_fn(row_dict)` — one record. With `backend="cpu"` the plan is pinned to
  host CPU-JAX in-process (no device round trip): sub-ms after warmup, the
  analog of the reference's local JVM scoring.
- `score_fn.batch(rows)` — a list of records in one fused pass.
- `score_fn.table(table)` — columnar in, columnar out: the high-throughput
  device path (no per-row dict churn; one fused result fetch via `to_list`).
- `score_fn.stream(batches)` — pipelined micro-batch scoring: host table
  build of the next batch overlaps the fused device pass of the current one
  (the shared input executor, readers/pipeline.py).
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Any, Mapping, Optional, Sequence

from ..types import Column, Table

if TYPE_CHECKING:  # pragma: no cover
    from ..workflow.workflow import WorkflowModel


#: batches strictly below this row count route to the CPU columnar plan under
#: backend="auto": BENCH_r05 measured 101.55 ms single-row on the (tunneled)
#: device vs 0.307 ms on host CPU-JAX — a device round trip only pays for
#: itself when the batch amortizes it
AUTO_CPU_THRESHOLD = 256


class ScoreFunction:
    """Callable serving handle for a fitted WorkflowModel.

    backend: "auto" (default) = route by batch size — batches below
    `auto_cpu_threshold` rows run on the in-process host CPU-JAX plan (the
    sub-ms single-record path), larger ones on the process-default device;
    each decision is recorded as a `serve:routing` event on the active trace
    span. None = always the process default (TPU when present); "cpu" = pin
    every jit + intermediate to host CPU-JAX (`jax.default_device`). Explicit
    values are always respected — no routing happens unless backend="auto".

    mesh: optional device mesh — batches whose rows divide its data axis (and
    that routed to the device plan) are placed row-sharded before the fused
    pass, so the scoring program partitions across chips.
    """

    def __init__(self, model: "WorkflowModel", result_names: Optional[Sequence[str]] = None,
                 pad_to: Optional[Sequence[int]] = None,
                 backend: Optional[str] = "auto",
                 auto_cpu_threshold: int = AUTO_CPU_THRESHOLD,
                 mesh=None, monitor=None):
        self._model = model
        self._result_names = list(result_names) if result_names else [
            f.name for f in model.result_features
        ]
        self._predictors = [f for f in model.raw_features if not f.is_response]
        self._responses = [f for f in model.raw_features if f.is_response]
        #: pad batches up to these sizes to bound XLA recompilation (one compiled
        #: program per bucket, analog of serving-side shape bucketing)
        self._pad_to = sorted(pad_to) if pad_to else None
        self._backend = backend
        self._auto_cpu_threshold = int(auto_cpu_threshold)
        self._mesh = mesh
        #: drift monitor (obs/monitor.py). monitor=True builds one from the
        #: model's stamped serving_baseline; a ServingMonitor instance is used
        #: as-is; None/False disables. Batches fold into its streaming
        #: sketches BEFORE padding (filler rows must not skew fill rates).
        if monitor is True:
            from ..obs.monitor import ServingMonitor

            monitor = ServingMonitor.for_model(model)
        self.monitor = monitor or None
        self._plans: dict = {}  # backend key -> LocalPlan
        #: registry instruments cached per backend lane: get-or-create
        #: freezes/sorts labels under the registry lock — measurable at
        #: per-record serving frequency (same policy as ServingMonitor._gauge)
        self._route_counters: dict = {}
        self._lat_hists: dict = {}

    def _plan_for(self, backend: Optional[str]):
        key = backend or "default"
        plan = self._plans.get(key)
        if plan is None:
            from .local import LocalPlan

            device = None
            if backend is not None:
                import jax

                device = jax.devices(backend)[0]
            plan = self._plans[key] = LocalPlan(
                self._model.stages, self._result_names, device=device)
        return plan

    def _route(self, n_rows: int):
        """-> (LocalPlan, backend label). Under "auto", small batches take the
        CPU columnar path; the decision lands on the score trace span AND the
        metrics registry (`serve_routing_total{backend,decided}`)."""
        from .. import obs

        if self._backend != "auto":
            backend = self._backend
            decided = "explicit"
        else:
            import jax

            default_is_cpu = jax.devices()[0].platform == "cpu"
            backend = ("cpu" if not default_is_cpu
                       and n_rows < self._auto_cpu_threshold else None)
            decided = "auto"
        obs.add_event("serve:routing", backend=backend or "device",
                      rows=int(n_rows), decided=decided)
        key = (backend or "device", decided)
        c = self._route_counters.get(key)
        if c is None:
            c = self._route_counters[key] = obs.default_registry().counter(
                "serve_routing_total",
                help="serving batches routed per backend lane",
                labels={"backend": key[0], "decided": decided})
        c.inc()
        return self._plan_for(backend), backend

    def _timed_run(self, plan, table, backend: Optional[str]):
        """plan.run with the per-backend latency histogram
        (`serve_latency_seconds{backend}`: log buckets + exact p50/p95/p99).
        The observe is a few µs under one lock — noise against even the
        sub-ms CPU single-record path."""
        import time

        from .. import obs

        t0 = time.perf_counter()
        out = plan.run(table)
        key = backend or "device"
        h = self._lat_hists.get(key)
        if h is None:
            h = self._lat_hists[key] = obs.default_registry().histogram(
                "serve_latency_seconds",
                help="LocalPlan scoring latency per backend lane",
                labels={"backend": key})
        h.observe(time.perf_counter() - t0)
        return out

    def _observe(self, table_or_cols, n: int) -> None:
        """Fold a scoring batch into the drift monitor (no-op without one;
        never raises — the monitor owns its error counter)."""
        if self.monitor is None:
            return
        if isinstance(table_or_cols, Table):
            self.monitor.observe_table(table_or_cols, n=n)
        else:
            self.monitor.observe_columns(table_or_cols, n=n)

    def _local_plan(self):
        # back-compat surface (tests/tools introspect it): the device-lane plan
        return self._plan_for(None if self._backend == "auto" else self._backend)

    def _maybe_shard(self, table_or_cols, n_rows: int, backend: Optional[str]):
        """Row-shard numeric columns over the mesh data axis for large
        device-lane batches (pre-sharded inputs partition the fused pass)."""
        if self._mesh is None or backend is not None:
            return table_or_cols
        from ..mesh import DATA_AXIS

        n_data = int(self._mesh.shape[DATA_AXIS])
        if n_data <= 1 or n_rows < n_data or n_rows % n_data != 0:
            return table_or_cols
        from ..workflow.runner import shard_table_rows

        if isinstance(table_or_cols, Table):
            return shard_table_rows(self._mesh, table_or_cols)
        sharded = shard_table_rows(self._mesh, Table(dict(table_or_cols)))
        return {n: sharded[n] for n in sharded.names()}

    # --- single record ------------------------------------------------------------------
    def __call__(self, record: Mapping[str, Any]) -> dict[str, Any]:
        return self.batch([record])[0]

    # --- batch --------------------------------------------------------------------------
    def batch(self, records: Sequence[Mapping[str, Any]]) -> list[dict[str, Any]]:
        n = len(records)
        if n == 0:
            return []
        padded = self._pad(records)
        # route on the REAL row count: pad_to bucketing must not flip a
        # 4-row request onto the device lane just because its bucket is big
        plan, backend = self._route(n)
        table = self._build_table(padded)
        self._observe(table, n)
        table = self._maybe_shard(table, len(padded), backend)
        out = self._timed_run(plan, table, backend)
        return self._rows_out(out, n)

    def _rows_out(self, out: Mapping[str, Column], n: int) -> list[dict[str, Any]]:
        results: list[dict[str, Any]] = [{} for _ in range(n)]
        for name in self._result_names:
            for i, v in enumerate(out[name].to_list()[:n]):
                results[i][name] = v
        return results

    # --- streaming ----------------------------------------------------------------------
    def stream(self, batches, *, prefetch: int = 2):
        """Pipelined batch scoring over an iterable of record batches: the
        host-side table build (+ padding) of batch k+1 runs on a producer
        thread while the fused LocalPlan program scores batch k — the serving
        face of the shared input executor (readers/pipeline.py). Yields one
        `batch()`-shaped result list per input batch, in order; results are
        bit-identical to mapping `batch()` over the same stream. `prefetch=0`
        degrades to the synchronous loop."""
        if prefetch <= 0:
            for records in batches:
                yield self.batch(records)
            return
        from ..readers.pipeline import Prefetcher

        def prep(records):
            n = len(records)
            if n == 0:
                return 0, None, None
            padded = self._pad(records)
            plan, backend = self._route(n)  # real rows, not the pad bucket
            table = self._build_table(padded)
            # drift sketches fold on the PRODUCER thread: the numpy histogram
            # pass overlaps the device scoring of the previous batch instead
            # of extending the critical path
            self._observe(table, n)
            return n, table, (plan, backend, len(padded))

        def place(item):
            # producer-thread device placement: under a mesh, device-lane
            # batches land PRE-SHARDED over the data axis while the fused
            # pass still scores the previous batch
            n, table, route = item
            if route is None:
                return item
            plan, backend, n_padded = route
            return n, self._maybe_shard(table, n_padded, backend), route

        # plans build once, outside the timed overlap
        if self._backend == "auto":
            self._plan_for(None)
            import jax

            if jax.devices()[0].platform != "cpu":
                self._plan_for("cpu")
        else:
            self._local_plan()

        with Prefetcher(batches, prep, depth=prefetch, name="serve_build",
                        place=place) as pf:
            for n, table, route in pf:
                # bare-Prefetcher use: the consumer owns the batch count
                # (run_pipeline's loop does this for the runner), so
                # close()-time stats.publish() has real totals to fold
                pf.stats.batches += 1
                yield ([] if n == 0 else self._rows_out(
                    self._timed_run(route[0], table, route[1]), n))

    # --- columnar -----------------------------------------------------------------------
    def table(self, table: Table) -> Table:
        """Columnar scoring: a Table holding the raw predictor columns (responses
        optional — serving is unlabeled) -> a Table of the result columns. The
        throughput path: no per-row dict building, results fetched lazily (call
        `.to_list()` on a result column for one fused device_get)."""
        cols = {f.name: table[f.name] for f in self._predictors}
        n = table.nrows
        for f in self._responses:
            if f.name in table.columns:
                cols[f.name] = table[f.name]
            else:
                cols[f.name] = Column.build(f.kind, [_placeholder(f.kind)] * n, device=False)
        plan, backend = self._route(n)
        self._observe(cols, n)
        cols = self._maybe_shard(cols, n, backend)
        out = self._timed_run(plan, cols, backend)
        return Table({n_: out[n_] for n_ in self._result_names})

    def _pad(self, records: Sequence[Mapping[str, Any]]):
        if not self._pad_to or len(records) >= self._pad_to[-1]:
            return list(records)
        target = next(b for b in self._pad_to if b >= len(records))
        filler = dict(records[0])
        return list(records) + [filler] * (target - len(records))

    def _build_table(self, records: Sequence[Mapping[str, Any]]) -> Table:
        cols = {}
        for f in self._predictors:
            try:
                vals = [r[f.name] for r in records]
            except KeyError as e:
                raise KeyError(
                    f"serving record missing predictor {f.name!r}"
                ) from e
            cols[f.name] = Column.build(f.kind, vals, device=False)
        for f in self._responses:  # placeholder labels (serving is unlabeled)
            default = _placeholder(f.kind)
            vals = [r.get(f.name, default) for r in records]
            vals = [default if v is None else v for v in vals]
            cols[f.name] = Column.build(f.kind, vals, device=False)
        return Table(cols)


def _placeholder(kind) -> Any:
    """Kind-appropriate missing-label placeholder: numerics get 0, host object kinds
    (text/lists/maps) get their natural empty value — fabricating int 0 into a Text
    column would crash downstream string stages."""
    from ..types import Storage

    st = kind.storage
    if st is Storage.TEXT:
        return None
    if st in (Storage.TEXT_LIST, Storage.DATE_LIST):
        return []
    if st is Storage.TEXT_SET:
        return frozenset()
    if st is Storage.MAP:
        return {}
    return 0


def score_function(model: "WorkflowModel", result_names: Optional[Sequence[str]] = None,
                  pad_to: Optional[Sequence[int]] = None,
                  backend: Optional[str] = "auto",
                  auto_cpu_threshold: int = AUTO_CPU_THRESHOLD,
                  mesh=None, monitor=None) -> ScoreFunction:
    """Build the serving callable (analog of `model.scoreFunction`)."""
    return ScoreFunction(model, result_names=result_names, pad_to=pad_to,
                         backend=backend, auto_cpu_threshold=auto_cpu_threshold,
                         mesh=mesh, monitor=monitor)
