"""Spark-free serving: `model.score_fn()` — dict in, dict out.

TPU-native analog of OpWorkflowModelLocal.scoreFunction (reference local/src/main/scala/
com/salesforce/op/local/OpWorkflowModelLocal.scala:54-154, runner
OpWorkflowRunnerLocal.scala:42). The reference needs a whole MLeap conversion layer
because its training stages are Spark-bound; here the SAME stage kernels serve — the
fitted workflow's transform plan is applied to a 1-row (or N-row) Table built from the
input dict, with the device portions jit-compiled and cached across calls.

Batching semantics: `score_fn(row_dict)` scores one record (µs-scale after warmup on
CPU-JAX; the reference quotes ~µs/row for its local scoring), `score_fn.batch(rows)`
scores a list of records in one fused device pass — the TPU-friendly path.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Any, Mapping, Optional, Sequence

from ..types import Column, Table

if TYPE_CHECKING:  # pragma: no cover
    from ..workflow.workflow import WorkflowModel


class ScoreFunction:
    """Callable serving handle for a fitted WorkflowModel."""

    def __init__(self, model: "WorkflowModel", result_names: Optional[Sequence[str]] = None,
                 pad_to: Optional[Sequence[int]] = None):
        self._model = model
        self._result_names = list(result_names) if result_names else [
            f.name for f in model.result_features
        ]
        self._predictors = [f for f in model.raw_features if not f.is_response]
        self._responses = [f for f in model.raw_features if f.is_response]
        #: pad batches up to these sizes to bound XLA recompilation (one compiled
        #: program per bucket, analog of serving-side shape bucketing)
        self._pad_to = sorted(pad_to) if pad_to else None

    # --- single record ------------------------------------------------------------------
    def __call__(self, record: Mapping[str, Any]) -> dict[str, Any]:
        return self.batch([record])[0]

    # --- batch --------------------------------------------------------------------------
    def batch(self, records: Sequence[Mapping[str, Any]]) -> list[dict[str, Any]]:
        n = len(records)
        if n == 0:
            return []
        padded = self._pad(records)
        table = self._build_table(padded)
        out = self._model.transform(table, keep_intermediate=True)
        results: list[dict[str, Any]] = [{} for _ in range(n)]
        for name in self._result_names:
            col = out[name]
            for i, v in enumerate(col.to_list()[:n]):
                results[i][name] = v
        return results

    def _pad(self, records: Sequence[Mapping[str, Any]]):
        if not self._pad_to or len(records) >= self._pad_to[-1]:
            return list(records)
        target = next(b for b in self._pad_to if b >= len(records))
        filler = dict(records[0])
        return list(records) + [filler] * (target - len(records))

    def _build_table(self, records: Sequence[Mapping[str, Any]]) -> Table:
        cols = {}
        for f in self._predictors:
            try:
                vals = [r[f.name] for r in records]
            except KeyError as e:
                raise KeyError(
                    f"serving record missing predictor {f.name!r}"
                ) from e
            cols[f.name] = Column.build(f.kind, vals)
        for f in self._responses:  # placeholder labels (serving is unlabeled)
            default = _placeholder(f.kind)
            vals = [r.get(f.name, default) for r in records]
            vals = [default if v is None else v for v in vals]
            cols[f.name] = Column.build(f.kind, vals)
        return Table(cols)


def _placeholder(kind) -> Any:
    """Kind-appropriate missing-label placeholder: numerics get 0, host object kinds
    (text/lists/maps) get their natural empty value — fabricating int 0 into a Text
    column would crash downstream string stages."""
    from ..types import Storage

    st = kind.storage
    if st is Storage.TEXT:
        return None
    if st in (Storage.TEXT_LIST, Storage.DATE_LIST):
        return []
    if st is Storage.TEXT_SET:
        return frozenset()
    if st is Storage.MAP:
        return {}
    return 0


def score_function(model: "WorkflowModel", result_names: Optional[Sequence[str]] = None,
                  pad_to: Optional[Sequence[int]] = None) -> ScoreFunction:
    """Build the serving callable (analog of `model.scoreFunction`)."""
    return ScoreFunction(model, result_names=result_names, pad_to=pad_to)
