"""Latency-optimized local scoring plan — the TPU-native answer to the
reference's `local/` module (OpWorkflowModelLocal.scala:54-154), whose defining
property is µs-scale single-record scoring on a plain JVM with no cluster.

The fitted workflow's stages are re-grouped for SERVING rather than training:

- ALL consecutive device stages — including `kernel_jitted` fitted models and
  the VectorsCombiner, which training keeps OUT of the fused jit to avoid
  per-train retraces — fuse into ONE jit program per run. A serving plan wraps
  exactly one fixed model, so baking its fitted params in as trace constants
  is free (and lets XLA constant-fold the model into the program).
- Host stages run as bare `transform_columns` calls: no Table re-wrapping, no
  per-call slot-history attachment (that is insight metadata, not serving
  output — the training path's `attach_slot_history` costs ~15 ms/call in
  dataclass churn on a Titanic-sized schema).
- `device="cpu"` pins the whole plan to host CPU-JAX **in the same process**
  via `jax.default_device`: every jit compiles a CPU executable and every
  intermediate stays in host memory, so a single record never pays a device
  round trip. This is the deployment analog of the reference running its
  fitted pipeline on a local JVM instead of a Spark cluster.

Schema note: stages construct their own output VectorSchemas inside
`transform_columns`; for fused runs that happens once at trace time (Column is
a pytree whose schema rides the static aux slot), so the steady-state path
executes pure XLA + the host stages only.
"""
from __future__ import annotations

import contextlib
from typing import Callable, Optional, Sequence

import jax

from .. import obs
from ..types import Column


class LocalPlan:
    """Compiled serving executor over a fitted stage list.

    `run(raw_cols)` maps {raw feature name: Column} to {result name: Column}.
    Stage outputs not consumed by later stages or requested as results are
    dropped from fused-run outputs (dead-code elimination at plan build).
    """

    def __init__(self, stages: Sequence, result_names: Sequence[str],
                 device: Optional[object] = None):
        self._device = device
        self._result_names = list(result_names)
        out_slot: dict[str, int] = {}
        srcs_of: list[tuple] = []
        for si, s in enumerate(stages):
            srcs = tuple(("m", out_slot[f.name]) if f.name in out_slot
                         else ("r", f.name) for f in s.inputs)
            srcs_of.append(srcs)
            out_slot[s.get_output().name] = si

        # liveness: a stage output must be materialized out of its fused run
        # iff a later HOST step, a later fused run, or the result set reads it
        needed = {out_slot[n] for n in result_names if n in out_slot}
        self._passthrough = [n for n in result_names if n not in out_slot]

        groups: list[tuple[str, list[int]]] = []
        for si, s in enumerate(stages):
            kind = "d" if s.device_op else "h"
            if groups and groups[-1][0] == kind == "d":
                groups[-1][1].append(si)
            else:
                groups.append((kind, [si]))
        group_of = {si: gi for gi, (_, sis) in enumerate(groups) for si in sis}
        for si, srcs in enumerate(srcs_of):
            for tag, ref in srcs:
                if tag == "m" and group_of[ref] != group_of[si]:
                    needed.add(ref)

        self._steps: list[tuple] = []
        for kind, sis in groups:
            if kind == "h":
                for si in sis:
                    s = stages[si]
                    # serving kernel when the family provides one: pure numpy,
                    # index dicts + schema precomputed once (no per-call jnp
                    # eager dispatches, no per-call SlotInfo churn); the
                    # instance-memoized accessor shares the kernel with the
                    # training transform path
                    get_kernel = getattr(s, "serving_kernel", None)
                    kernel = get_kernel() if get_kernel is not None else None
                    fn = kernel if kernel is not None else s.transform_columns
                    self._steps.append(("h", fn, srcs_of[si], si))
            else:
                in_group = set(sis)
                ext_srcs: list[tuple] = []
                pos: dict[tuple, int] = {}
                wiring = []
                for si in sis:
                    w = []
                    for tag, ref in srcs_of[si]:
                        if tag == "m" and ref in in_group:
                            w.append(("g", sis.index(ref)))
                        else:
                            key = (tag, ref)
                            if key not in pos:
                                pos[key] = len(ext_srcs)
                                ext_srcs.append(key)
                            w.append(("x", pos[key]))
                    wiring.append(tuple(w))
                out_sis = [si for si in sis if si in needed]
                out_pos = [sis.index(si) for si in out_sis]
                fn = _fuse_serving_run([stages[si] for si in sis],
                                       tuple(wiring), tuple(out_pos))
                self._steps.append(("d", fn, tuple(ext_srcs), tuple(out_sis)))
        self._result_slot = {n: out_slot[n] for n in result_names
                             if n in out_slot}

    def _ctx(self):
        return (jax.default_device(self._device) if self._device is not None
                else contextlib.nullcontext())

    def run(self, raw_cols) -> dict[str, Column]:
        mid: dict[int, Column] = {}

        def get(src):
            tag, ref = src
            return raw_cols[ref] if tag == "r" else mid[ref]

        # obs.span is a no-op without an active tracer (~1µs), so the serving
        # hot path stays unburdened; under a tracer, any steady-state compile
        # here (a serving retrace — the round-4 failure class) is attributed
        with obs.span("serve:run"), self._ctx():
            for step in self._steps:
                if step[0] == "h":
                    _, fn, srcs, si = step
                    mid[si] = fn([get(s) for s in srcs])
                else:
                    _, fn, ext_srcs, out_sis = step
                    outs = fn(tuple(get(s) for s in ext_srcs))
                    for si, c in zip(out_sis, outs):
                        mid[si] = c
        out = {n: mid[si] for n, si in self._result_slot.items()}
        for n in self._passthrough:
            out[n] = raw_cols[n]
        return out


def _fuse_serving_run(stages: Sequence, wiring: tuple,
                      out_pos: tuple) -> Callable[[tuple], tuple]:
    """One jit over a run of device stages. Unlike the training-time
    `_fuse_device_run` (workflow.py), kernel_jitted stages are fused too and
    their fitted params become trace constants — a serving plan compiles once
    per model, so the retrace-per-train concern does not apply, and constant
    params let XLA fold them into the executable."""

    def fn(cols: tuple) -> tuple:
        mid: dict[int, Column] = {}
        for gi, s in enumerate(stages):
            ins = [mid[j] if tag == "g" else cols[j] for tag, j in wiring[gi]]
            mid[gi] = s.transform_columns(ins)
        return tuple(mid[p] for p in out_pos)

    return jax.jit(fn)
