"""Latency-optimized local scoring plan — the TPU-native answer to the
reference's `local/` module (OpWorkflowModelLocal.scala:54-154), whose defining
property is µs-scale single-record scoring on a plain JVM with no cluster.

The fitted workflow's stages are re-grouped for SERVING rather than training:

- ALL consecutive device stages — including `kernel_jitted` fitted models and
  the VectorsCombiner, which training keeps OUT of the fused jit to avoid
  per-train retraces — fuse into ONE jit program per run. A serving plan wraps
  exactly one fixed model, so baking its fitted params in as trace constants
  is free (and lets XLA constant-fold the model into the program).
- Host stages run as bare `transform_columns` calls: no Table re-wrapping, no
  per-call slot-history attachment (that is insight metadata, not serving
  output — the training path's `attach_slot_history` costs ~15 ms/call in
  dataclass churn on a Titanic-sized schema).
- `device="cpu"` pins the whole plan to host CPU-JAX **in the same process**
  via `jax.default_device`: every jit compiles a CPU executable and every
  intermediate stays in host memory, so a single record never pays a device
  round trip. This is the deployment analog of the reference running its
  fitted pipeline on a local JVM instead of a Spark cluster.

Schema note: stages construct their own output VectorSchemas inside
`transform_columns`; for fused runs that happens once at trace time (Column is
a pytree whose schema rides the static aux slot), so the steady-state path
executes pure XLA + the host stages only.
"""
from __future__ import annotations

import contextlib
from typing import Callable, Optional, Sequence

import jax

from .. import obs
from ..types import Column


class _AotDispatch:
    """Per-fused-step AOT executable table: row count -> a pre-loaded
    compiled executable (serve/aot.py hydration). Shapes flowing into a
    serving plan are fully determined by the padded row count (widths come
    from the fitted schema), so the dispatch key is just `len(rows)` — one
    dict probe on the hot path, no aval hashing. Any miss (an unwarmed
    shape) or a loaded executable that fails at call time falls back to the
    wrapped jit program — correctness is never at stake, only compile time —
    and reports through `on_fallback` so the handle's `aot_fallback_compiles`
    counter stays honest."""

    __slots__ = ("jit", "by_rows", "on_fallback")

    def __init__(self, jit_fn: Callable, on_fallback: Optional[Callable] = None):
        self.jit = jit_fn
        self.by_rows: dict[int, object] = {}
        self.on_fallback = on_fallback

    def _rows_of(self, cols: tuple) -> int:
        return len(cols[0]) if cols else 0

    def install(self, rows: int, loaded) -> None:
        self.by_rows[int(rows)] = loaded

    def mark_warmed(self, rows: int) -> None:
        """Record that `rows` was warmed through the JIT path (hydration did
        not cover it and `warm` compiled it instead): dispatches at that
        shape are compile-free steady state, NOT misses — they must not tick
        the fallback counter and read as a limping replica."""
        self.by_rows.setdefault(int(rows), self.jit)

    def __call__(self, cols: tuple) -> tuple:
        n = self._rows_of(cols)
        ex = self.by_rows.get(n)
        if ex is None:
            if self.on_fallback is not None:
                self.on_fallback(n)
            return self.jit(cols)
        if ex is self.jit:  # warmed-via-compile shape: normal jit dispatch
            return self.jit(cols)
        try:
            return ex(cols)
        except Exception:  # noqa: BLE001 — any AOT failure degrades to jit
            # a deserialized executable unusable at call time (avals drifted,
            # backend refused it) is permanently retired for this shape and
            # REPLACED by the jit path, so the retirement counts exactly once
            # — not on every subsequent dispatch at the shape
            self.by_rows[n] = self.jit
            if self.on_fallback is not None:
                self.on_fallback(n)
            return self.jit(cols)


class LocalPlan:
    """Compiled serving executor over a fitted stage list.

    `run(raw_cols)` maps {raw feature name: Column} to {result name: Column}.
    Stage outputs not consumed by later stages or requested as results are
    dropped from fused-run outputs (dead-code elimination at plan build).
    """

    def __init__(self, stages: Sequence, result_names: Sequence[str],
                 device: Optional[object] = None):
        self._device = device
        self._result_names = list(result_names)
        out_slot: dict[str, int] = {}
        srcs_of: list[tuple] = []
        for si, s in enumerate(stages):
            srcs = tuple(("m", out_slot[f.name]) if f.name in out_slot
                         else ("r", f.name) for f in s.inputs)
            srcs_of.append(srcs)
            out_slot[s.get_output().name] = si

        # liveness: a stage output must be materialized out of its fused run
        # iff a later HOST step, a later fused run, or the result set reads it
        needed = {out_slot[n] for n in result_names if n in out_slot}
        self._passthrough = [n for n in result_names if n not in out_slot]

        groups: list[tuple[str, list[int]]] = []
        for si, s in enumerate(stages):
            kind = "d" if s.device_op else "h"
            if groups and groups[-1][0] == kind == "d":
                groups[-1][1].append(si)
            else:
                groups.append((kind, [si]))
        group_of = {si: gi for gi, (_, sis) in enumerate(groups) for si in sis}
        for si, srcs in enumerate(srcs_of):
            for tag, ref in srcs:
                if tag == "m" and group_of[ref] != group_of[si]:
                    needed.add(ref)

        self._steps: list[tuple] = []
        for kind, sis in groups:
            if kind == "h":
                for si in sis:
                    s = stages[si]
                    # serving kernel when the family provides one: pure numpy,
                    # index dicts + schema precomputed once (no per-call jnp
                    # eager dispatches, no per-call SlotInfo churn); the
                    # instance-memoized accessor shares the kernel with the
                    # training transform path
                    get_kernel = getattr(s, "serving_kernel", None)
                    kernel = get_kernel() if get_kernel is not None else None
                    fn = kernel if kernel is not None else s.transform_columns
                    self._steps.append(("h", fn, srcs_of[si], si))
            else:
                in_group = set(sis)
                ext_srcs: list[tuple] = []
                pos: dict[tuple, int] = {}
                wiring = []
                for si in sis:
                    w = []
                    for tag, ref in srcs_of[si]:
                        if tag == "m" and ref in in_group:
                            w.append(("g", sis.index(ref)))
                        else:
                            key = (tag, ref)
                            if key not in pos:
                                pos[key] = len(ext_srcs)
                                ext_srcs.append(key)
                            w.append(("x", pos[key]))
                    wiring.append(tuple(w))
                out_sis = [si for si in sis if si in needed]
                out_pos = [sis.index(si) for si in out_sis]
                fn = _fuse_serving_run([stages[si] for si in sis],
                                       tuple(wiring), tuple(out_pos))
                self._steps.append(("d", fn, tuple(ext_srcs), tuple(out_sis)))
        self._result_slot = {n: out_slot[n] for n in result_names
                             if n in out_slot}

    def _ctx(self):
        return (jax.default_device(self._device) if self._device is not None
                else contextlib.nullcontext())

    def run(self, raw_cols) -> dict[str, Column]:
        mid: dict[int, Column] = {}

        def get(src):
            tag, ref = src
            return raw_cols[ref] if tag == "r" else mid[ref]

        # obs.span is a no-op without an active tracer (~1µs), so the serving
        # hot path stays unburdened; under a tracer, any steady-state compile
        # here (a serving retrace — the round-4 failure class) is attributed
        with obs.span("serve:run"), self._ctx():
            for step in self._steps:
                if step[0] == "h":
                    _, fn, srcs, si = step
                    mid[si] = fn([get(s) for s in srcs])
                else:
                    _, fn, ext_srcs, out_sis = step
                    outs = fn(tuple(get(s) for s in ext_srcs))
                    for si, c in zip(out_sis, outs):
                        mid[si] = c
        out = {n: mid[si] for n, si in self._result_slot.items()}
        for n in self._passthrough:
            out[n] = raw_cols[n]
        return out

    # --- AOT hooks (serve/aot.py) -------------------------------------------------------
    def device_step_indices(self) -> list[int]:
        """Positions of the fused device steps in execution order — the
        programs an AOT artifact set serializes (host steps are plain python
        and need no artifacts)."""
        return [i for i, step in enumerate(self._steps) if step[0] == "d"]

    def mark_warmed(self, rows: int) -> None:
        """Tell every AOT-wrapped fused step that `rows` was compiled via the
        jit path (no-op on steps without a dispatch wrapper — a plan that was
        never hydrated keeps zero per-call overhead)."""
        for step in self._steps:
            if step[0] == "d" and isinstance(step[1], _AotDispatch):
                step[1].mark_warmed(rows)

    @contextlib.contextmanager
    def aot_admission_guard(self):
        """Scope for warm's admission validation passes: on a SYNC backend a
        call-time executable failure is caught inside `_AotDispatch.__call__`
        (which retires the shape and invokes `on_fallback`) — during
        admission that must read as a validation failure, not a hot-path
        "limping replica" miss. Temporarily reroutes every wrapped step's
        `on_fallback` into the yielded list; the caller demotes the bucket
        when it comes back non-empty. Callbacks are restored on exit."""
        import threading

        disps = [s[1] for s in self._steps
                 if s[0] == "d" and isinstance(s[1], _AotDispatch)]
        fails: list[int] = []
        saved = [d.on_fallback for d in disps]
        # scope the reroute to THIS thread: warm() may be re-invoked on a
        # handle that is already serving, and a concurrent request's
        # fallback must keep reaching the real counter instead of being
        # misread as a validation failure of the bucket under test
        owner = threading.get_ident()
        for d, cb in zip(disps, saved):
            def rerouted(rows, _cb=cb):
                if threading.get_ident() == owner:
                    fails.append(rows)
                elif _cb is not None:
                    _cb(rows)
            d.on_fallback = rerouted
        try:
            yield fails
        finally:
            for d, cb in zip(disps, saved):
                d.on_fallback = cb

    def retire_aot(self, rows: int) -> None:
        """Replace any installed AOT executable at `rows` with the jit path
        on every fused step: an admission validation pass found a blob that
        deserialized but cannot run (serve/scoring.py warm). The shape then
        compiles like an uncovered bucket — correctness over cold-start."""
        for step in self._steps:
            if step[0] == "d" and isinstance(step[1], _AotDispatch):
                step[1].by_rows[int(rows)] = step[1].jit

    def aot_dispatch(self, idx: int,
                     on_fallback: Optional[Callable] = None) -> _AotDispatch:
        """Get-or-wrap the fused step at `idx` in an `_AotDispatch` so
        pre-compiled executables can be installed per row count. Idempotent;
        the wrapper keeps the original jit program as its fallback."""
        kind, fn, ext_srcs, out_sis = self._steps[idx]
        if kind != "d":
            raise ValueError(f"step {idx} is a host step, not a fused run")
        if not isinstance(fn, _AotDispatch):
            fn = _AotDispatch(fn, on_fallback=on_fallback)
            self._steps[idx] = (kind, fn, ext_srcs, out_sis)
        elif on_fallback is not None:
            fn.on_fallback = on_fallback
        return fn

    def walk_device_steps(self, raw_cols, on_device: Callable):
        """Execute the plan while delegating every fused device step to
        `on_device(step_idx, jit_fn, args_tuple) -> outputs` — the export
        path's capture hook (serve/aot.py lowers+compiles+serializes each
        step at the bucket's exact shapes). Host steps run normally; runs
        under the plan's device context exactly like `run`."""
        mid: dict[int, Column] = {}

        def get(src):
            tag, ref = src
            return raw_cols[ref] if tag == "r" else mid[ref]

        with self._ctx():
            for idx, step in enumerate(self._steps):
                if step[0] == "h":
                    _, fn, srcs, si = step
                    mid[si] = fn([get(s) for s in srcs])
                else:
                    _, fn, ext_srcs, out_sis = step
                    jit_fn = fn.jit if isinstance(fn, _AotDispatch) else fn
                    args = tuple(get(s) for s in ext_srcs)
                    outs = on_device(idx, jit_fn, args)
                    for si, c in zip(out_sis, outs):
                        mid[si] = c
        out = {n: mid[si] for n, si in self._result_slot.items()}
        for n in self._passthrough:
            out[n] = raw_cols[n]
        return out


def _fuse_serving_run(stages: Sequence, wiring: tuple,
                      out_pos: tuple) -> Callable[[tuple], tuple]:
    """One jit over a run of device stages. Unlike the training-time
    `_fuse_device_run` (workflow.py), kernel_jitted stages are fused too and
    their fitted params become trace constants — a serving plan compiles once
    per model, so the retrace-per-train concern does not apply, and constant
    params let XLA fold them into the executable."""

    def fn(cols: tuple) -> tuple:
        mid: dict[int, Column] = {}
        for gi, s in enumerate(stages):
            ins = [mid[j] if tag == "g" else cols[j] for tag, j in wiring[gi]]
            mid[gi] = s.transform_columns(ins)
        return tuple(mid[p] for p in out_pos)

    return jax.jit(fn)
