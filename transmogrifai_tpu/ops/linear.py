"""Functional linear-model trainers: pure jnp, fixed iteration counts, vmap/pjit-safe.

These are the compute cores behind OpLogisticRegression / OpLinearRegression /
OpLinearSVC / OpGeneralizedLinearRegression (reference wrappers at core/.../impl/
classification/OpLogisticRegression.scala:46 etc. delegate to Spark MLlib trainers whose
gradient aggregation is RDD treeAggregate; here the analogous aggregation is a jnp
reduction that XLA lowers to MXU matmuls + ICI psum when sharded).

Design rules for TPU:
  - fixed-shape, fixed-iteration solvers (lax.scan / fori_loop) -> one compiled program
    reusable across hyperparameters and CV folds, vmappable over a hyperparameter axis;
  - Newton/IRLS for convex problems: D is feature-vector width (hundreds..thousands),
    so the D x D normal/Hessian solve is trivial next to the N x D matmuls;
  - sample weights thread through everything (DataBalancer integration).
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class LinearParams(NamedTuple):
    """weights [D] (or [C, D] multiclass) + intercept."""

    w: jnp.ndarray
    b: jnp.ndarray


def _weighted(sample_weight, n):
    if sample_weight is None:
        return jnp.ones(n, jnp.float32)
    return jnp.asarray(sample_weight, jnp.float32)


def _adam_update(theta, m, v, g, t, lr_t,
                 b1=0.9, b2=0.999, eps=1e-8):
    """One Adam step over matching pytrees (tuples) of params/moments/grads.
    t is the 1-based step for bias correction. Delegates to the ONE shared
    rule in ops/optimizer.py (also used by the MLP trainers and the sharded-
    state path) so the solvers can never drift."""
    from .optimizer import adam_update

    return adam_update(theta, m, v, g, t, lr_t, b1=b1, b2=b2, eps=eps)


def _cosine_lr(lr, i, total):
    return lr * 0.5 * (1 + jnp.cos(jnp.pi * i / total))


def ridge_solve(H: jnp.ndarray, g: jnp.ndarray,
                fallback: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Solve H x = g with a RELATIVE ridge.

    Rank-deficient normal matrices are ROUTINE here (n << D after width
    bucketing pads zero columns); an absolute 1e-6 ridge vanishes next to
    large diagonal entries and the f32 Cholesky then returns NaN — which the
    callers' step-norm caps pass straight through (NaN > cap is False).
    Scaling the ridge by the mean diagonal keeps the system positive-definite
    at any data magnitude.

    `fallback` substitutes for a still-non-finite solution: iterative callers
    pass their no-op value (a zero STEP, or the previous iterate) so one bad
    solve cannot poison every later iteration. Without a fallback the raw
    solution returns — single closed-form solves should surface NaN honestly
    rather than silently produce an all-zero model."""
    d = H.shape[0]
    scale = jnp.trace(H) / d + 1e-12
    x = jax.scipy.linalg.solve(H + (1e-5 * scale) * jnp.eye(d), g,
                               assume_a="pos")
    if fallback is None:
        return x
    return jnp.where(jnp.all(jnp.isfinite(x)), x, fallback)


# --- logistic regression (binary): IRLS/Newton ------------------------------------------
@partial(jax.jit, static_argnames=("max_iter",))
def fit_logistic(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    l2: float = 0.0,
    max_iter: int = 25,
    init: Optional[tuple] = None,
) -> LinearParams:
    """Newton-IRLS for binary logistic regression. X [N,D] float32, y [N] in {0,1}.

    Each iteration: p = sigmoid(Xw+b); grad = X^T r; H = X^T diag(s) X — both single
    MXU matmuls; when rows are sharded across a mesh these become psum'd partials
    (the treeAggregate replacement, SURVEY §2.12).

    `init`: optional (w [D], b) warm start — Newton steps FROM the previous
    champion's weights instead of zero. At convergence the result matches the
    cold fit (the optimum is unique under l2 >= 0); on near-identical data it
    converges in a step or two (the autopilot's drift-retrain case). Warm
    steps are DAMPED (norm cap 2 instead of the cold path's 1e3): a
    confidently-wrong init — the champion after a concept flip — saturates
    the sigmoids, the Hessian collapses, and full Newton steps oscillate for
    hundreds of iterations; capped steps walk straight back to the optimum
    (and from an already-converged init the steps are ~0, so the damping
    never binds — the fixed point is preserved, pinned by test)."""
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    n, d = X.shape
    wts = _weighted(sample_weight, n)
    wsum = wts.sum()
    Xa = jnp.concatenate([X, jnp.ones((n, 1), jnp.float32)], axis=1)  # bias fold
    lam = jnp.asarray(l2, jnp.float32)
    # cold fits keep the historical 1e3 divergence guard (bitwise-pinned by
    # golden digests); warm fits damp to 2.0 — see the docstring
    step_cap = 1e3 if init is None else 2.0

    def step(theta, _):
        z = Xa @ theta
        p = jax.nn.sigmoid(z)
        s = jnp.clip(p * (1.0 - p), 1e-6, None) * wts
        r = (p - y) * wts
        reg = lam * theta.at[-1].set(0.0)  # don't penalize intercept
        grad = Xa.T @ r / wsum + reg
        H = (Xa.T * s) @ Xa / wsum + lam * jnp.eye(d + 1).at[-1, -1].set(0.0)
        delta = ridge_solve(H, grad, fallback=jnp.zeros_like(grad))
        # guard divergence: cap the Newton step norm
        norm = jnp.linalg.norm(delta)
        delta = jnp.where(norm > step_cap, delta * (step_cap / norm), delta)
        return theta - delta, None

    if init is None:
        theta0 = jnp.zeros(d + 1, jnp.float32)
    else:
        w0, b0 = init
        theta0 = jnp.concatenate([
            jnp.asarray(w0, jnp.float32).reshape(-1),
            jnp.asarray(b0, jnp.float32).reshape(1)])
    theta, _ = jax.lax.scan(step, theta0, None, length=max_iter)
    return LinearParams(w=theta[:-1], b=theta[-1])


# --- logistic regression (binary), wide-D solver: full-batch Adam -----------------------
@partial(jax.jit, static_argnames=("max_iter",))
def fit_logistic_gd(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    l2: float = 0.0,
    max_iter: int = 300,
    lr: float = 0.5,
    warm: Optional[tuple] = None,
) -> LinearParams:
    """Gradient solver for binary logistic regression, for WIDE feature matrices.

    Newton-IRLS (fit_logistic) builds a DxD Hessian — quadratic memory and an NxD^2
    matmul per step, prohibitive past a few thousand columns. This solver is linear
    in D: each step is two [N,D] matmuls (forward + grad), exactly the shapes that
    shard as P(data, model) over the mesh — rows psum over the data axis, partial
    dot-products psum over the model axis (SURVEY §5.7 wide-feature sharding). The
    reference leans on MLlib's OWLQN/L-BFGS over sparse vectors for the same regime
    (OpLogisticRegression.scala:46); here the MXU eats the dense matmuls instead."""
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    n, d = X.shape
    wts = _weighted(sample_weight, n)
    wsum = wts.sum()

    def loss_fn(theta):
        w, b = theta
        z = X @ w + b
        ll = wts * (jax.nn.log_sigmoid(z) * y + jax.nn.log_sigmoid(-z) * (1.0 - y))
        return -ll.sum() / wsum + 0.5 * l2 * (w ** 2).sum()

    grad_fn = jax.grad(loss_fn)

    def step(carry, i):
        theta, m, v = carry
        g = grad_fn(theta)
        theta, m, v = _adam_update(theta, m, v, g, i + 1,
                                   _cosine_lr(lr, i, max_iter))
        return (theta, m, v), None

    if warm is None:  # `warm` mirrors fit_logistic's init: (w [D], b)
        w0, b0 = jnp.zeros(d, jnp.float32), jnp.asarray(0.0, jnp.float32)
    else:
        w0 = jnp.asarray(warm[0], jnp.float32).reshape(-1)
        b0 = jnp.asarray(warm[1], jnp.float32).reshape(())
    init = ((w0, b0), (jnp.zeros_like(w0), jnp.zeros_like(b0)),
            (jnp.zeros_like(w0), jnp.zeros_like(b0)))
    (theta, _, _), _ = jax.lax.scan(step, init, jnp.arange(max_iter))
    return LinearParams(w=theta[0], b=theta[1])


#: feature widths past this use the gradient solver instead of Newton-IRLS
WIDE_D_THRESHOLD = 2048


@jax.jit
def predict_logistic(params: LinearParams, X: jnp.ndarray):
    """-> (pred {0,1} [N], raw [N,2], prob [N,2]). Jitted as one program: eager
    matmul+sigmoid+stack glue would dispatch several tiny compiles per shape."""
    z = jnp.asarray(X, jnp.float32) @ params.w + params.b
    p1 = jax.nn.sigmoid(z)
    prob = jnp.stack([1.0 - p1, p1], axis=1)
    raw = jnp.stack([-z, z], axis=1)
    return (p1 >= 0.5).astype(jnp.float32), raw, prob


# --- multinomial logistic regression: fixed-step full-batch Adam ------------------------
@partial(jax.jit, static_argnames=("num_classes", "max_iter"))
def fit_multinomial(
    X: jnp.ndarray,
    y: jnp.ndarray,
    num_classes: int,
    sample_weight: Optional[jnp.ndarray] = None,
    l2: float = 0.0,
    max_iter: int = 300,
    lr: float = 0.5,
) -> LinearParams:
    """Softmax regression via full-batch Adam with cosine decay (fixed shape/steps,
    vmappable over l2). y [N] int class ids."""
    X = jnp.asarray(X, jnp.float32)
    yi = jnp.asarray(y, jnp.int32)
    n, d = X.shape
    wts = _weighted(sample_weight, n)
    wsum = wts.sum()
    Y = jax.nn.one_hot(yi, num_classes)

    def loss_fn(theta):
        w, b = theta
        logits = X @ w.T + b
        ll = (wts * (jax.nn.log_softmax(logits) * Y).sum(axis=1)).sum() / wsum
        return -ll + 0.5 * l2 * (w ** 2).sum()

    grad_fn = jax.grad(loss_fn)
    w0 = jnp.zeros((num_classes, d), jnp.float32)
    b0 = jnp.zeros(num_classes, jnp.float32)

    def step(carry, i):
        theta, m, v = carry
        g = grad_fn(theta)
        theta, m, v = _adam_update(theta, m, v, g, i + 1,
                                   _cosine_lr(lr, i, max_iter))
        return (theta, m, v), None

    init = ((w0, b0), (jnp.zeros_like(w0), jnp.zeros_like(b0)),
            (jnp.zeros_like(w0), jnp.zeros_like(b0)))
    (theta, _, _), _ = jax.lax.scan(step, init, jnp.arange(max_iter))
    return LinearParams(w=theta[0], b=theta[1])


@jax.jit
def predict_multinomial(params: LinearParams, X: jnp.ndarray):
    logits = jnp.asarray(X, jnp.float32) @ params.w.T + params.b
    prob = jax.nn.softmax(logits, axis=1)
    pred = jnp.argmax(logits, axis=1).astype(jnp.float32)
    return pred, logits, prob


# --- linear regression: ridge normal equations ------------------------------------------
@jax.jit
def fit_linear(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    l2: float = 0.0,
) -> LinearParams:
    """Closed-form (weighted) ridge: (X^T W X + lam I) theta = X^T W y — one matmul
    + D x D solve (reference OpLinearRegression's L-BFGS path collapses to this)."""
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    n, d = X.shape
    wts = _weighted(sample_weight, n)
    Xa = jnp.concatenate([X, jnp.ones((n, 1), jnp.float32)], axis=1)
    A = (Xa.T * wts) @ Xa / wts.sum()
    lam = jnp.asarray(l2, jnp.float32)
    A = A + lam * jnp.eye(d + 1).at[-1, -1].set(0.0)
    g = (Xa.T * wts) @ y / wts.sum()
    theta = ridge_solve(A, g)
    return LinearParams(w=theta[:-1], b=theta[-1])


@jax.jit
def predict_linear(params: LinearParams, X: jnp.ndarray):
    z = jnp.asarray(X, jnp.float32) @ params.w + params.b
    return z, z[:, None], z[:, None]


# --- linear regression, wide-D solver: full-batch Adam ----------------------------------
@partial(jax.jit, static_argnames=("max_iter",))
def fit_linear_gd(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    l2: float = 0.0,
    max_iter: int = 300,
    lr: float = 0.5,
) -> LinearParams:
    """Gradient ridge regression for WIDE matrices: the normal-equation path
    (fit_linear) materializes a DxD system; this is linear in D and shards
    P(data, model) like fit_logistic_gd (SURVEY §5.7)."""
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    n, d = X.shape
    wts = _weighted(sample_weight, n)
    wsum = wts.sum()
    # standardize the target for a scale-free lr; un-scale the params afterwards
    y_mu = (wts * y).sum() / wsum
    y_sd = jnp.sqrt(jnp.maximum((wts * (y - y_mu) ** 2).sum() / wsum, 1e-12))
    ys = (y - y_mu) / y_sd

    def loss_fn(theta):
        w, b = theta
        err = X @ w + b - ys
        return (wts * err ** 2).sum() / wsum + l2 * (w ** 2).sum()

    grad_fn = jax.grad(loss_fn)

    def step(carry, i):
        theta, m, v = carry
        g = grad_fn(theta)
        theta, m, v = _adam_update(theta, m, v, g, i + 1,
                                   _cosine_lr(lr, i, max_iter))
        return (theta, m, v), None

    w0, b0 = jnp.zeros(d, jnp.float32), jnp.asarray(0.0, jnp.float32)
    init = ((w0, b0), (jnp.zeros_like(w0), jnp.float32(0.0)),
            (jnp.zeros_like(w0), jnp.float32(0.0)))
    ((w, b), _, _), _ = jax.lax.scan(step, init, jnp.arange(max_iter))
    return LinearParams(w=w * y_sd, b=b * y_sd + y_mu)


# --- linear SVC: smoothed hinge via Newton-like fixed Adam ------------------------------
@partial(jax.jit, static_argnames=("max_iter",))
def fit_svc(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    reg: float = 1e-2,
    max_iter: int = 300,
    lr: float = 0.1,
) -> LinearParams:
    """Linear SVM with squared hinge (smooth -> plain full-batch Adam; reference
    OpLinearSVC uses OWLQN on hinge). y in {0,1} -> {-1,+1}."""
    X = jnp.asarray(X, jnp.float32)
    ypm = jnp.asarray(y, jnp.float32) * 2.0 - 1.0
    n, d = X.shape
    wts = _weighted(sample_weight, n)
    wsum = wts.sum()

    def loss_fn(theta):
        w, b = theta
        margin = ypm * (X @ w + b)
        hinge = jnp.maximum(0.0, 1.0 - margin) ** 2
        return (wts * hinge).sum() / wsum + 0.5 * reg * (w ** 2).sum()

    grad_fn = jax.grad(loss_fn)

    def step(carry, i):
        theta, m, v = carry
        g = grad_fn(theta)
        theta, m, v = _adam_update(theta, m, v, g, i + 1,
                                   _cosine_lr(lr, i, max_iter))
        return (theta, m, v), None

    w0, b0 = jnp.zeros(d, jnp.float32), jnp.asarray(0.0, jnp.float32)
    init = ((w0, b0), (jnp.zeros_like(w0), jnp.zeros_like(b0)),
            (jnp.zeros_like(w0), jnp.zeros_like(b0)))
    (theta, _, _), _ = jax.lax.scan(step, init, jnp.arange(max_iter))
    return LinearParams(w=theta[0], b=theta[1])


@jax.jit
def predict_svc(params: LinearParams, X: jnp.ndarray):
    z = jnp.asarray(X, jnp.float32) @ params.w + params.b
    raw = jnp.stack([-z, z], axis=1)
    prob = jax.nn.sigmoid(raw)  # not calibrated; mirrors rawPrediction-only SVC
    return (z >= 0.0).astype(jnp.float32), raw, prob


# --- streaming (chunked) logistic regression for data larger than HBM -------------------
@partial(jax.jit, donate_argnums=(0,))
def logistic_stream_step(state, X, y, lr_t, l2):
    """One minibatch Adam step on a row chunk. state = ((w, b), (m...), (v...), t).
    Chunks may be generated on the fly (e.g. one-hot from category indices), so the
    full [N, D] matrix never exists — HBM holds one chunk (SURVEY §5.7 scale path)."""
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)

    def loss_fn(theta):
        w, b = theta
        z = X @ w + b
        ll = jax.nn.log_sigmoid(z) * y + jax.nn.log_sigmoid(-z) * (1.0 - y)
        return -ll.mean() + 0.5 * l2 * (w ** 2).sum()

    theta, m, v, t = state
    g = jax.grad(loss_fn)(theta)
    t = t + 1
    theta, m, v = _adam_update(theta, m, v, g, t, lr_t)
    return theta, m, v, t


def fit_logistic_streaming(chunk_fn, n_chunks: int, d: int, *, l2: float = 0.0,
                           epochs: int = 10, lr: float = 0.3) -> LinearParams:
    """Minibatch-Adam logistic regression over chunks produced by chunk_fn(i) ->
    (X [R, D], y [R]) device arrays. Cosine lr decay over the full step budget."""
    w0 = jnp.zeros(d, jnp.float32)
    state = ((w0, jnp.float32(0.0)),
             (jnp.zeros_like(w0), jnp.float32(0.0)),
             (jnp.zeros_like(w0), jnp.float32(0.0)),
             jnp.float32(0.0))
    import math

    total = epochs * n_chunks
    i = 0
    for _ in range(epochs):
        for c in range(n_chunks):
            X, y = chunk_fn(c)
            lr_t = lr * 0.5 * (1 + math.cos(math.pi * i / total))
            state = logistic_stream_step(state, X, y, jnp.float32(lr_t),
                                         jnp.float32(l2))
            i += 1
    (w, b), _, _, _ = state
    return LinearParams(w=w, b=b)


# --- one-hot (sparse) logistic regression: gather instead of matmul ---------------------
@partial(jax.jit, static_argnames=("n_weights",))
def fit_logistic_onehot(
    idx: jnp.ndarray,
    offsets: jnp.ndarray,
    y: jnp.ndarray,
    n_weights: int,
    sample_weight: Optional[jnp.ndarray] = None,
    l2: float = 0.0,
    max_iter: int = 300,
    lr: float = 0.5,
) -> LinearParams:
    """Exact equivalent of fit_logistic_gd on the one-hot expansion of categorical
    features, WITHOUT materializing it: idx [N, F] holds each feature's level id,
    offsets [F] the feature's column offset, and X@w becomes a gather
    w[idx + offsets].sum(-1) (whose autodiff transpose is a scatter-add). Work per
    step drops from O(N*D) to O(N*F) — the dense matmul does D/F times more FLOPs
    for the same model. This is the TPU answer to MLlib's sparse-vector LR
    (OpLogisticRegression.scala:46): embedding-style lookups on the vector units
    instead of a dense MXU pass over mostly-zero columns."""
    idx = jnp.asarray(idx, jnp.int32)
    y = jnp.asarray(y, jnp.float32)
    n, f = idx.shape
    wts = _weighted(sample_weight, n)
    wsum = wts.sum()
    cols = idx + jnp.asarray(offsets, jnp.int32)[None, :]

    def loss_fn(theta):
        w, b = theta
        z = w[cols].sum(axis=1) + b
        ll = wts * (jax.nn.log_sigmoid(z) * y + jax.nn.log_sigmoid(-z) * (1.0 - y))
        return -ll.sum() / wsum + 0.5 * l2 * (w ** 2).sum()

    grad_fn = jax.grad(loss_fn)

    # fori_loop with a TRACED bound: one compiled program serves every iteration
    # count (warmup at max_iter=1 covers the real run)
    def step(i, carry):
        theta, m, v = carry
        g = grad_fn(theta)
        return _adam_update(theta, m, v, g, i + 1, _cosine_lr(lr, i, max_iter))

    w0, b0 = jnp.zeros(n_weights, jnp.float32), jnp.asarray(0.0, jnp.float32)
    init = ((w0, b0), (jnp.zeros_like(w0), jnp.float32(0.0)),
            (jnp.zeros_like(w0), jnp.float32(0.0)))
    (w, b), _, _ = jax.lax.fori_loop(0, jnp.asarray(max_iter, jnp.int32), step, init)
    return LinearParams(w=w, b=b)


@jax.jit
def predict_logistic_onehot(params: LinearParams, idx, offsets):
    cols = jnp.asarray(idx, jnp.int32) + jnp.asarray(offsets, jnp.int32)[None, :]
    z = params.w[cols].sum(axis=1) + params.b
    p1 = jax.nn.sigmoid(z)
    prob = jnp.stack([1.0 - p1, p1], axis=1)
    raw = jnp.stack([-z, z], axis=1)
    return (p1 >= 0.5).astype(jnp.float32), raw, prob
