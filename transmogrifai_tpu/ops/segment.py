"""Device segment reductions: the TPU-native replacement for Spark's reduceByKey /
groupByKey shuffle in aggregate readers (reference DataReader.scala:206-279).

Keys are factorized host-side (strings -> dense segment ids via np.unique); the actual
per-key reduction runs on device as one `jax.ops.segment_*` call — an XLA scatter-reduce
that tiles onto the VPU, replacing a network shuffle with on-chip memory traffic.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def factorize_keys(keys) -> tuple[np.ndarray, np.ndarray]:
    """String/any keys -> (segment_ids [N] int32, unique_keys [K]) in sorted key order
    (np.unique) — deterministic for any input order."""
    keys = np.asarray(keys, dtype=object)
    uniq, inv = np.unique(keys.astype(str), return_inverse=True)
    return inv.astype(np.int32), uniq


def segment_reduce(
    values,
    segment_ids,
    num_segments: int,
    op: str = "sum",
    mask: Optional[jnp.ndarray] = None,
):
    """Masked per-segment reduction on device.

    values: [N] or [N, D] float/bool array; segment_ids: [N] int; op in
    {"sum", "max", "min", "or", "count", "mean"}. Returns (reduced [K,...],
    out_mask [K] = segment had >=1 present row).
    """
    values = jnp.asarray(values)
    segment_ids = jnp.asarray(segment_ids, jnp.int32)
    present = (
        jnp.ones(values.shape[0], bool) if mask is None else jnp.asarray(mask, bool)
    )
    counts = jax.ops.segment_sum(
        present.astype(jnp.int32), segment_ids, num_segments=num_segments
    )
    out_mask = counts > 0
    pm = present if values.ndim == 1 else present[:, None]

    if op == "count":
        return counts, out_mask
    if op in ("sum", "mean"):
        vals = jnp.where(pm, values.astype(jnp.float32), 0.0)
        s = jax.ops.segment_sum(vals, segment_ids, num_segments=num_segments)
        if op == "mean":
            denom = jnp.maximum(counts, 1).astype(jnp.float32)
            s = s / (denom if s.ndim == 1 else denom[:, None])
        return s, out_mask
    if op == "or":
        vals = jnp.where(pm, values.astype(bool), False)
        s = jax.ops.segment_max(
            vals.astype(jnp.int32), segment_ids, num_segments=num_segments
        )
        return s > 0, out_mask
    if op == "max":
        neg = jnp.finfo(jnp.float32).min
        vals = jnp.where(pm, values.astype(jnp.float32), neg)
        s = jax.ops.segment_max(vals, segment_ids, num_segments=num_segments)
        return jnp.where(out_mask if s.ndim == 1 else out_mask[:, None], s, 0.0), out_mask
    if op == "min":
        pos = jnp.finfo(jnp.float32).max
        vals = jnp.where(pm, values.astype(jnp.float32), pos)
        s = jax.ops.segment_min(vals, segment_ids, num_segments=num_segments)
        return jnp.where(out_mask if s.ndim == 1 else out_mask[:, None], s, 0.0), out_mask
    raise ValueError(f"unknown segment op {op!r}")
