"""Generalized linear models (gaussian / poisson / gamma / binomial, IRLS) and
isotonic regression (pool-adjacent-violators).

Compute cores of OpGeneralizedLinearRegression (reference core/.../impl/regression/
OpGeneralizedLinearRegression.scala wrapping Spark GLR, families+links per MLlib) and
IsotonicRegressionCalibrator (core/.../impl/regression/IsotonicRegressionCalibrator.scala).
IRLS is a fixed-iteration Newton scheme: each step is one weighted X^T X matmul + DxD
solve — MXU work with psum-able partials. PAV is inherently sequential, so isotonic
fitting runs host-side (numpy) exactly once at fit time; prediction is a device
searchsorted/interp.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .linear import LinearParams, ridge_solve

_FAMILIES = ("gaussian", "poisson", "gamma", "binomial")


@partial(jax.jit, static_argnames=("family", "max_iter"))
def fit_glm(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    *,
    family: str = "gaussian",
    l2=0.0,
    max_iter: int = 25,
) -> LinearParams:
    """IRLS with canonical-ish links: gaussian=identity, poisson/gamma=log,
    binomial=logit. Fixed iteration count -> one compiled program across folds/grids."""
    if family not in _FAMILIES:
        raise ValueError(f"unknown family {family!r}; one of {_FAMILIES}")
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    n, d = X.shape
    w = jnp.ones(n, jnp.float32) if sample_weight is None else jnp.asarray(sample_weight, jnp.float32)
    Xa = jnp.concatenate([X, jnp.ones((n, 1), jnp.float32)], axis=1)
    lam = jnp.asarray(l2, jnp.float32)
    reg_eye = jnp.eye(d + 1).at[-1, -1].set(0.0)

    def irls_step(theta, _):
        eta = Xa @ theta
        if family == "gaussian":
            mu, dmu, var = eta, jnp.ones_like(eta), jnp.ones_like(eta)
        elif family == "poisson":
            mu = jnp.exp(jnp.clip(eta, -30.0, 30.0))
            dmu, var = mu, jnp.clip(mu, 1e-6, None)
        elif family == "gamma":
            mu = jnp.exp(jnp.clip(eta, -30.0, 30.0))
            dmu, var = mu, jnp.clip(mu ** 2, 1e-6, None)
        else:  # binomial
            mu = jax.nn.sigmoid(eta)
            dmu = jnp.clip(mu * (1 - mu), 1e-6, None)
            var = dmu
        # working response and weights (standard IRLS)
        z = eta + (y - mu) / jnp.clip(dmu, 1e-6, None)
        ww = w * dmu ** 2 / jnp.clip(var, 1e-6, None)
        A = (Xa.T * ww) @ Xa / jnp.clip(ww.sum(), 1e-6, None) + lam * reg_eye
        g = (Xa.T * ww) @ z / jnp.clip(ww.sum(), 1e-6, None)
        # a non-finite solve keeps the previous iterate (IRLS progress survives)
        theta_new = ridge_solve(A, g, fallback=theta)
        return theta_new, None

    theta0 = jnp.zeros(d + 1, jnp.float32)
    theta, _ = jax.lax.scan(irls_step, theta0, None, length=max_iter)
    return LinearParams(w=theta[:-1], b=theta[-1])


@partial(jax.jit, static_argnames=("family",))
def predict_glm(params: LinearParams, X: jnp.ndarray, family: str = "gaussian"):
    eta = jnp.asarray(X, jnp.float32) @ params.w + params.b
    if family == "gaussian":
        mu = eta
    elif family in ("poisson", "gamma"):
        mu = jnp.exp(jnp.clip(eta, -30.0, 30.0))
    else:
        mu = jax.nn.sigmoid(eta)
    return mu, mu[:, None], mu[:, None]


# --- isotonic regression ---------------------------------------------------------------
def fit_isotonic(x: np.ndarray, y: np.ndarray,
                 sample_weight: Optional[np.ndarray] = None,
                 increasing: bool = True) -> tuple[np.ndarray, np.ndarray]:
    """Pool-adjacent-violators on the host -> (boundaries, values) knots.
    Sequential by nature (the reference runs Spark's parallel-PAV variant); at
    calibration scale (one scalar feature) the host pass is negligible."""
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    w = np.ones_like(y) if sample_weight is None else np.asarray(sample_weight, np.float64)
    order = np.argsort(x, kind="stable")
    xs, ys, ws = x[order], y[order], w[order]
    # pre-pool tied x values to their weighted mean (Spark averages ties before PAV;
    # without this, tied inputs produce duplicate knots and predict the max label)
    ux, inv = np.unique(xs, return_inverse=True)
    if len(ux) < len(xs):
        wsum = np.bincount(inv, weights=ws)
        ysum = np.bincount(inv, weights=ys * ws)
        xs, ws = ux, wsum
        ys = ysum / wsum
    if not increasing:
        ys = -ys
    # pooled blocks: (weighted sum, weight, x-min, x-max)
    vals: list[float] = []
    wts: list[float] = []
    lo: list[float] = []
    hi: list[float] = []
    for xi, yi, wi in zip(xs, ys, ws):
        vals.append(yi * wi)
        wts.append(wi)
        lo.append(xi)
        hi.append(xi)
        while len(vals) > 1 and vals[-2] / wts[-2] >= vals[-1] / wts[-1]:
            v, ww = vals.pop(), wts.pop()
            h = hi.pop()
            lo.pop()
            vals[-1] += v
            wts[-1] += ww
            hi[-1] = h
    knots_x = []
    knots_y = []
    for v, ww, l, h in zip(vals, wts, lo, hi):
        mean = v / ww if increasing else -v / ww
        knots_x.extend([l, h] if l != h else [l])
        knots_y.extend([mean, mean] if l != h else [mean])
    return np.asarray(knots_x, np.float32), np.asarray(knots_y, np.float32)


@jax.jit
def predict_isotonic(boundaries: jnp.ndarray, values: jnp.ndarray, x: jnp.ndarray):
    """Linear interpolation between isotonic knots (Spark IsotonicRegressionModel
    semantics), clamped at the ends."""
    return jnp.interp(jnp.asarray(x, jnp.float32), boundaries, values)
