"""Shared backend probe for kernel-path selection."""
from __future__ import annotations

import functools


@functools.cache
def backend_is_tpu() -> bool:
    """True when the default jax backend is a TPU (cached; False on init failure)."""
    import jax

    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend init failure
        return False
