"""Statistics kernels: moments, label correlations, contingency tables, Cramér's V.

TPU-native analogs of the reference's stats substrate — OpStatistics
(utils/src/main/scala/com/salesforce/op/utils/stats/OpStatistics.scala: contingency /
PMI / Cramér's V) and the MLlib Statistics.colStats / Statistics.corr calls inside
SanityChecker.fitFn (core/.../impl/preparators/SanityChecker.scala:535) and
RawFeatureFilter (RawFeatureFilter.scala:180). Where Spark aggregates per-partition
moments with treeAggregate, these are single fused jnp reductions: one X^T-style pass
produces every moment and correlation, and contingency tables are one-hot matmuls on
the MXU — sharded over a row mesh axis they psum over ICI.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

_EPS = 1e-12


class ColumnStats(NamedTuple):
    """Per-column moments of a feature matrix [D]."""

    mean: jnp.ndarray
    variance: jnp.ndarray
    min: jnp.ndarray
    max: jnp.ndarray
    count_nonzero: jnp.ndarray


@jax.jit
def column_stats(X: jnp.ndarray, w: Optional[jnp.ndarray] = None) -> ColumnStats:
    """Weighted per-column mean/variance/min/max/nnz in ONE pass over X [N, D]."""
    X = jnp.asarray(X, jnp.float32)
    n = X.shape[0]
    w = jnp.ones(n, jnp.float32) if w is None else jnp.asarray(w, jnp.float32)
    wsum = w.sum() + _EPS
    mean = (w[:, None] * X).sum(0) / wsum
    var = (w[:, None] * (X - mean[None, :]) ** 2).sum(0) / wsum
    return ColumnStats(
        mean=mean,
        variance=var,
        min=X.min(axis=0),
        max=X.max(axis=0),
        count_nonzero=(w[:, None] * (X != 0)).sum(0),
    )


@jax.jit
def pearson_with_label(X: jnp.ndarray, y: jnp.ndarray,
                       w: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Pearson correlation of every column of X [N, D] with y [N] -> [D].
    Zero-variance columns yield 0 (the reference reports NaN; 0 keeps downstream
    drop logic branch-free)."""
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    n = X.shape[0]
    w = jnp.ones(n, jnp.float32) if w is None else jnp.asarray(w, jnp.float32)
    wsum = w.sum() + _EPS
    mx = (w[:, None] * X).sum(0) / wsum
    my = (w * y).sum() / wsum
    xc = X - mx[None, :]
    yc = y - my
    cov = (w[:, None] * xc * yc[:, None]).sum(0) / wsum
    vx = (w[:, None] * xc ** 2).sum(0) / wsum
    vy = (w * yc ** 2).sum() / wsum
    denom = jnp.sqrt(vx * vy)
    return jnp.where(denom > _EPS, cov / jnp.clip(denom, _EPS, None), 0.0)


def _rank(v: jnp.ndarray) -> jnp.ndarray:
    """Average-free dense ranks (argsort of argsort); ties get arbitrary order, which
    matches MLlib's rank behavior closely enough for drop thresholds."""
    order = jnp.argsort(v, axis=0)
    n = v.shape[0]
    ranks = jnp.zeros_like(order).at[order].set(jnp.arange(n, dtype=order.dtype))
    return ranks.astype(jnp.float32)


@jax.jit
def spearman_with_label(X: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Spearman correlation of each column with y: Pearson on ranks."""
    Xr = jax.vmap(_rank, in_axes=1, out_axes=1)(jnp.asarray(X, jnp.float32))
    yr = _rank(jnp.asarray(y, jnp.float32))
    return pearson_with_label(Xr, yr)


@jax.jit
def correlation_matrix(X: jnp.ndarray) -> jnp.ndarray:
    """Full feature-feature Pearson correlation [D, D] as one X^T X MXU pass."""
    X = jnp.asarray(X, jnp.float32)
    n = X.shape[0]
    mx = X.mean(0)
    xc = X - mx[None, :]
    cov = xc.T @ xc / n
    sd = jnp.sqrt(jnp.clip(jnp.diag(cov), _EPS, None))
    corr = cov / (sd[:, None] * sd[None, :])
    return jnp.clip(corr, -1.0, 1.0)


@jax.jit
def contingency_table(indicators: jnp.ndarray, label_onehot: jnp.ndarray,
                      w: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Weighted contingency counts [K, C] = indicators^T @ diag(w) @ label_onehot.
    `indicators` [N, K] are 0/1 slot columns of one categorical group
    (OpStatistics.contingencyStats input, computed as a single matmul)."""
    ind = jnp.asarray(indicators, jnp.float32)
    lab = jnp.asarray(label_onehot, jnp.float32)
    if w is not None:
        ind = ind * jnp.asarray(w, jnp.float32)[:, None]
    return ind.T @ lab


@jax.jit
def cramers_v(table: jnp.ndarray) -> jnp.ndarray:
    """Bias-uncorrected Cramér's V of a contingency table [K, C]
    (OpStatistics.cramersV): sqrt(chi2 / (n * (min(K, C) - 1)))."""
    t = jnp.asarray(table, jnp.float32)
    n = t.sum() + _EPS
    rows = t.sum(1, keepdims=True)
    cols = t.sum(0, keepdims=True)
    expected = rows @ cols / n
    chi2 = jnp.where(expected > _EPS, (t - expected) ** 2 / jnp.clip(expected, _EPS, None), 0.0).sum()
    k = jnp.minimum((rows[:, 0] > 0).sum(), (cols[0] > 0).sum()).astype(jnp.float32)
    dof = jnp.clip(k - 1.0, 1e-6, None)
    return jnp.sqrt(chi2 / (n * dof))


@jax.jit
def pointwise_mutual_info(table: jnp.ndarray) -> jnp.ndarray:
    """PMI matrix [K, C] in BITS: log2(p(x,y) / (p(x) p(y))) — base 2 to match
    the reference (OpStatistics.mutualInfo divides by log(2),
    OpStatistics.scala:258); empty cells/rows/cols yield 0."""
    t = jnp.asarray(table, jnp.float32)
    n = t.sum() + _EPS
    pxy = t / n
    px = pxy.sum(1, keepdims=True)
    py = pxy.sum(0, keepdims=True)
    safe = (pxy > _EPS) & (px > _EPS) & (py > _EPS)
    return jnp.where(
        safe,
        jnp.log2(jnp.clip(pxy, _EPS, None) / jnp.clip(px * py, _EPS, None)),
        0.0)


@jax.jit
def mutual_information(table: jnp.ndarray) -> jnp.ndarray:
    """Total mutual information (bits) of a contingency table [K, C]:
    sum of PMI * p(x,y) (OpStatistics.mutualInfo, OpStatistics.scala:269)."""
    t = jnp.asarray(table, jnp.float32)
    n = t.sum() + _EPS
    return (pointwise_mutual_info(t) * t / n).sum()


@jax.jit
def rule_confidence(table: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Association-rule stats per indicator row of a contingency table [K, C]:
    (max over classes of P(class | indicator) [K], support P(indicator) [K])
    (SanityChecker maxRuleConfidence / minRequiredRuleSupport)."""
    t = jnp.asarray(table, jnp.float32)
    n = t.sum() + _EPS
    row = t.sum(1)
    conf = jnp.where(row[:, None] > _EPS, t / jnp.clip(row[:, None], _EPS, None), 0.0).max(1)
    support = row / n
    return conf, support


# --- streaming (chunked) stats for matrices too wide/tall to materialize --------------
class StreamingStats(NamedTuple):
    """Accumulator for one pass of SanityChecker-grade statistics over row chunks of
    a design matrix that never exists in memory at once (the 1M x 10k regime,
    SURVEY §5.7). Finalize yields moments, label correlations, and the full DxD
    correlation matrix — the same quantities the in-memory fused pass computes."""

    n: jnp.ndarray          # scalar rows seen
    s1: jnp.ndarray         # [D] sum x
    s2: jnp.ndarray         # [D] sum x^2
    sy: jnp.ndarray         # [D] sum x*y
    xtx: jnp.ndarray        # [D, D] sum x_i x_j (fp32, accumulated from bf16 matmul)
    y1: jnp.ndarray         # scalar sum y
    y2: jnp.ndarray         # scalar sum y^2
    mn: jnp.ndarray         # [D] min
    mx: jnp.ndarray         # [D] max


def streaming_stats_init(d: int) -> StreamingStats:
    z = jnp.zeros(d, jnp.float32)
    return StreamingStats(
        n=jnp.float32(0.0), s1=z, s2=z, sy=z,
        xtx=jnp.zeros((d, d), jnp.float32),
        y1=jnp.float32(0.0), y2=jnp.float32(0.0),
        mn=jnp.full(d, jnp.inf, jnp.float32), mx=jnp.full(d, -jnp.inf, jnp.float32),
    )


@jax.jit
def streaming_stats_update(acc: StreamingStats, X: jnp.ndarray,
                           y: jnp.ndarray) -> StreamingStats:
    """Fold one [R, D] chunk in. The X^T X partial runs in bfloat16 on the MXU and
    accumulates in fp32 — the FLOPs workhorse of the wide sanity pass. Chunks may
    arrive in bf16 (halving the generator's write bandwidth); the per-consumer f32
    casts below fuse into their reductions, so no f32 copy of X materializes."""
    cast = lambda: jnp.asarray(X, jnp.float32)  # noqa: E731 — fused per consumer
    Xb = jnp.asarray(X, jnp.bfloat16)
    yf = jnp.asarray(y, jnp.float32)
    return StreamingStats(
        n=acc.n + X.shape[0],
        s1=acc.s1 + cast().sum(axis=0),
        s2=acc.s2 + jnp.square(cast()).sum(axis=0),
        sy=acc.sy + jnp.einsum("nd,n->d", cast(), yf),
        xtx=acc.xtx + jnp.asarray(Xb.T @ Xb, jnp.float32),
        y1=acc.y1 + yf.sum(),
        y2=acc.y2 + (yf * yf).sum(),
        mn=jnp.minimum(acc.mn, cast().min(axis=0)),
        mx=jnp.maximum(acc.mx, cast().max(axis=0)),
    )


@jax.jit
def streaming_stats_finalize(acc: StreamingStats):
    """-> (mean [D], var [D], min, max, corr_with_label [D], corr_matrix [D, D])."""
    n = jnp.maximum(acc.n, 1.0)
    mean = acc.s1 / n
    var = jnp.maximum(acc.s2 / n - mean ** 2, 0.0)
    y_mean = acc.y1 / n
    y_var = jnp.maximum(acc.y2 / n - y_mean ** 2, 1e-12)
    cov_y = acc.sy / n - mean * y_mean
    corr_y = cov_y / jnp.sqrt(jnp.maximum(var, 1e-12) * y_var)
    cov = acc.xtx / n - jnp.outer(mean, mean)
    sd = jnp.sqrt(jnp.maximum(var, 1e-12))
    corr = cov / jnp.outer(sd, sd)
    return mean, var, acc.mn, acc.mx, corr_y, corr
