"""Pallas TPU kernel: gradient histograms for tree growth as one-hot MXU matmuls.

The tree grower's inner loop sums per-row gradient/hessian vectors into
(node, feature, bin) cells (ops/trees.py `_histogram`, the RDD treeAggregate analog of
the reference's MLlib/xgboost4j trainers — SURVEY §2.11d/2.12). The jnp fallback is a
`segment_sum`, which XLA lowers to a scatter-add: correct everywhere, but scatters
serialize on TPU.

This kernel reformulates the scatter as dense matmuls, which is what the MXU is for:
for each feature d in a cell's feature tile, one segment tile, and a block of rows,
build the one-hot membership matrix M[r, s] = [node(r) * n_bins + bin(r, d) == s] in
VMEM and accumulate out[d, :, s_tile] += GH^T @ M — the segment axis rides the MXU
lanes (the channel count is tiny, so the transposed orientation is what keeps the
MXU wide). Row blocks stream sequentially and accumulate ("arbitrary" grid dim);
feature tiles and segment tiles are independent ("parallel"). Deep trees (many
nodes) grow the segment axis, so it is tiled at SEG_TILE lanes to bound VMEM.

NOTE: this kernel is retained as a comparison baseline and optional path
(TT_HIST=pallas); the production default on TPU is ops/trees.histogram_binmm,
whose bin-wise dense-matmul decomposition avoids materializing the [Bn, S]
one-hot entirely and measures 3-13x faster (bench_extra.run_hist) — the rare
case where plain XLA beats the hand-written kernel because the better algorithm
is expressible as matmuls XLA already schedules well.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: max one-hot lanes per grid cell; 2048 f32 lanes x 512 rows = 4 MB VMEM
SEG_TILE = 2048


@functools.cache
def use_pallas_histogram() -> bool:
    """Whether the pallas kernel is RUNNABLE here (TPU backend; TT_PALLAS_HIST=0/1
    overrides). Note this gates availability only — the live training histogram
    is selected by TT_HIST in ops/trees._histogram (default: binmm on TPU, which
    measures faster than this kernel; pallas stays as a comparison baseline)."""
    env = os.environ.get("TT_PALLAS_HIST")
    if env is not None:
        return env == "1"
    from .backend import backend_is_tpu

    return backend_is_tpu()


def _hist_kernel_ftile(xb_ref, node_ref, gh_ref, out_ref, *, n_bins: int,
                       seg_tile: int, f_tile: int):
    """One (feature-tile, segment-tile, row-block) cell: for each of the f_tile
    features resident in this cell's [Bn, f_tile] bin block, accumulate
    out[j, :, tile] += gh^T @ onehot. Unlike the one-feature-per-cell layout,
    each cell loads only its feature slice (HBM traffic O(N*D) total instead of
    O(N*D*D/f_tile)) and the per-feature lane-select scans f_tile lanes, not D."""
    s = pl.program_id(1)
    first_rows = pl.program_id(2) == 0
    base = node_ref[:, 0] * n_bins - s * seg_tile  # [Bn], tile-local
    seg = jax.lax.broadcasted_iota(jnp.int32, (base.shape[0], seg_tile), 1)
    gh = gh_ref[:, :]
    xb = xb_ref[:, :]

    def body(j, _):
        col = jax.lax.broadcasted_iota(jnp.int32, xb.shape, 1) == j
        xb_j = jnp.sum(jnp.where(col, xb, 0), axis=1)  # [Bn]
        onehot = ((base + xb_j)[:, None] == seg).astype(jnp.float32)
        acc = jax.lax.dot_general(
            gh, onehot, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )[None]  # [1, C, S_T]
        prev = out_ref[pl.ds(j, 1), :, :]
        out_ref[pl.ds(j, 1), :, :] = jnp.where(first_rows, acc, prev + acc)
        return 0

    jax.lax.fori_loop(0, f_tile, body, 0)


def histogram_pallas(
    vals: jnp.ndarray,
    Xb: jnp.ndarray,
    node: jnp.ndarray,
    n_nodes: int,
    n_bins: int,
    *,
    block_rows: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """Sum vals [N, C] into per-(node, feature, bin) cells -> [n_nodes, D, n_bins, C].

    Drop-in replacement for the segment-sum histogram in ops/trees.py; zero-padded
    rows carry zero gradient mass and out-of-tile keys match no one-hot lane, so
    padding never perturbs counts."""
    N, D = Xb.shape
    C = vals.shape[1]
    S = n_nodes * n_bins
    seg_tile = min(S, SEG_TILE)
    n_seg_tiles = (S + seg_tile - 1) // seg_tile
    s_pad = n_seg_tiles * seg_tile
    n_blocks = max((N + block_rows - 1) // block_rows, 1)
    pad = n_blocks * block_rows - N
    f_tile = min(D, 128)  # lane-granule feature tile
    f_pad = (-D) % f_tile
    vals_p = jnp.pad(jnp.asarray(vals, jnp.float32), ((0, pad), (0, 0)))
    Xb_p = jnp.pad(Xb.astype(jnp.int32), ((0, pad), (0, f_pad)))
    # padded rows get key -1 (node -1): matches no segment lane in any tile
    node_p = jnp.pad(node.astype(jnp.int32)[:, None], ((0, pad), (0, 0)),
                     constant_values=-1)
    Dp = D + f_pad

    out = pl.pallas_call(
        functools.partial(_hist_kernel_ftile, n_bins=n_bins, seg_tile=seg_tile,
                          f_tile=f_tile),
        grid=(Dp // f_tile, n_seg_tiles, n_blocks),
        in_specs=[
            pl.BlockSpec((block_rows, f_tile), lambda f, s, r: (r, f)),  # bin slice
            pl.BlockSpec((block_rows, 1), lambda f, s, r: (r, 0)),  # row -> node id
            pl.BlockSpec((block_rows, C), lambda f, s, r: (r, 0)),  # gradient/hessian
        ],
        out_specs=pl.BlockSpec((f_tile, C, seg_tile), lambda f, s, r: (f, 0, s)),
        out_shape=jax.ShapeDtypeStruct((Dp, C, s_pad), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(Xb_p, node_p, vals_p)
    # [Dp, C, S] -> [n_nodes, D, n_bins, C] (trees.py layout)
    return out[:D, :, :S].reshape(D, C, n_nodes, n_bins).transpose(2, 0, 3, 1)
