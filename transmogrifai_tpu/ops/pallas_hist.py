"""Pallas TPU kernel: gradient histograms for tree growth as one-hot MXU matmuls.

The tree grower's inner loop sums per-row gradient/hessian vectors into
(node, feature, bin) cells (ops/trees.py `_histogram`, the RDD treeAggregate analog of
the reference's MLlib/xgboost4j trainers — SURVEY §2.11d/2.12). The jnp fallback is a
`segment_sum`, which XLA lowers to a scatter-add: correct everywhere, but scatters
serialize on TPU.

This kernel reformulates the scatter as dense matmuls, which is what the MXU is for:
for one feature d, one segment tile, and a block of rows, build the one-hot membership
matrix M[r, s] = [node(r) * n_bins + bin(r, d) == s] in VMEM and accumulate
out[d, :, s_tile] += GH^T @ M — the segment axis rides the MXU lanes (the channel
count is tiny, so the transposed orientation is what keeps the MXU wide). Row blocks
stream sequentially and accumulate ("arbitrary" grid dim); features and segment tiles
are independent ("parallel"). Deep trees (many nodes) grow the segment axis, so it is
tiled at SEG_TILE lanes to bound VMEM: per-cell budget is Bn*D bins + Bn*SEG_TILE
one-hot + C*SEG_TILE out ~= 4.5 MB at Bn=512, D<=1024 — inside the ~16 MB/core budget
(pallas_guide.md: Memory Spaces).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: max one-hot lanes per grid cell; 2048 f32 lanes x 512 rows = 4 MB VMEM
SEG_TILE = 2048


@functools.cache
def use_pallas_histogram() -> bool:
    """Pallas path on by default on TPU backends; force with TT_PALLAS_HIST=0/1."""
    env = os.environ.get("TT_PALLAS_HIST")
    if env is not None:
        return env == "1"
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend init failure
        return False


def _hist_kernel(xb_ref, node_ref, gh_ref, out_ref, *, n_bins: int, seg_tile: int):
    """One (feature, segment-tile, row-block) cell: out[d, :, tile] += gh^T @ onehot.

    The whole [Bn, D] bin block is resident (TPU blocks can't slice the lane dim
    below 128); this cell's feature column is picked with an iota mask + row-sum —
    a VPU select, far cheaper than the matmul it feeds."""
    d = pl.program_id(0)
    s = pl.program_id(1)
    col = jax.lax.broadcasted_iota(jnp.int32, xb_ref.shape, 1) == d
    xb_d = jnp.sum(jnp.where(col, xb_ref[:, :], 0), axis=1)            # [Bn]
    keys = node_ref[:, 0] * n_bins + xb_d - s * seg_tile               # [Bn], tile-local
    seg = jax.lax.broadcasted_iota(jnp.int32, (keys.shape[0], seg_tile), 1)
    onehot = (keys[:, None] == seg).astype(jnp.float32)                # [Bn, S_T]
    # gh^T @ onehot -> [C, S_T]: lanes = segments keeps the MXU wide (C is tiny);
    # HIGHEST precision = true f32 accumulation, comparable to the scatter path
    acc = jax.lax.dot_general(
        gh_ref[:, :], onehot,
        (((0,), (0,)), ((), ())),                                      # contract rows
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )                                                                  # [C, S_T]

    @pl.when(pl.program_id(2) == 0)
    def _init():
        out_ref[0, :, :] = acc

    @pl.when(pl.program_id(2) > 0)
    def _accum():
        out_ref[0, :, :] += acc


def histogram_pallas(
    vals: jnp.ndarray,
    Xb: jnp.ndarray,
    node: jnp.ndarray,
    n_nodes: int,
    n_bins: int,
    *,
    block_rows: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """Sum vals [N, C] into per-(node, feature, bin) cells -> [n_nodes, D, n_bins, C].

    Drop-in replacement for the segment-sum histogram in ops/trees.py; zero-padded
    rows carry zero gradient mass and out-of-tile keys match no one-hot lane, so
    padding never perturbs counts."""
    N, D = Xb.shape
    C = vals.shape[1]
    S = n_nodes * n_bins
    seg_tile = min(S, SEG_TILE)
    n_seg_tiles = (S + seg_tile - 1) // seg_tile
    s_pad = n_seg_tiles * seg_tile
    n_blocks = max((N + block_rows - 1) // block_rows, 1)
    pad = n_blocks * block_rows - N
    vals_p = jnp.pad(jnp.asarray(vals, jnp.float32), ((0, pad), (0, 0)))
    Xb_p = jnp.pad(Xb.astype(jnp.int32), ((0, pad), (0, 0)))
    # padded rows get key -1 (node -1): matches no segment lane in any tile
    node_p = jnp.pad(node.astype(jnp.int32)[:, None], ((0, pad), (0, 0)),
                     constant_values=-1)

    out = pl.pallas_call(
        functools.partial(_hist_kernel, n_bins=n_bins, seg_tile=seg_tile),
        grid=(D, n_seg_tiles, n_blocks),
        in_specs=[
            pl.BlockSpec((block_rows, D), lambda d, s, r: (r, 0)),  # all features' bins
            pl.BlockSpec((block_rows, 1), lambda d, s, r: (r, 0)),  # row -> node id
            pl.BlockSpec((block_rows, C), lambda d, s, r: (r, 0)),  # gradient/hessian
        ],
        out_specs=pl.BlockSpec((1, C, seg_tile), lambda d, s, r: (d, 0, s)),
        out_shape=jax.ShapeDtypeStruct((D, C, s_pad), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(Xb_p, node_p, vals_p)
    # [D, C, S] -> [n_nodes, D, n_bins, C] (trees.py layout)
    return out[:, :, :S].reshape(D, C, n_nodes, n_bins).transpose(2, 0, 3, 1)
