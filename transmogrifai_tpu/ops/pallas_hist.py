"""Pallas TPU kernel: gradient histograms for tree growth as one-hot MXU matmuls.

The tree grower's inner loop sums per-row gradient/hessian vectors into
(node, feature, bin) cells (ops/trees.py `_histogram`, the RDD treeAggregate analog of
the reference's MLlib/xgboost4j trainers — SURVEY §2.11d/2.12). The jnp fallback is a
`segment_sum`, which XLA lowers to a scatter-add: correct everywhere, but scatters
serialize on TPU.

This kernel reformulates the scatter as dense matmuls, which is what the MXU is for:
for one feature d and a block of rows, build the one-hot membership matrix
M[r, s] = [node(r) * n_bins + bin(r, d) == s] in VMEM and accumulate
out[d] += M^T @ GH — a [S, Bn] x [Bn, C] matmul per (feature, row-block) grid cell.
Row blocks stream through VMEM (grid dim 1, "arbitrary" = sequential, accumulating);
features are independent ("parallel").

VMEM budget per cell: Bn*S one-hot + Bn*C gh + S*C out; with Bn=512, S<=1024 that is
~2.6 MB — well inside the ~16 MB/core budget (pallas_guide.md: Memory Spaces).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


@functools.cache
def use_pallas_histogram() -> bool:
    """Pallas path on by default on TPU backends; force with TT_PALLAS_HIST=0/1."""
    env = os.environ.get("TT_PALLAS_HIST")
    if env is not None:
        return env == "1"
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend init failure
        return False


def _hist_kernel(xb_ref, node_ref, gh_ref, out_ref, *, n_bins: int, n_seg: int):
    """One (feature, row-block) cell: out[d] += onehot(keys)^T @ gh.

    The whole [Bn, D] bin block is resident (TPU blocks can't slice the lane dim
    below 128); this cell's feature column is picked with an iota mask + row-sum —
    a VPU select, far cheaper than the matmul it feeds."""
    d = pl.program_id(0)
    col = jax.lax.broadcasted_iota(jnp.int32, xb_ref.shape, 1) == d
    xb_d = jnp.sum(jnp.where(col, xb_ref[:, :], 0), axis=1)           # [Bn]
    keys = node_ref[:, 0] * n_bins + xb_d                              # [Bn]
    seg = jax.lax.broadcasted_iota(jnp.int32, (keys.shape[0], n_seg), 1)
    onehot = (keys[:, None] == seg).astype(jnp.float32)                # [Bn, S]
    # gh^T @ onehot -> [C, S]: S on the lane axis keeps the MXU wide (C is tiny);
    # HIGHEST precision = true f32 accumulation, bit-comparable to the scatter path
    acc = jax.lax.dot_general(
        gh_ref[:, :], onehot,
        (((0,), (0,)), ((), ())),                                      # contract rows
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )                                                                  # [C, S]

    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[0, :, :] = acc

    @pl.when(pl.program_id(1) > 0)
    def _accum():
        out_ref[0, :, :] += acc


def histogram_pallas(
    vals: jnp.ndarray,
    Xb: jnp.ndarray,
    node: jnp.ndarray,
    n_nodes: int,
    n_bins: int,
    *,
    block_rows: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """Sum vals [N, C] into per-(node, feature, bin) cells -> [n_nodes, D, n_bins, C].

    Drop-in replacement for the segment-sum histogram in ops/trees.py; zero-padded
    rows carry zero gradient mass, so padding never perturbs counts."""
    N, D = Xb.shape
    C = vals.shape[1]
    S = n_nodes * n_bins
    n_blocks = max((N + block_rows - 1) // block_rows, 1)
    pad = n_blocks * block_rows - N
    vals_p = jnp.pad(jnp.asarray(vals, jnp.float32), ((0, pad), (0, 0)))
    Xb_p = jnp.pad(Xb.astype(jnp.int32), ((0, pad), (0, 0)))
    node_p = jnp.pad(node.astype(jnp.int32)[:, None], ((0, pad), (0, 0)))

    out = pl.pallas_call(
        functools.partial(_hist_kernel, n_bins=n_bins, n_seg=S),
        grid=(D, n_blocks),
        in_specs=[
            pl.BlockSpec((block_rows, D), lambda d, r: (r, 0)),   # all features' bins
            pl.BlockSpec((block_rows, 1), lambda d, r: (r, 0)),   # row -> node id
            pl.BlockSpec((block_rows, C), lambda d, r: (r, 0)),   # gradient/hessian
        ],
        out_specs=pl.BlockSpec((1, C, S), lambda d, r: (d, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((D, C, S), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(Xb_p, node_p, vals_p)
    # [D, C, n_nodes * n_bins] -> [n_nodes, D, n_bins, C] (trees.py layout)
    return out.reshape(D, C, n_nodes, n_bins).transpose(2, 0, 3, 1)
