from .linear import (
    LinearParams,
    fit_linear,
    fit_logistic,
    fit_multinomial,
    fit_svc,
    predict_linear,
    predict_logistic,
    predict_multinomial,
    predict_svc,
)

from .trees import (
    TreeEnsembleParams,
    bin_features,
    fit_forest,
    fit_gbt,
    grow_tree,
    predict_ensemble,
    quantile_bins,
)

__all__ = [
    "LinearParams",
    "fit_logistic",
    "predict_logistic",
    "fit_multinomial",
    "predict_multinomial",
    "fit_linear",
    "predict_linear",
    "fit_svc",
    "predict_svc",
    "TreeEnsembleParams",
    "quantile_bins",
    "bin_features",
    "grow_tree",
    "fit_gbt",
    "fit_forest",
    "predict_ensemble",
]
