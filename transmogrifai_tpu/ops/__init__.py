from .linear import (
    LinearParams,
    fit_linear,
    fit_logistic,
    fit_multinomial,
    fit_svc,
    predict_linear,
    predict_logistic,
    predict_multinomial,
    predict_svc,
)

__all__ = [
    "LinearParams",
    "fit_logistic",
    "predict_logistic",
    "fit_multinomial",
    "predict_multinomial",
    "fit_linear",
    "predict_linear",
    "fit_svc",
    "predict_svc",
]
