"""Shared optimizer core: ONE Adam update rule + ZeRO-style sharded state.

Before r10 the bias-corrected Adam step existed three times (ops/linear.py,
ops/mlp.py twice — the full-batch trainer inlined its own copy) and every copy
had to be hand-kept in sync. `adam_update` is now the single rule all of them
delegate to; it is the function the sharded-state path below updates SHARDS
with, so the replicated and sharded trainers cannot drift.

Sharded optimizer state (arXiv 2004.13336, the cross-replica weight-update
sharding this ROADMAP item names; ZeRO stage-1/2 in DeepSpeed vocabulary):
under data parallelism every device holds the SAME f32 master params and Adam
(m, v) — 12 bytes/param replicated N times — and the gradient all-reduce must
complete before any update work starts. Sharding the update instead:

    psum_scatter(grads)  ->  each device owns 1/N of every flat gradient
    local Adam update    ->  on its 1/N shard of (master, m, v)
    all_gather(params)   ->  bf16 compute params for the next forward

Per-device state drops to 12 * ceil(P / N) bytes (+ the transient gathered
compute copy every scheme needs), and because the scatter/update/gather of one
layer is independent of every other layer's, XLA's latency-hiding scheduler
overlaps layer k's reduce with layer k+1's update math — the collectives ride
the same program, not a separate blocking all-reduce pass.

The primitives here are trainer-agnostic: leaves are flattened, padded to a
multiple of the data-axis size, and laid P(DATA_AXIS) so a `shard_map` body
sees its local [P/N] slice. `gather_compute` is the one collective trainers
call in their loss: forward = all_gather of COMPUTE-dtype params (bf16 on the
deep-tabular lane — half the ICI bytes of f32), backward = psum_scatter of the
cotangent in f32 (the reduction never accumulates in bf16).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def adam_update(theta, m, v, g, t, lr_t, b1=0.9, b2=0.999, eps=1e-8):
    """One bias-corrected Adam step over matching pytrees of params/moments/
    grads; `t` is the 1-based step for bias correction, `lr_t` the (possibly
    scheduled) learning rate. THE update rule: the linear GD solvers, the
    streamed LR, all three MLP trainers, and the sharded-state path all
    delegate here so their math can never diverge."""
    m = jax.tree.map(lambda mi, gi: b1 * mi + (1 - b1) * gi, m, g)
    v = jax.tree.map(lambda vi, gi: b2 * vi + (1 - b2) * gi ** 2, v, g)
    theta = jax.tree.map(
        lambda p, mi, vi: p - lr_t * (mi / (1 - b1 ** t))
        / (jnp.sqrt(vi / (1 - b2 ** t)) + eps),
        theta, m, v)
    return theta, m, v


def is_batched(*xs) -> bool:
    """True when any arg is a vmap tracer — mesh/pallas fast paths opt out
    under vmap (the selector's folds x grid batching) and the plain jnp/
    replicated paths serve. Shared by trees and the MLP trainers."""
    try:
        from jax.interpreters.batching import BatchTracer
    except ImportError:  # moved in newer jax
        from jax._src.interpreters.batching import BatchTracer

    return any(isinstance(x, BatchTracer) for x in xs)


# --- sharded flat-state plumbing --------------------------------------------------------

def shard_pinned(shard_optimizer) -> bool:
    """True for the spellings that PIN sharding ("on"): an eager fit with a
    pinned knob refuses to run replicated (resolve_shard_optimizer raises
    without a >1 data axis), which is what justifies oplint OP405's
    exemption — the replicated-state OOM cannot occur, the fit fails fast."""
    return shard_optimizer is True or str(shard_optimizer) in (
        "on", "1", "True", "true")


def resolve_shard_optimizer(mesh, shard_optimizer, *arrays) -> bool:
    """The `shard_optimizer` contract. True (shard the state) iff:

    - a mesh with a data axis > 1 is attached,
    - the fit is not riding a vmap batch axis (the selector's folds x grid
      search programs stay on the replicated path; sharding applies to solo
      fits and the winner refit), and
    - the knob does not force it off ("off"/False/"0").

    "auto" degrades silently: with no mesh / one data device the caller runs
    the EXACT pre-existing replicated path — same function objects, same jit
    caches, bitwise-identical results (pinned by test). "on" is BINDING for
    eager fits: a missing (or 1-device) mesh raises instead of silently
    replicating a state the user declared must shard (vmapped search programs
    still fall back — batched fits cannot shard_map and their per-point state
    is the search's own memory story)."""
    if shard_optimizer in (False, None) or str(shard_optimizer) in ("off", "0"):
        return False
    pinned = shard_pinned(shard_optimizer)
    if not pinned and str(shard_optimizer) != "auto":
        raise ValueError(
            f"shard_optimizer must be auto|on|off, got {shard_optimizer!r}")
    if is_batched(*arrays):
        return False
    from ..mesh import DATA_AXIS

    n_data = 0 if mesh is None else int(mesh.shape[DATA_AXIS])
    if n_data <= 1:
        if pinned:
            raise ValueError(
                "shard_optimizer='on' requires a multi-device mesh (data "
                "axis > 1) — attach one with with_mesh()/train(mesh=), or "
                "use 'auto' to shard opportunistically")
        return False
    return True


def shard_width(size: int, n_shards: int) -> int:
    """Per-device flat width of a `size`-element leaf over n_shards."""
    return -(-size // n_shards)


def flatten_pad(leaf, n_shards: int):
    """[*] leaf -> [n_shards * shard_width] f32 flat, zero-padded."""
    flat = jnp.ravel(leaf).astype(jnp.float32)
    pad = n_shards * shard_width(flat.shape[0], n_shards) - flat.shape[0]
    return jnp.pad(flat, (0, pad)) if pad else flat


def unflatten(flat, shape):
    """Inverse of flatten_pad given the original leaf shape."""
    size = int(np.prod(shape)) if shape else 1
    return flat[:size].reshape(shape)


def shard_state_leaf(mesh, leaf):
    """Place one flat-padded leaf with its (only) axis over DATA_AXIS — the
    storage layout of sharded master params / moments."""
    from ..mesh import DATA_AXIS, record_transfer
    from jax.sharding import NamedSharding, PartitionSpec as P

    flat = flatten_pad(leaf, int(mesh.shape[DATA_AXIS]))
    record_transfer(flat)
    return jax.device_put(flat, NamedSharding(mesh, P(DATA_AXIS)))


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_compute(shard, axis_name: str, dtype):
    """all_gather of a local state shard in the COMPUTE dtype, whose custom
    vjp is psum_scatter of the cotangent in f32 — the ZeRO round trip as one
    differentiable op. bf16 on the wire forward (half the ICI bytes), f32 on
    the wire backward (the cross-device reduction never rounds in bf16)."""
    return jax.lax.all_gather(shard.astype(dtype), axis_name, tiled=True)


def _gather_compute_fwd(shard, axis_name, dtype):
    return gather_compute(shard, axis_name, dtype), None


def _gather_compute_bwd(axis_name, dtype, _res, ct):
    return (jax.lax.psum_scatter(ct.astype(jnp.float32), axis_name,
                                 tiled=True),)


gather_compute.defvjp(_gather_compute_fwd, _gather_compute_bwd)


# --- observability ----------------------------------------------------------------------

def optimizer_state_bytes(n_params: int, sharded: bool, n_shards: int = 1) -> int:
    """Per-device optimizer-state bytes: f32 master params + Adam m + v
    (12 B/param), divided by the shard count when sharded."""
    per = shard_width(int(n_params), int(n_shards)) if sharded else int(n_params)
    return 12 * per


def record_state_bytes(n_params: int, sharded: bool, n_shards: int = 1) -> int:
    """Publish the `train_optimizer_state_bytes{sharded}` gauge (PR-5
    registry; rides AppMetrics' `metrics` section) so the sharding win is
    observable, not asserted. Returns the per-device byte count."""
    from ..obs import metrics as _metrics

    per_device = optimizer_state_bytes(n_params, sharded, n_shards)
    _metrics.default_registry().gauge(
        "train_optimizer_state_bytes",
        help="per-device optimizer-state bytes (f32 master params + Adam m/v) "
             "of the most recent deep-tabular fit",
        labels={"sharded": "1" if sharded else "0"},
    ).set(float(per_device))
    return per_device


def data_axis_size(mesh) -> Optional[int]:
    """Data-axis size of a mesh, or None for unmeshed."""
    if mesh is None:
        return None
    from ..mesh import DATA_AXIS

    return int(mesh.shape[DATA_AXIS])
