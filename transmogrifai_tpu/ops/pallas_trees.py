"""Pallas TPU kernels for at-scale tree training: bin-loop MXU histograms + digitize.

Why these exist (measured on TPU v5e at the gbt_scale shape, 1M rows x 256
features x 64 bins — see docs/performance.md "Tree engine roofline"):

- `histogram_mxu` replaces ops/trees.histogram_binmm for LARGE fits. binmm re-reads
  the binned matrix from HBM once per bin (64x) and leaves the one-hot mask
  materialization to XLA; this kernel loads each row tile into VMEM ONCE and runs
  all bins' mask-build + [M, TN] @ [TN, D] MXU dots from VMEM, with bf16 operands
  and f32 accumulation. Measured 13-19 ms per level (flat across tree depth) vs
  50-76 ms for binmm — ~3.5x on the dominant op of GBT/RF training.
  The per-level cost is FLAT in the node count because every dot's M axis
  (nodes x channels <= 128) occupies one padded MXU tile regardless: this op is
  PADDING-bound, not bandwidth-bound, and that is its roofline (the bin one-hot
  is a rank-n_bins coupling of (row, feature) with bin — it cannot be expressed
  as fewer/fuller matmuls; see the analysis in docs/performance.md).

- `histogram_split_mxu` (r10) FUSES split finding into the histogram program:
  the accumulator lives in a VMEM scratch, and on the last row tile the kernel
  scans candidate bins — cumulative G/H, XGBoost gain, min_child_weight mask,
  per-feature argmax — while the tiles are still on-chip. Only [n_nodes, D]
  split stats return to HBM instead of the full [n_nodes, D, bins, 2C]
  histogram (its writeback + re-read by a second program held the GBT lane at
  0.41 MFU vs the MLP's 0.74, BENCH_r05). Split decisions are bitwise-equal
  to the two-pass path scored on the SAME (mxu) histogram backend
  (ops/trees.grow_tree gates via TT_SPLIT, pinned by test; a different
  backend's f32-exact histograms can legitimately tie-flip candidates inside
  the bf16 rounding gap).

- `digitize_mxu` replaces jnp.searchsorted for LARGE binning. XLA lowers
  vmapped searchsorted to a per-element binary-search while_loop with gathers:
  measured 15.8 SECONDS for 1M x 256 on v5e — 2/3 of the whole gbt_scale fit.
  The kernel reads X once and sums 0/1 threshold compares on the VPU
  (bin = #edges <= x, identical to side="right" binary search for finite x).

Reference provenance: the reference's tree trainers delegate split statistics to
Spark MLlib / xgboost4j treeAggregate reductions (OpGBTClassifier.scala,
OpXGBoostClassifier.scala:48); these kernels are the TPU-native replacement for
that aggregation layer at data scale.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

#: rows per grid step (VMEM tile height) — the hand-measured default
#: (best among 1024/2048/4096 on v5e at the gbt_scale shape). Since the
#: autotune PR this is a real per-call parameter (`row_tile=` on every
#: kernel below, TT_ROW_TILE env as the process default) so the tuner can
#: search it instead of trusting one measurement forever.
ROW_TILE = 2048

#: the ladder `op autotune` searches (tune/space.py); every value must be a
#: positive multiple of 128 (the tile's lane dimension for the transposed
#: node/vals operands — see _resolve_row_tile)
ROW_TILE_CHOICES = (1024, 2048, 4096)

#: VMEM budget for the resident accumulator [n_bins * M, D] f32
_ACC_BYTES_MAX = 8 << 20


def _resolve_row_tile(row_tile: int | None = None) -> int:
    """Effective rows-per-tile: explicit argument > TT_ROW_TILE env > ROW_TILE.

    Tiles must be positive multiples of 128: ROW_TILE rides as the LANE
    dimension of the node/vals blocks ((1, tile) / (V, tile)) and the int8
    sublane dimension of the binned-matrix block — 128 satisfies both
    alignments on current TPUs."""
    tile = int(row_tile or os.environ.get("TT_ROW_TILE", 0) or ROW_TILE)
    if tile <= 0 or tile % 128:
        raise ValueError(
            f"row_tile must be a positive multiple of 128, got {tile}")
    return tile


def histogram_mxu_supported(n_rows: int, n_feats: int, n_nodes: int,
                            n_channels: int, n_bins: int,
                            row_tile: int | None = None) -> bool:
    """Static-shape gate: the accumulator must fit VMEM and bins must be int8.

    `row_tile` participates so the tuner can prune tile candidates with the
    same gate the runtime uses: a tile whose streaming buffers (int8 binned
    block + f32 vals block) would crowd the accumulator out of VMEM is
    infeasible, not merely slow."""
    M = n_nodes * n_channels
    Dp = (n_feats + 127) // 128 * 128
    try:
        tile = _resolve_row_tile(row_tile)
    except ValueError:
        return False
    # double-buffered worst case: 2 tiles of int8 Xb + f32 vals/node stream
    # beside the accumulator; each side gets half the ~16 MB VMEM so the
    # accumulator gate at the default tile is unchanged from before the knob
    stream_bytes = 2 * tile * (Dp + (n_channels + 1) * 4)
    return (n_bins <= 127
            and n_bins * M * Dp * 4 <= _ACC_BYTES_MAX
            and stream_bytes <= _ACC_BYTES_MAX)


def _accumulate_hist(node_ref, vals_ref, xb_ref, acc_ref, *, n_bins, n_nodes,
                     V):
    """One row tile's bin-loop MXU accumulation into acc_ref [n_bins*M, Dp] —
    shared by the histogram-only kernel (acc = the output block) and the fused
    histogram->split kernel (acc = a VMEM scratch that never leaves the chip).
    """
    tn = xb_ref.shape[0]
    # A^T [M, TN] built in VMEM, channel-major: rows v*n_nodes + n hold
    # vals[:, v] masked to rows of node n (pad rows carry node -1 -> all-zero)
    oh_t = (node_ref[:] == jax.lax.broadcasted_iota(
        jnp.int32, (n_nodes, tn), 0)).astype(jnp.bfloat16)
    a_t = jnp.concatenate(
        [oh_t * vals_ref[v:v + 1, :].astype(jnp.bfloat16) for v in range(V)],
        axis=0)
    xb = xb_ref[:].astype(jnp.int32)  # int8 compares unsupported on v5e mosaic
    M = V * n_nodes
    for b in range(n_bins):
        mask = (xb == b).astype(jnp.bfloat16)
        acc_ref[b * M:(b + 1) * M, :] += jax.lax.dot_general(
            a_t, mask, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


def _hist_kernel(node_ref, vals_ref, xb_ref, out_ref, *, n_bins, n_nodes, V):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    _accumulate_hist(node_ref, vals_ref, xb_ref, out_ref,
                     n_bins=n_bins, n_nodes=n_nodes, V=V)


def histogram_mxu(vals: jnp.ndarray, Xb: jnp.ndarray, node: jnp.ndarray,
                  n_nodes: int, n_bins: int, *, row_tile: int | None = None,
                  interpret: bool = False) -> jnp.ndarray:
    """Sum vals [N, V] into per-(node, feature, bin) cells -> [n_nodes, D, n_bins, V].

    Drop-in for ops/trees._histogram at large shapes. Operands are bf16 with f32
    accumulation (masks are exact in bf16; vals round at ~2^-9 relative — split
    GAINS see that rounding, leaf VALUES never do, they are refit in f32 by the
    caller). Rows pad with node=-1 (zero mass), features pad with bin -1
    (matches no bin). `row_tile` picks the VMEM tile height (default
    TT_ROW_TILE env, then ROW_TILE) — the knob `op autotune` searches."""
    if n_bins > 127:
        # bins ride int8 through HBM; a forced TT_HIST=mxu with wide bins
        # must fail loudly, not silently drop the mass of bins >= 128
        raise ValueError(f"histogram_mxu supports n_bins <= 127, got {n_bins}")
    tile = _resolve_row_tile(row_tile)
    N, D = Xb.shape
    V = vals.shape[1]
    M = V * n_nodes
    row_pad = (-N) % tile
    f_pad = (-D) % 128
    Dp = D + f_pad
    xb8 = jnp.pad(Xb.astype(jnp.int8), ((0, row_pad), (0, f_pad)),
                  constant_values=-1)
    node_p = jnp.pad(node.astype(jnp.int32), (0, row_pad), constant_values=-1)
    vals_p = jnp.pad(jnp.asarray(vals, jnp.float32), ((0, row_pad), (0, 0)))

    out = pl.pallas_call(
        functools.partial(_hist_kernel, n_bins=n_bins, n_nodes=n_nodes, V=V),
        grid=((N + row_pad) // tile,),
        in_specs=[
            pl.BlockSpec((1, tile), lambda i: (0, i)),
            pl.BlockSpec((V, tile), lambda i: (0, i)),
            pl.BlockSpec((tile, Dp), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((n_bins * M, Dp), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_bins * M, Dp), jnp.float32),
        interpret=interpret,
    )(node_p[None, :], vals_p.T, xb8)
    return out.reshape(n_bins, V, n_nodes, Dp).transpose(2, 3, 0, 1)[:, :D]


_SPLIT_EPS = 1e-8  # MUST equal ops/trees._EPS: gains are compared across paths


def fused_split_supported(n_rows: int, n_feats: int, n_nodes: int,
                          n_channels: int, n_bins: int,
                          row_tile: int | None = None) -> bool:
    """Static-shape gate for the fused histogram->split kernel: the histogram
    accumulator (now a VMEM scratch, not an output) must fit the same budget,
    and there must be at least one candidate bin."""
    return n_bins >= 2 and histogram_mxu_supported(
        n_rows, n_feats, n_nodes, n_channels, n_bins, row_tile)


def _scan_best_split(cell, lam, mcw, *, n_bins, n_nodes, V):
    """The candidate-bin scan shared by every fused split consumer: `cell(b, v)`
    reads the [n_nodes, Dp] histogram slab of (bin b, channel v) — from the
    fused kernel's VMEM scratch, or from a psum-merged histogram under the
    data-axis shard_map (r14). One arithmetic, one tie-break rule (strict ->
    update = argmax-first-max), so decisions agree bitwise across all of them
    when scored on the same histogram values."""
    C = V // 2  # channels: first C are gradients, last C hessians
    tot = []  # per-node totals per channel (the Gt/Ht of the gain)
    for v in range(V):
        t = cell(0, v)
        for b in range(1, n_bins):
            t = t + cell(b, v)
        tot.append(t)
    sT = sum(tot[c] ** 2 / (tot[C + c] + lam + _SPLIT_EPS)
             for c in range(C))
    cum = [cell(0, v) for v in range(V)]  # inclusive cumsum at bin 0
    best_gain = jnp.full(cum[0].shape, -jnp.inf, jnp.float32)
    best_bin = jnp.zeros(cum[0].shape, jnp.int32)
    for b in range(n_bins - 1):  # last bin is never a valid split
        if b > 0:
            cum = [cum[v] + cell(b, v) for v in range(V)]
        sL = sum(cum[c] ** 2 / (cum[C + c] + lam + _SPLIT_EPS)
                 for c in range(C))
        sR = sum((tot[c] - cum[c]) ** 2
                 / ((tot[C + c] - cum[C + c]) + lam + _SPLIT_EPS)
                 for c in range(C))
        hl = sum(cum[C + c] for c in range(C))
        hr = sum(tot[C + c] - cum[C + c] for c in range(C))
        g = jnp.where((hl >= mcw) & (hr >= mcw), sL + sR - sT, -jnp.inf)
        upd = g > best_gain  # strict: first max wins, like argmax
        best_gain = jnp.where(upd, g, best_gain)
        best_bin = jnp.where(upd, b, best_bin)
    return best_gain, best_bin


def _hist_split_kernel(node_ref, vals_ref, xb_ref, scal_ref, gain_ref,
                       bin_ref, acc_ref, *, n_bins, n_nodes, V):
    """Fused histogram build + split finding: grid steps accumulate row tiles
    into the VMEM scratch accumulator; the LAST step scans candidate bins
    while the tiles are still in VMEM and writes only the per-(node, feature)
    best (gain, bin) back to HBM. The full [nodes, D, bins, 2C] histogram
    never exists off-chip — the HBM writeback + re-read that held the
    two-program path to 0.41 MFU (BENCH_r05) disappears.

    The bin scan mirrors ops/trees.grow_tree's two-pass math term for term
    (inclusive cumulative G/H, XGBoost gain G^2/(H+lam), min_child_weight
    masking, strict-> update = argmax-first-max tie-breaking), so split
    DECISIONS are bitwise-equal to the two-pass path scored on the same
    histogram backend — pinned by test."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    _accumulate_hist(node_ref, vals_ref, xb_ref, acc_ref,
                     n_bins=n_bins, n_nodes=n_nodes, V=V)

    @pl.when(i == pl.num_programs(0) - 1)
    def _split():
        M = V * n_nodes
        lam = scal_ref[0, 0]
        mcw = scal_ref[0, 1]

        def cell(b, v):  # [n_nodes, Dp] histogram slab of (bin b, channel v)
            return acc_ref[b * M + v * n_nodes:b * M + (v + 1) * n_nodes, :]

        best_gain, best_bin = _scan_best_split(
            cell, lam, mcw, n_bins=n_bins, n_nodes=n_nodes, V=V)
        gain_ref[:] = best_gain
        bin_ref[:] = best_bin


def histogram_split_mxu(vals: jnp.ndarray, Xb: jnp.ndarray, node: jnp.ndarray,
                        n_nodes: int, n_bins: int, reg_lambda,
                        min_child_weight, *, row_tile: int | None = None,
                        interpret: bool = False):
    """Fused per-(node, feature) split finding over vals [N, 2C] (g then h
    channels) -> (best_gain [n_nodes, D] f32, best_bin [n_nodes, D] int32).

    Same operand discipline as histogram_mxu (bf16 masks/vals, f32
    accumulation, node -1 row pads, bin -1 feature pads); reg_lambda and
    min_child_weight ride as TRACED scalars through a tiny SMEM-shaped input,
    so the selector's hyperparameter values never force a recompile. The
    feature-mask (colsample) and min_gain gates stay OUTSIDE: both are
    per-(node, feature) decisions the caller applies to the returned stats.
    Padded feature columns return gain 0 at hl=hr=0 — callers slice [:, :D]
    (done here) so they never reach an argmax."""
    if n_bins > 127:
        raise ValueError(
            f"histogram_split_mxu supports n_bins <= 127, got {n_bins}")
    from jax.experimental.pallas import tpu as pltpu

    tile = _resolve_row_tile(row_tile)
    N, D = Xb.shape
    V = vals.shape[1]
    M = V * n_nodes
    row_pad = (-N) % tile
    f_pad = (-D) % 128
    Dp = D + f_pad
    xb8 = jnp.pad(Xb.astype(jnp.int8), ((0, row_pad), (0, f_pad)),
                  constant_values=-1)
    node_p = jnp.pad(node.astype(jnp.int32), (0, row_pad), constant_values=-1)
    vals_p = jnp.pad(jnp.asarray(vals, jnp.float32), ((0, row_pad), (0, 0)))
    scal = jnp.stack([jnp.asarray(reg_lambda, jnp.float32),
                      jnp.asarray(min_child_weight, jnp.float32)]).reshape(1, 2)

    gain, best_bin = pl.pallas_call(
        functools.partial(_hist_split_kernel, n_bins=n_bins, n_nodes=n_nodes,
                          V=V),
        grid=((N + row_pad) // tile,),
        in_specs=[
            pl.BlockSpec((1, tile), lambda i: (0, i)),
            pl.BlockSpec((V, tile), lambda i: (0, i)),
            pl.BlockSpec((tile, Dp), lambda i: (i, 0)),
            pl.BlockSpec((1, 2), lambda i: (0, 0)),
        ],
        out_specs=[pl.BlockSpec((n_nodes, Dp), lambda i: (0, 0)),
                   pl.BlockSpec((n_nodes, Dp), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((n_nodes, Dp), jnp.float32),
                   jax.ShapeDtypeStruct((n_nodes, Dp), jnp.int32)],
        scratch_shapes=[pltpu.VMEM((n_bins * M, Dp), jnp.float32)],
        interpret=interpret,
    )(node_p[None, :], vals_p.T, xb8, scal)
    return gain[:, :D], best_bin[:, :D]


def _hist_partial_kernel(node_hbm, vals_hbm, xb_hbm, out_ref, *, n_bins,
                         n_nodes, V, n_tiles, row_tile):
    """Per-shard partial histogram with MANUAL double-buffered DMA (r14): the
    inputs stay in ANY/HBM memory space and row tiles stream through a 2-slot
    VMEM scratch — tile t+1's copy is IN FLIGHT while tile t runs its bin-loop
    MXU accumulation, so under the data-axis shard_map round k+1's histogram
    DMA overlaps round k's compute/split consumption instead of serializing
    behind it (the automatic-pipelining analog of the gridded kernels, written
    out by hand because this kernel owns its own tile loop). The accumulator
    IS the output block [n_bins*V*n_nodes, Dp]: it lives in VMEM for the whole
    program and is written back once."""
    from jax.experimental.pallas import tpu as pltpu

    out_ref[:] = jnp.zeros_like(out_ref)
    dp = xb_hbm.shape[1]

    def body(node_buf, vals_buf, xb_buf, sems):
        def copies(t, slot):
            return (
                pltpu.make_async_copy(
                    node_hbm.at[:, pl.ds(t * row_tile, row_tile)],
                    node_buf.at[slot], sems.at[slot, 0]),
                pltpu.make_async_copy(
                    vals_hbm.at[:, pl.ds(t * row_tile, row_tile)],
                    vals_buf.at[slot], sems.at[slot, 1]),
                pltpu.make_async_copy(
                    xb_hbm.at[pl.ds(t * row_tile, row_tile), :],
                    xb_buf.at[slot], sems.at[slot, 2]),
            )

        for c in copies(0, 0):  # warm-up: slot 0's DMA starts before the loop
            c.start()

        def step(t, carry):
            slot = jax.lax.rem(t, 2)

            @pl.when(t + 1 < n_tiles)
            def _prefetch():  # next tile -> other slot, overlapping this tile
                for c in copies(t + 1, jax.lax.rem(t + 1, 2)):
                    c.start()

            for c in copies(t, slot):
                c.wait()
            _accumulate_hist(node_buf.at[slot], vals_buf.at[slot],
                             xb_buf.at[slot], out_ref,
                             n_bins=n_bins, n_nodes=n_nodes, V=V)
            return carry

        jax.lax.fori_loop(0, n_tiles, step, 0)

    pl.run_scoped(body,
                  node_buf=pltpu.VMEM((2, 1, row_tile), jnp.int32),
                  vals_buf=pltpu.VMEM((2, V, row_tile), jnp.float32),
                  xb_buf=pltpu.VMEM((2, row_tile, dp), jnp.int8),
                  sems=pltpu.SemaphoreType.DMA((2, 3)))


def histogram_partial_flat_mxu(vals: jnp.ndarray, Xb: jnp.ndarray,
                               node: jnp.ndarray, n_nodes: int, n_bins: int, *,
                               row_tile: int | None = None,
                               interpret: bool = False) -> jnp.ndarray:
    """One device's PARTIAL histogram over its row shard, in the flat VMEM
    layout [n_bins * V * n_nodes, D] f32 (row b*M + v*n_nodes + n = bin b,
    channel v, node n — the layout `_scan_best_split` cells index). The
    data-axis sharded split path (ops/trees._data_axis_hist_split) calls this
    per device inside shard_map, psums the flat stats over DATA_AXIS, and
    scans the merged histogram with `split_scan_mxu` — only [n_nodes, D]
    (gain, bin) ever leaves that program. Same operand discipline as
    histogram_mxu (bf16 masks/vals, f32 accumulation, node -1 row pads,
    bin -1 feature pads)."""
    if n_bins > 127:
        raise ValueError(
            f"histogram_partial_flat_mxu supports n_bins <= 127, got {n_bins}")
    from jax.experimental.pallas import tpu as pltpu

    tile = _resolve_row_tile(row_tile)
    N, D = Xb.shape
    V = vals.shape[1]
    M = V * n_nodes
    row_pad = (-N) % tile
    f_pad = (-D) % 128
    Dp = D + f_pad
    xb8 = jnp.pad(Xb.astype(jnp.int8), ((0, row_pad), (0, f_pad)),
                  constant_values=-1)
    node_p = jnp.pad(node.astype(jnp.int32), (0, row_pad), constant_values=-1)
    vals_p = jnp.pad(jnp.asarray(vals, jnp.float32), ((0, row_pad), (0, 0)))

    out = pl.pallas_call(
        functools.partial(_hist_partial_kernel, n_bins=n_bins,
                          n_nodes=n_nodes, V=V,
                          n_tiles=(N + row_pad) // tile, row_tile=tile),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 3,
        out_specs=pl.BlockSpec((n_bins * M, Dp), lambda: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_bins * M, Dp), jnp.float32),
        interpret=interpret,
    )(node_p[None, :], vals_p.T, xb8)
    return out[:, :D]


def _split_scan_kernel(hist_ref, scal_ref, gain_ref, bin_ref, *, n_bins,
                       n_nodes, V):
    M = V * n_nodes

    def cell(b, v):  # [n_nodes, Dp] slab of (bin b, channel v)
        return hist_ref[b * M + v * n_nodes:b * M + (v + 1) * n_nodes, :]

    best_gain, best_bin = _scan_best_split(
        cell, scal_ref[0, 0], scal_ref[0, 1],
        n_bins=n_bins, n_nodes=n_nodes, V=V)
    gain_ref[:] = best_gain
    bin_ref[:] = best_bin


def split_scan_mxu(hist_flat: jnp.ndarray, n_nodes: int, n_bins: int,
                   reg_lambda, min_child_weight, *, interpret: bool = False):
    """Split scan over an ALREADY-MERGED flat histogram [n_bins*V*n_nodes, D]
    (the psum epilogue of the data-axis sharded path) -> (best_gain
    [n_nodes, D] f32, best_bin [n_nodes, D] int32). Identical arithmetic and
    tie-breaking to the fused kernel's last-step scan (`_scan_best_split` is
    shared), so the sharded path's split decisions match the unmeshed fused
    path's wherever the merged histograms tie-break identically. Padded
    feature columns behave as in histogram_split_mxu (gain 0 at hl=hr=0,
    sliced off here)."""
    MB, D = hist_flat.shape
    V = MB // (n_bins * n_nodes)
    f_pad = (-D) % 128
    Dp = D + f_pad
    hp = jnp.pad(jnp.asarray(hist_flat, jnp.float32), ((0, 0), (0, f_pad)))
    scal = jnp.stack([jnp.asarray(reg_lambda, jnp.float32),
                      jnp.asarray(min_child_weight, jnp.float32)]).reshape(1, 2)
    gain, best_bin = pl.pallas_call(
        functools.partial(_split_scan_kernel, n_bins=n_bins, n_nodes=n_nodes,
                          V=V),
        in_specs=[pl.BlockSpec((MB, Dp), lambda: (0, 0)),
                  pl.BlockSpec((1, 2), lambda: (0, 0))],
        out_specs=[pl.BlockSpec((n_nodes, Dp), lambda: (0, 0)),
                   pl.BlockSpec((n_nodes, Dp), lambda: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((n_nodes, Dp), jnp.float32),
                   jax.ShapeDtypeStruct((n_nodes, Dp), jnp.int32)],
        interpret=interpret,
    )(hp, scal)
    return gain[:, :D], best_bin[:, :D]


def _digitize_kernel(x_ref, edges_ref, out_ref, *, n_cuts):
    x = x_ref[:]
    acc = jnp.zeros(x.shape, jnp.int32)
    for b in range(n_cuts):
        acc += (x >= edges_ref[b:b + 1, :]).astype(jnp.int32)
    out_ref[:] = acc


def digitize_mxu(X: jnp.ndarray, edges: jnp.ndarray, *,
                 row_tile: int | None = None,
                 interpret: bool = False) -> jnp.ndarray:
    """Per-feature digitize: X [N, D] f32 vs edges [D, B-1] -> int32 bins.

    bin = #{edges[d] <= x}: identical to searchsorted(side="right") for finite
    x and monotone edges (ties included on both). NaN lands in bin 0 (an
    all-false compare), not the last bin — upstream kernels impute before
    binning, so this is unobservable in practice. One pass over X on the VPU."""
    tile = _resolve_row_tile(row_tile)
    N, D = X.shape
    n_cuts = edges.shape[1]
    row_pad = (-N) % tile
    f_pad = (-D) % 128
    Xp = jnp.pad(jnp.asarray(X, jnp.float32), ((0, row_pad), (0, f_pad)))
    # padded feature columns: +inf edges -> every x in bin 0
    ep = jnp.pad(jnp.asarray(edges, jnp.float32).T, ((0, 0), (0, f_pad)),
                 constant_values=jnp.inf)  # [B-1, Dp]
    out = pl.pallas_call(
        functools.partial(_digitize_kernel, n_cuts=n_cuts),
        grid=((N + row_pad) // tile,),
        in_specs=[
            pl.BlockSpec((tile, D + f_pad), lambda i: (i, 0)),
            pl.BlockSpec((n_cuts, D + f_pad), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile, D + f_pad), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N + row_pad, D + f_pad), jnp.int32),
        interpret=interpret,
    )(Xp, ep)
    return out[:N, :D]
