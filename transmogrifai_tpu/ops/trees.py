"""Histogram-based tree ensembles: pure jnp, fixed-depth, vmap/pjit-safe.

TPU-native replacement for the reference's tree workhorses — OpRandomForestClassifier /
OpGBTClassifier / OpDecisionTreeClassifier / OpXGBoostClassifier and the regressor twins
(reference wrappers at core/.../impl/classification/OpRandomForestClassifier.scala,
OpGBTClassifier.scala, OpXGBoostClassifier.scala:48 delegate to Spark MLlib / xgboost4j
trainers whose split statistics are RDD treeAggregate reductions; SURVEY §2.11d flags
this family as the credibility-deciding component).

Design (SURVEY §7 "Trees on TPU"): data-dependent recursive partitioning is reformulated
as *level-wise growth of perfect binary trees of fixed depth* so every step has static
shapes and no data-dependent control flow:

  1. quantile-bin each feature once -> Xb [N, D] int32 (n_bins buckets);
  2. at level t, every row carries its node id in [0, 2^t); per-(node, feature, bin)
     gradient/hessian histograms are ONE flat segment-sum (the treeAggregate analog —
     under a row-sharded mesh this psums partial histograms over ICI);
  3. split gain for ALL (node, feature, bin) candidates at once via cumulative sums
     over bins (XGBoost-style second-order gain G^2/(H+lambda));
  4. rows route to children with a gather; nodes that fail min-gain/min-weight keep a
     dummy all-left split (threshold +inf), so the tree stays perfect;
  5. leaves hold multi-output values [C] — one tree serves multiclass/one-hot targets
     (no per-class tree loops on device).

Boosting (GBT/XGBoost) runs trees under lax.scan with the margin as carry; forests
(RF/DT) scan over independent bootstrap keys. Hyperparameters that enter arithmetic only
(learning_rate, reg_lambda, min_child_weight, min_gain) are traced scalars, so the
ModelSelector can vmap grid points over them; depth / tree count / bins are static.
"""
from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from .backend import backend_is_tpu

_EPS = 1e-8


class TreeEnsembleParams(NamedTuple):
    """A stack of perfect binary trees of equal depth.

    split_feature   [T, 2^depth - 1] int32  — heap-ordered internal nodes
    split_threshold [T, 2^depth - 1] float32 — go right iff x >= threshold
    leaf_values     [T, 2^depth, C] float32
    base            [C] float32 — ensemble offset (boosting margin init / 0 for forests)
    """

    split_feature: jnp.ndarray
    split_threshold: jnp.ndarray
    leaf_values: jnp.ndarray
    base: jnp.ndarray
    #: [D] total split gain per feature summed over all trees (the XGBoost
    #: "total_gain" importance; reference ModelInsights.scala:72-391 reports
    #: featureImportances for every Spark tree model). None on pre-r5 params.
    feature_gain: Optional[jnp.ndarray] = None


#: above this many rows, quantile edges come from a strided row sketch — the
#: xgboost "approx sketch" analog; 128k rows bound the per-feature quantile
#: error at ~O(1e-3) while cutting the O(N log N) per-feature sorts ~8x at 1M
_QUANTILE_SKETCH_ROWS = 1 << 17

#: N*D threshold for the pallas at-scale kernels (selector fits stay below it,
#: so the nested folds x grid vmap never sees a pallas_call)
_PALLAS_MIN_ELEMS = 1 << 24


def _is_batched(*xs) -> bool:
    """True when any arg is a vmap tracer — the pallas paths opt out under
    vmap (the selector's folds x grid batching) and the jnp paths serve.
    (Shared rule: ops/optimizer.is_batched.)"""
    from .optimizer import is_batched

    return is_batched(*xs)


def _fused_split_supported(n_rows: int, n_feats: int, n_nodes: int,
                           n_channels: int, n_bins: int,
                           row_tile: Optional[int] = None) -> bool:
    from .pallas_trees import fused_split_supported

    return fused_split_supported(n_rows, n_feats, n_nodes, n_channels, n_bins,
                                 row_tile)


def _env_row_tile() -> int:
    """TT_ROW_TILE resolved to a literal at CALL time (0 = kernel default).

    fit_gbt/fit_forest bake this into their jit static args: two fits that
    differ only in the env knob must compile two programs, not silently share
    one cache entry — the property `op autotune`'s measured trials rely on
    (the kernels themselves read the env only at trace time)."""
    return int(os.environ.get("TT_ROW_TILE", 0) or 0)


def _model_axis_constraint(mesh, Xb, edges):
    """Lay the FEATURE axis of the binned matrix (and its edges) over the mesh
    MODEL axis: per-(feature, bin) histogram columns and per-feature split
    scans are independent, so GSPMD partitions every boosting round's
    histogram + split work across the model axis from this one annotation —
    the tree-lane counterpart of the MLP state sharding (rows keep whatever
    DATA_AXIS sharding they arrived with only when the model axis is idle;
    dual-axis sharding replays the PR-4 SPMD miscompile class, so feature
    sharding takes precedence here). Returns (Xb, edges, sharded?)."""
    from ..mesh import MODEL_AXIS

    if mesh is None:
        return Xb, edges, False
    n_model = int(mesh.shape[MODEL_AXIS])
    D = Xb.shape[1]
    if n_model <= 1 or D % n_model != 0 or _is_batched(Xb, edges):
        return Xb, edges, False
    from jax.sharding import NamedSharding, PartitionSpec as P

    Xb = jax.lax.with_sharding_constraint(
        Xb, NamedSharding(mesh, P(None, MODEL_AXIS)))
    edges = jax.lax.with_sharding_constraint(
        edges, NamedSharding(mesh, P(MODEL_AXIS, None)))
    return Xb, edges, True


def _data_axis_hist_split(mesh, gh, Xb, node, n_nodes, n_bins, reg_lambda,
                          min_child_weight, feature_sharded: bool,
                          hist_mode: Optional[str] = None,
                          row_tile: Optional[int] = None):
    """Data-axis sharded fused split finding (r14): one shard_map over the
    FULL mesh per tree level. Each device accumulates a partial histogram
    over its row shard — on TPU via the double-buffered-DMA pallas kernel
    (pallas_trees.histogram_partial_flat_mxu), off-TPU/forced via the jnp
    decompositions reshaped to the same flat [n_bins*2C*n_nodes, D_local]
    VMEM layout — then ONE psum over DATA_AXIS merges the stats over ICI
    (the in-network aggregate-then-reduce structure, PAPERS.md 1903.06701)
    and the split scan (pallas_trees.split_scan_mxu, sharing the fused
    kernel's `_scan_best_split` arithmetic) runs on the merged histogram.
    Only [n_nodes, D] (gain, best_bin) ever leaves the program, exactly like
    the unmeshed fused kernel.

    Composes data x model: with `feature_sharded` the feature axis of Xb
    additionally lays over MODEL_AXIS (the existing _model_axis_constraint
    placement) and each model group scans only its D/n_model feature slab —
    the psum stays within each model group's data-axis column.

    shard_map runs with replication checking off (mesh_shard_map): the body
    carries pallas_calls, for which no replication rule exists; output
    consistency across the data axis is established by the psum itself."""
    from jax.sharding import PartitionSpec as P

    from ..mesh import DATA_AXIS, MODEL_AXIS, mesh_shard_map
    from .pallas_trees import (fused_split_supported,
                               histogram_partial_flat_mxu, split_scan_mxu)

    N, D = Xb.shape
    V = gh.shape[1]
    n_data = int(mesh.shape[DATA_AXIS])
    n_model = int(mesh.shape[MODEL_AXIS])
    d_local = D // n_model if feature_sharded else D
    tpu = backend_is_tpu()
    mode = hist_mode if hist_mode is not None else os.environ.get("TT_HIST")
    if mode is None:
        # same resolution as _histogram, on the PER-DEVICE shard shapes: the
        # partial-accumulate pallas kernel where its VMEM accumulator fits,
        # else the partitioner-friendly jnp decompositions
        if tpu:
            mode = ("mxu" if fused_split_supported(
                -(-N // n_data), d_local, n_nodes, V, n_bins,
                row_tile) else "binmm")
        else:
            mode = "segsum"
    scal = jnp.stack([jnp.asarray(reg_lambda, jnp.float32),
                      jnp.asarray(min_child_weight, jnp.float32)]
                     ).reshape(1, 2)

    def body(gh_l, xb_l, node_l, scal_l):
        if mode == "mxu":
            part = histogram_partial_flat_mxu(gh_l, xb_l, node_l, n_nodes,
                                              n_bins, row_tile=row_tile,
                                              interpret=not tpu)
        else:
            hist4 = (histogram_binmm if mode == "binmm"
                     else histogram_segment_sum)(
                gh_l, xb_l, node_l, n_nodes, n_bins)
            # [n_nodes, d, bins, V] -> the flat layout the scan kernel indexes
            part = hist4.transpose(2, 3, 0, 1).reshape(
                n_bins * V * n_nodes, -1)
        merged = jax.lax.psum(part, DATA_AXIS)
        return split_scan_mxu(merged, n_nodes, n_bins, scal_l[0, 0],
                              scal_l[0, 1], interpret=not tpu)

    fspec = MODEL_AXIS if feature_sharded else None
    fn = mesh_shard_map(
        body, mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, fspec), P(DATA_AXIS),
                  P(None, None)),
        out_specs=(P(None, fspec), P(None, fspec)))
    return fn(gh, Xb, node, scal)


def _pad_rows_weight0(Xb, Y, w, pad: int):
    """Grow the row axis by `pad` zero-WEIGHT copies of row 0 so it divides
    the mesh data axis (XLA needs even shards). Weight-0 rows contribute
    exactly zero gradient/hessian mass — histograms and leaf sums see only
    real rows — and the repeated bin values introduce no new categories, so
    split decisions are preserved (gains move by reduction-order ulp at
    most). Callers compute quantile edges and the objective's base/wsum on
    the ORIGINAL rows first: those see raw values/weights, not masses."""
    Xb = jnp.concatenate([Xb, jnp.repeat(Xb[:1], pad, axis=0)])
    Y = jnp.concatenate([Y, jnp.repeat(Y[:1], pad, axis=0)])
    w = jnp.concatenate([w, jnp.zeros(pad, w.dtype)])
    return Xb, Y, w


def quantile_bins(X: jnp.ndarray, n_bins: int) -> jnp.ndarray:
    """Per-feature quantile bin edges -> [D, n_bins - 1].

    Above _QUANTILE_SKETCH_ROWS rows a strided subsample estimates the
    quantiles (deterministic, no RNG): at 1M x 256 the exact per-feature sorts
    cost ~0.7 s on v5e for edges whose placement is statistically identical."""
    X = jnp.asarray(X, jnp.float32)
    n = X.shape[0]
    if n > _QUANTILE_SKETCH_ROWS and not _is_batched(X):
        stride = -(-n // _QUANTILE_SKETCH_ROWS)
        X = X[::stride]
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return jnp.quantile(X, qs, axis=0).T


def bin_features(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """Digitize X [N, D] against per-feature edges [D, B-1] -> int32 bins in [0, B-1].

    bin b means edges[b-1] <= x < edges[b], so the split "bin <= b goes left" is
    exactly "x < edges[b]" on raw values — inference never re-bins.

    Implementation: bin = #{edges <= x}, summed threshold compares under a
    lax.scan (one compare pass per edge). NOT searchsorted: XLA lowers vmapped
    binary search to a per-element while_loop with gathers — measured 15.8 s
    for 1M x 256 on v5e vs ~0.2 s for the compare scan and ~10 ms for the
    pallas single-pass kernel (digitize_mxu), which takes over on TPU at
    large static shapes."""
    X = jnp.asarray(X, jnp.float32)
    if (backend_is_tpu() and X.size >= _PALLAS_MIN_ELEMS
            and not _is_batched(X, edges)):
        from .pallas_trees import digitize_mxu

        return digitize_mxu(X, edges)

    def step(acc, eb):  # eb [D]: one edge per feature
        return acc + (X >= eb[None, :]).astype(jnp.int32), None

    acc, _ = jax.lax.scan(step, jnp.zeros(X.shape, jnp.int32),
                          jnp.asarray(edges, jnp.float32).T, unroll=8)
    return acc


def _histogram(vals: jnp.ndarray, Xb: jnp.ndarray, node: jnp.ndarray,
               n_nodes: int, n_bins: int, mode: Optional[str] = None,
               row_tile: Optional[int] = None) -> jnp.ndarray:
    """Sum `vals` [N, C] into per-(node, feature, bin) cells -> [n_nodes, D, n_bins, C].

    Default paths on TPU: the pallas bin-loop MXU kernel (pallas_trees.
    histogram_mxu — reads each row tile into VMEM once, ~3.5x binmm, flat in
    tree depth) for LARGE unbatched shapes, else the bin-wise MXU matmul
    decomposition (histogram_binmm), which never materializes the [N, S]
    one-hot: per bin b, one [nodes*C, N] @ [N, D] matmul whose mask operand is
    an elementwise compare XLA fuses into the matmul read. Non-TPU backends
    default to the segment-sum (CPU scatter-add beats CPU dense matmuls; binmm
    parity has its own test). TT_HIST=binmm|mxu|segsum forces a
    specific path. All paths are collectives-safe: partial histograms psum
    across a row-sharded mesh axis (the RDD treeAggregate replacement, SURVEY
    §2.12).

    NOTE: the mode is read at TRACE time — jit caches bake the chosen path per
    shape, so set TT_HIST before the first fit of a process (changing it later
    only affects not-yet-compiled shapes). An explicit `mode` overrides the
    env (how the mesh model-axis path pins the GSPMD-partitionable jnp
    decompositions — a pallas_call is opaque to the SPMD partitioner)."""
    if mode is None:
        mode = os.environ.get("TT_HIST")
    if mode is None:
        if backend_is_tpu():
            from .pallas_trees import histogram_mxu_supported

            big = (Xb.size >= _PALLAS_MIN_ELEMS
                   and not _is_batched(vals, Xb, node)
                   and histogram_mxu_supported(Xb.shape[0], Xb.shape[1],
                                               n_nodes, vals.shape[1], n_bins,
                                               row_tile))
            mode = "mxu" if big else "binmm"
        else:
            mode = "segsum"
    if mode == "mxu":
        from .pallas_trees import histogram_mxu

        # interpret mode off-TPU: lets a forced TT_HIST=mxu run anywhere —
        # how the fused-vs-two-pass equality tests pin BOTH paths to the
        # same (bf16-operand) histogram accumulation on the CPU suite
        return histogram_mxu(vals, Xb, node, n_nodes, n_bins,
                             row_tile=row_tile,
                             interpret=not backend_is_tpu())
    if mode == "segsum":
        return histogram_segment_sum(vals, Xb, node, n_nodes, n_bins)
    if mode != "binmm":
        # the r2 showcase "pallas" one-hot kernel was deleted in r5: it
        # measured 4x SLOWER than binmm (BENCH_r04 hist_kernel); the winning
        # pallas path is "mxu" (pallas_trees.histogram_mxu)
        raise ValueError(
            f"TT_HIST={mode!r}: expected binmm | mxu | segsum")
    return histogram_binmm(vals, Xb, node, n_nodes, n_bins)




def histogram_binmm(vals: jnp.ndarray, Xb: jnp.ndarray, node: jnp.ndarray,
                    n_nodes: int, n_bins: int) -> jnp.ndarray:
    """Bin-wise matmul histogram: hist[n,d,b,c] = sum_r node1h[r,n]*gh[r,c]*(Xb[r,d]==b).

    Folding (node, channel) into one small lane axis A = node1h (x) gh [N, n*C]
    turns each bin into ONE dense matmul A^T @ (Xb==b) — the MXU does the
    reduction, no scatter, no [N, n*bins] one-hot ever materializes. The scan
    over bins is unrolled 8-wide so XLA overlaps mask builds with matmuls.
    This is the TPU default for SMALL/batched shapes (it vmaps under the
    selector's folds x grid); large fits route to pallas_trees.histogram_mxu,
    which avoids this path's per-bin HBM re-read of Xb (~3.5x at 1M x 256)."""
    N, D = Xb.shape
    C = vals.shape[1]
    node1h = jax.nn.one_hot(node, n_nodes, dtype=jnp.float32)  # [-1 pad rows -> 0]
    A = (node1h[:, :, None] * jnp.asarray(vals, jnp.float32)[:, None, :]
         ).reshape(N, n_nodes * C)
    Xb8 = Xb.astype(jnp.int8) if n_bins <= 127 else Xb  # 4x less mask-read traffic

    def step(_, b):
        maskb = (Xb8 == b).astype(jnp.float32)
        return None, jnp.matmul(A.T, maskb, precision=jax.lax.Precision.HIGHEST)

    _, hist = jax.lax.scan(step, None, jnp.arange(n_bins, dtype=Xb8.dtype),
                           unroll=8)  # [bins, n*C, D]
    return hist.reshape(n_bins, n_nodes, C, D).transpose(1, 3, 0, 2)


def histogram_segment_sum(vals: jnp.ndarray, Xb: jnp.ndarray, node: jnp.ndarray,
                          n_nodes: int, n_bins: int) -> jnp.ndarray:
    """Portable scatter-add histogram (the non-pallas path; also the baseline the
    pallas kernel is benchmarked against in bench_extra.py)."""
    N, D = Xb.shape
    C = vals.shape[1]
    keys = (node[:, None] * D + jnp.arange(D)[None, :]) * n_bins + Xb  # [N, D]
    data = jnp.broadcast_to(vals[:, None, :], (N, D, C)).reshape(N * D, C)
    flat = jax.ops.segment_sum(data, keys.reshape(-1), num_segments=n_nodes * D * n_bins)
    return flat.reshape(n_nodes, D, n_bins, C)


def _l1_threshold(G, reg_alpha):
    """xgboost L1 soft-threshold T_alpha(G) = sign(G) * max(|G| - alpha, 0).
    When reg_alpha is the Python scalar 0, skip the thresholding at TRACE time —
    a traced alpha cannot be folded away by XLA and would tax the
    [nodes, D, bins, C] gain tensors. Callers inside jit must therefore pass a
    LITERAL 0 when L1 is off (fit_gbt's use_l1 static flag does this; a traced
    0.0 would defeat the guard)."""
    if isinstance(reg_alpha, (int, float)) and reg_alpha == 0:
        return G
    return jnp.sign(G) * jnp.maximum(jnp.abs(G) - reg_alpha, 0.0)


def grow_tree(
    Xb: jnp.ndarray,
    edges: jnp.ndarray,
    g: jnp.ndarray,
    h: jnp.ndarray,
    max_depth: int,
    reg_lambda,
    min_child_weight,
    min_gain,
    feature_mask: Optional[jnp.ndarray] = None,
    reg_alpha=0.0,
    hist_mode: Optional[str] = None,
    split_mode: Optional[str] = None,
    data_mesh=None,
    data_feature_sharded: bool = False,
    row_tile: Optional[int] = None,
):
    """Grow one perfect tree level-by-level on binned features.

    Xb [N, D] int32 bins; edges [D, B-1]; g, h [N, C] per-row gradient/hessian
    (channels = output dimension). Returns (split_feature [2^depth-1] int32,
    split_threshold [2^depth-1] f32, leaf_values [2^depth, C], leaf_of_row [N] int32)
    where leaf_values = -T_alpha(G)/(H + lambda) per leaf, with
    T_alpha(G) = sign(G) * max(|G| - alpha, 0) the xgboost L1 soft-threshold
    (reg_alpha=0 recovers the plain second-order leaf).

    Split finding runs one of two paths (r10):

    - two-pass (the default off-TPU / under vmap / with L1 on): per-level
      histogram -> HBM, then cumsum/gain/argmax as a separate program;
    - FUSED (pallas_trees.histogram_split_mxu; TT_SPLIT=fused|twopass forces,
      auto picks it for large unbatched TPU fits): gain + per-feature argmax
      run in the SAME pallas program while the histogram tiles are still in
      VMEM — only [n_nodes, D] split stats return to HBM, killing the
      full-histogram writeback + re-read of the two-pass path. Split
      decisions are bitwise-equal to the two-pass path scored on the SAME
      histogram backend (TT_HIST=mxu — what large TPU fits use; pinned by
      test). Against a DIFFERENT backend (e.g. the exact-f32 segment-sum CPU
      default) candidates within the bf16 rounding gap can legitimately pick
      a different, equally-scoring split.

    `hist_mode` overrides TT_HIST for the two-pass histogram (the mesh
    model-axis path pins a partitionable jnp decomposition).

    `data_mesh` (r14): a mesh whose data axis is > 1 routes every level's
    split finding through the SHARDED fused program (_data_axis_hist_split:
    per-device partial histograms, one psum over DATA_AXIS, on-device merged
    scan) under the same eligibility gates as the fused kernel (literal
    reg_alpha 0, n_bins >= 2, not batched, TT_SPLIT != twopass);
    `data_feature_sharded` additionally lays the feature axis over
    MODEL_AXIS inside that program (data x model composition). Callers pass
    ROW COUNTS divisible by the data axis (weight-0 pad via
    _pad_rows_weight0). data_mesh=None is byte-for-byte the pre-r14
    program."""
    N, D = Xb.shape
    n_bins = edges.shape[1] + 1
    # at-scale TPU fits swap the row-gather routing and scatter leaf sums for
    # one-hot compare/matmul forms (XLA's gather/scatter lowerings serialize);
    # small (selector-vmapped) fits keep the jnp forms
    big = (backend_is_tpu() and Xb.size >= _PALLAS_MIN_ELEMS
           and not _is_batched(Xb, g, h))
    fmask = jnp.ones(D, bool) if feature_mask is None else feature_mask
    node = jnp.zeros(N, jnp.int32)  # level-local node id
    feats, threshs = [], []
    feat_gain = jnp.zeros(D, jnp.float32)  # split-gain importance accumulator

    C = g.shape[1]
    gh = jnp.concatenate([g, h], axis=1)  # one fused histogram pass for both
    smode = split_mode if split_mode is not None else os.environ.get("TT_SPLIT")
    if smode not in (None, "fused", "twopass"):
        raise ValueError(f"TT_SPLIT={smode!r}: expected fused | twopass")
    # the fused kernel bakes the plain G^2/(H+lam) gain: a LITERAL-zero
    # reg_alpha (fit_gbt's use_l1=False) is the gate, a traced alpha is not
    fused_ok = (smode != "twopass"
                and isinstance(reg_alpha, (int, float)) and reg_alpha == 0
                and n_bins >= 2 and not _is_batched(Xb, g, h))
    use_data = data_mesh is not None and fused_ok
    for depth in range(max_depth):  # static unroll: shapes differ per level
        n_nodes = 2 ** depth
        use_fused = (not use_data) and fused_ok and (
            smode == "fused"
            or (smode is None and big and _fused_split_supported(
                N, D, n_nodes, 2 * C, n_bins, row_tile)))
        if use_data or use_fused:
            if use_data:
                gain_nf, bin_nf = _data_axis_hist_split(
                    data_mesh, gh, Xb, node, n_nodes, n_bins, reg_lambda,
                    min_child_weight, data_feature_sharded,
                    hist_mode=hist_mode, row_tile=row_tile)
            else:
                from .pallas_trees import histogram_split_mxu

                gain_nf, bin_nf = histogram_split_mxu(
                    gh, Xb, node, n_nodes, n_bins, reg_lambda,
                    min_child_weight, row_tile=row_tile,
                    interpret=not backend_is_tpu())
            # colsample mask + min_gain are per-(node, feature) gates: applied
            # here on the [n_nodes, D] stats, identical to the two-pass masks
            gain_nf = jnp.where(fmask[None, :], gain_nf, -jnp.inf)
            best_d_raw = jnp.argmax(gain_nf, axis=1).astype(jnp.int32)
            best_gain = jnp.take_along_axis(
                gain_nf, best_d_raw[:, None], axis=1)[:, 0]
            best_b_raw = jnp.take_along_axis(
                bin_nf, best_d_raw[:, None], axis=1)[:, 0]
            do_split = best_gain > min_gain
            best_d = jnp.where(do_split, best_d_raw, 0).astype(jnp.int32)
            best_b = jnp.where(do_split, best_b_raw,
                               n_bins - 1).astype(jnp.int32)
        else:
            cum = jnp.cumsum(
                _histogram(gh, Xb, node, n_nodes, n_bins, mode=hist_mode,
                           row_tile=row_tile),
                axis=2)
            GL, HL = cum[..., :C], cum[..., C:]
            Gt = GL[:, :1, -1:, :]  # per-node totals (identical across features)
            Ht = HL[:, :1, -1:, :]
            GR, HR = Gt - GL, Ht - HL

            def score(G, H):
                Gt_ = _l1_threshold(G, reg_alpha)
                return (Gt_ ** 2 / (H + reg_lambda + _EPS)).sum(-1)

            gain = score(GL, HL) + score(GR, HR) - score(Gt, Ht)  # [n_nodes, D, n_bins]
            hl, hr = HL.sum(-1), HR.sum(-1)
            valid = (
                (hl >= min_child_weight)
                & (hr >= min_child_weight)
                & fmask[None, :, None]
                & (jnp.arange(n_bins) < n_bins - 1)[None, None, :]
            )
            gain = jnp.where(valid, gain, -jnp.inf)

            flat = gain.reshape(n_nodes, D * n_bins)
            best = jnp.argmax(flat, axis=1)
            best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
            do_split = best_gain > min_gain
            best_d = jnp.where(do_split, best // n_bins, 0).astype(jnp.int32)
            best_b = jnp.where(do_split, best % n_bins, n_bins - 1).astype(jnp.int32)
        thresh = jnp.where(
            best_b < n_bins - 1,
            edges[best_d, jnp.clip(best_b, 0, n_bins - 2)],
            jnp.inf,
        )
        feats.append(best_d)
        threshs.append(thresh.astype(jnp.float32))
        # importance: realized gain of every executed split, scattered onto its
        # feature (n_nodes-sized scatter — tiny next to the histogram work)
        feat_gain = feat_gain.at[best_d].add(jnp.where(do_split, best_gain, 0.0))

        if big:
            # gather-free routing: the per-row split feature is selected with a
            # one-hot compare + integer sum (exact — bins < 2^31), because the
            # row-varying column gather lowers poorly at scale on TPU
            sel = best_d[node]  # [N] (small-table gather: fine)
            oh = sel[:, None] == jnp.arange(D)[None, :]
            xv = jnp.where(oh, Xb, 0).sum(axis=1)
            go_right = xv > best_b[node]
        else:
            go_right = Xb[jnp.arange(N), best_d[node]] > best_b[node]
        node = node * 2 + go_right.astype(jnp.int32)

    n_leaves = 2 ** max_depth
    if big:
        # scatter-free leaf sums: one [leaves, N] @ [N, C] matmul each (f32 —
        # leaf VALUES never see the histogram's bf16 rounding)
        oh = (node[None, :] == jnp.arange(n_leaves)[:, None]).astype(jnp.float32)
        Gleaf, Hleaf = oh @ g, oh @ h
    else:
        Gleaf = jax.ops.segment_sum(g, node, num_segments=n_leaves)
        Hleaf = jax.ops.segment_sum(h, node, num_segments=n_leaves)
    leaf_values = -_l1_threshold(Gleaf, reg_alpha) / (Hleaf + reg_lambda + _EPS)
    return (
        jnp.concatenate(feats),
        jnp.concatenate(threshs),
        leaf_values,
        node,
        feat_gain,
    )


def _route_leaves(X: jnp.ndarray, split_feature, split_threshold, max_depth: int):
    """Heap-walk rows of raw X down one tree -> leaf index [N]."""
    N = X.shape[0]
    node = jnp.zeros(N, jnp.int32)  # heap index
    for _ in range(max_depth):
        f = split_feature[node]
        t = split_threshold[node]
        go_right = X[jnp.arange(N), f] >= t
        node = 2 * node + 1 + go_right.astype(jnp.int32)
    return node - (2 ** max_depth - 1)


@partial(jax.jit, static_argnames=("average",))
def predict_ensemble(params: TreeEnsembleParams, X: jnp.ndarray,
                     average: bool = False) -> jnp.ndarray:
    """Ensemble output [N, C]: base + sum (boosting) or mean (forest) of leaf values.
    All trees route in parallel (vmap over the tree axis). Depth is recovered from
    the static node-array shape (perfect trees: internal = 2^depth - 1)."""
    X = jnp.asarray(X, jnp.float32)
    max_depth = (params.split_feature.shape[-1] + 1).bit_length() - 1

    def one_tree(sf, st, lv):
        return lv[_route_leaves(X, sf, st, max_depth)]  # [N, C]

    per_tree = jax.vmap(one_tree)(
        params.split_feature, params.split_threshold, params.leaf_values
    )  # [T, N, C]
    agg = per_tree.mean(axis=0) if average else per_tree.sum(axis=0)
    return params.base[None, :] + agg


def _weights(sample_weight, n):
    if sample_weight is None:
        return jnp.ones(n, jnp.float32)
    return jnp.asarray(sample_weight, jnp.float32)


# --- gradient boosting (GBT / XGBoost-style, second order) ---------------------------
def gbt_psum_payload_bytes(*, n_outputs: int, n_trees: int, max_depth: int,
                           n_bins: int, d_local: int) -> int:
    """ICI payload of the data-axis fused split program for ONE fit, in
    logical tensor bytes: each tree level psums one flat
    [n_bins * 2C * n_nodes, d_local] f32 partial histogram
    (_data_axis_hist_split's `part`), and levels 0..max_depth-1 sum to
    2**max_depth - 1 node slots per tree. The static resource model
    (analyze/shard_model.py) and the runtime `mesh_collective_bytes_total`
    record both price with THIS function — shapes derived independently, so
    parity tests catch drift in either."""
    V = 2 * max(1, int(n_outputs))  # g,h stacked per output column
    return (int(n_trees) * int(n_bins) * V * ((1 << int(max_depth)) - 1)
            * int(d_local) * 4)


def gbt_data_sharded(*, n_data: int, use_l1: bool, n_bins: int,
                     split: Optional[str] = None) -> bool:
    """The _fit_gbt/fit_forest data-axis gate, re-derivable without a fit:
    >1 data axis, literal-zero L1, something to scan, no twopass override.
    `split` overrides the TT_SPLIT env (how a tuner candidate's gate is
    evaluated without mutating the process environment)."""
    if split is None:
        split = os.environ.get("TT_SPLIT")
    return (int(n_data) > 1 and not use_l1 and int(n_bins) >= 2
            and split != "twopass")


def gbt_resource_profile(*, n_rows, d, n_outputs: int, n_trees: int,
                         max_depth: int, n_bins: int, n_data: int,
                         n_model: int, use_l1: bool = False,
                         split: Optional[str] = None) -> dict:
    """Static per-device footprint of one boosted/bagged fit — the stage-hook
    payload behind `op explain` (key contract in analyze/shard_model.py).
    Mirrors _fit_gbt's own resolution order: model-axis feature slabs when
    n_model divides D, data-axis row shards (weight-0 padded) when the fused
    gates open, int8 binned matrix under 128 bins. `split` overrides the
    TT_SPLIT env for the data-axis gate (how `op autotune` prices a twopass
    candidate without touching the environment)."""
    n_data, n_model = max(1, int(n_data)), max(1, int(n_model))
    d = int(d) if d else 0
    model_sharded = n_model > 1 and d > 0 and d % n_model == 0
    d_local = d // n_model if model_sharded else d
    data_sharded = gbt_data_sharded(n_data=n_data, use_l1=use_l1,
                                    n_bins=n_bins, split=split)
    pad = ((-int(n_rows)) % n_data if (data_sharded and n_rows) else 0)
    rows_dev = None
    if n_rows:
        rows_dev = (-(-(int(n_rows) + pad) // n_data) if data_sharded
                    else int(n_rows))
    cell = 1 if int(n_bins) <= 127 else 4
    V = 2 * max(1, int(n_outputs))
    notes = []
    if n_data > 1 and not data_sharded:
        notes.append("data axis unused: fused-split gates closed "
                     "(L1/bins/TT_SPLIT) — rows replicate (OP406)")
    if n_model > 1 and not model_sharded:
        notes.append(f"model axis unused: D={d} not divisible by "
                     f"{n_model}")
    flops = 0
    if rows_dev is not None and d_local:
        # per level: histogram accumulate over the row shard, then the
        # merged [bins, V, nodes, d_local] split scan
        flops = int(n_trees) * int(max_depth) * rows_dev * d_local * V * 2
        flops += (int(n_trees) * ((1 << int(max_depth)) - 1) * int(n_bins)
                  * V * d_local * 2)
    return {
        "aux_bytes": (rows_dev * d_local * cell
                      if (rows_dev is not None and d_local) else 0),
        "activation_bytes": (rows_dev * (d + V) * 4
                             if (rows_dev is not None and d) else 0),
        "collective_bytes": (gbt_psum_payload_bytes(
            n_outputs=n_outputs, n_trees=n_trees, max_depth=max_depth,
            n_bins=n_bins, d_local=d_local) if (data_sharded and d_local)
            else 0),
        "flops": flops,
        "pad_rows": pad,
        "rows_per_device": rows_dev,
        "rows_sharded": data_sharded,
        "features_sharded": model_sharded,
        "notes": notes,
    }


def _record_gbt_collectives(X, y, *, use_l1, mesh=None, objective="binary",
                            num_classes=2, n_trees=50, max_depth=5,
                            n_bins=32, split=None, **_kw) -> None:
    """Host-side honesty hook: when the fused data-axis program will run,
    record its psum payload (from the RUNTIME shapes) so mesh_stats() can be
    compared against the static prediction. Vmapped/batched fits and closed
    gates record nothing — exactly the fits that psum nothing."""
    if mesh is None or _is_batched(X, y):
        return
    from ..mesh import MODEL_AXIS, data_axis_size, record_collective

    if not gbt_data_sharded(n_data=data_axis_size(mesh), use_l1=use_l1,
                            n_bins=n_bins, split=split):
        return
    D = int(jnp.shape(X)[1])
    n_model = int(mesh.shape[MODEL_AXIS])
    model_sharded = n_model > 1 and D % n_model == 0
    d_local = D // n_model if model_sharded else D
    C = int(num_classes) if objective == "multiclass" else 1
    record_collective(gbt_psum_payload_bytes(
        n_outputs=C, n_trees=int(n_trees), max_depth=int(max_depth),
        n_bins=int(n_bins), d_local=d_local))


def fit_gbt(X, y, sample_weight=None, *, reg_alpha=0.0, **kw):
    """Public entry: decides the static use_l1 flag OUTSIDE the jit boundary.
    Inside _fit_gbt a default reg_alpha=0.0 would arrive as a TRACER, defeating
    _l1_threshold's literal-zero skip and taxing every fit with thresholding
    ops it doesn't need.

    The TT_ROW_TILE / TT_SPLIT env knobs resolve to LITERALS here, outside
    the jit boundary, so they participate in the jit cache key — `op
    autotune`'s back-to-back trials with different knob values each compile
    their own program instead of silently reusing the first one's. Callers
    (the tuner, tests) may pass `row_tile=`/`split=` explicitly instead."""
    use_l1 = not (isinstance(reg_alpha, (int, float)) and reg_alpha == 0)
    kw.setdefault("row_tile", _env_row_tile())
    kw.setdefault("split", os.environ.get("TT_SPLIT"))
    _record_gbt_collectives(X, y, use_l1=use_l1, **kw)
    return _fit_gbt(X, y, sample_weight, reg_alpha=reg_alpha, use_l1=use_l1, **kw)


@partial(
    jax.jit,
    static_argnames=(
        "objective", "num_classes", "n_trees", "max_depth", "n_bins",
        "subsample", "colsample", "seed", "use_l1", "mesh", "row_tile",
        "split",
    ),
)
def _fit_gbt(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    *,
    objective: str = "binary",  # binary | multiclass | regression
    num_classes: int = 2,
    n_trees: int = 50,
    max_depth: int = 5,
    learning_rate=0.1,
    reg_lambda=1.0,
    min_child_weight=1.0,
    min_gain=0.0,
    reg_alpha=0.0,
    use_l1: bool = False,
    subsample: float = 1.0,
    colsample: float = 1.0,
    n_bins: int = 32,
    seed: int = 7,
    mesh=None,
    row_tile: int = 0,
    split: Optional[str] = None,
) -> TreeEnsembleParams:
    """Second-order boosting: per round, (g, h) from the current margin, one
    multi-output tree, margin += leaf values (learning rate folded into leaves).

    `mesh` (static, r10): with a model axis > 1 that divides D, the binned
    matrix's feature axis lays over MODEL_AXIS so every round's independent
    per-feature histogram + split work partitions across it (a partitioned fit
    is a distinct executable — warm accordingly).

    Data axis (r14): with a data axis > 1 (and the fused-split gates open:
    literal reg_alpha 0, n_bins >= 2, TT_SPLIT != twopass, not vmapped), the
    margin/gradient ROWS shard over DATA_AXIS and every level's split finding
    runs the shard_map'd partial-histogram -> psum -> merged-scan program
    (_data_axis_hist_split), composing with the model-axis feature sharding
    on a (data x model) mesh. Non-dividing row counts pad with weight-0
    copies of row 0 AFTER quantile edges and the objective's base/wsum are
    computed on the original rows — pad rows carry zero mass, so split
    DECISIONS match the unmeshed fused path bitwise (gains move by psum-order
    ulp). NOTE: subsample < 1.0 draws its keep mask over the PADDED row
    count, so a padded fit's bootstrap differs from the unmeshed fit's —
    a stochastic, not correctness, difference."""
    X = jnp.asarray(X, jnp.float32)
    N, D = X.shape
    w = _weights(sample_weight, N)
    wsum = w.sum() + _EPS
    edges = quantile_bins(X, n_bins)
    Xb = bin_features(X, edges)

    if n_bins <= 127:
        # int8 bins end-to-end: the binned matrix is the fit's dominant tensor
        # (1 GB at 1M x 256 in int32); every level's histogram AND routing pass
        # re-reads it, so narrowing it 4x is a direct HBM-bandwidth win
        Xb = Xb.astype(jnp.int8)

    from ..mesh import data_axis_size

    # `split`/`row_tile` arrive as literals from the fit_gbt wrapper (env or
    # explicit tuner candidate); a None/0 falls back to the env at trace time
    env_split = split if split is not None else os.environ.get("TT_SPLIT")
    rt = row_tile or None
    data_sharded = (data_axis_size(mesh) > 1 and not use_l1 and n_bins >= 2
                    and env_split != "twopass"
                    and not _is_batched(X, y))
    Xb, edges, model_sharded = _model_axis_constraint(mesh, Xb, edges)
    # pallas_calls are opaque to the SPMD partitioner, so a PURELY
    # feature-sharded fit pins the partitionable jnp decompositions; the
    # data-axis path needs no pinning — its pallas programs are
    # partitioner-visible through shard_map (and it composes the model axis
    # itself)
    hist_mode = (("binmm" if backend_is_tpu() else "segsum")
                 if model_sharded and not data_sharded else None)
    split_mode = ("twopass" if model_sharded and not data_sharded
                  else env_split)

    if objective == "binary":
        Y = jnp.asarray(y, jnp.float32)[:, None]
        p0 = jnp.clip((w * Y[:, 0]).sum() / wsum, 1e-6, 1 - 1e-6)
        base = jnp.log(p0 / (1 - p0))[None]
    elif objective == "multiclass":
        Y = jax.nn.one_hot(jnp.asarray(y, jnp.int32), num_classes)
        freq = jnp.clip((w[:, None] * Y).sum(0) / wsum, 1e-6, None)
        base = jnp.log(freq)
    elif objective == "regression":
        Y = jnp.asarray(y, jnp.float32)[:, None]
        base = ((w * Y[:, 0]).sum() / wsum)[None]
    else:  # pragma: no cover
        raise ValueError(f"unknown objective {objective!r}")
    C = Y.shape[1]

    if data_sharded:
        pad = (-N) % data_axis_size(mesh)
        if pad:
            Xb, Y, w = _pad_rows_weight0(Xb, Y, w, pad)
            N = N + pad
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..mesh import DATA_AXIS, MODEL_AXIS

        Xb = jax.lax.with_sharding_constraint(
            Xb, NamedSharding(mesh, P(
                DATA_AXIS, MODEL_AXIS if model_sharded else None)))
        Y = jax.lax.with_sharding_constraint(
            Y, NamedSharding(mesh, P(DATA_AXIS, None)))
        w = jax.lax.with_sharding_constraint(
            w, NamedSharding(mesh, P(DATA_AXIS)))

    def grad_hess(F):
        if objective == "binary":
            p = jax.nn.sigmoid(F)
            return (p - Y) * w[:, None], jnp.clip(p * (1 - p), 1e-6, None) * w[:, None]
        if objective == "multiclass":
            p = jax.nn.softmax(F, axis=1)
            return (p - Y) * w[:, None], jnp.clip(p * (1 - p), 1e-6, None) * w[:, None]
        return (F - Y) * w[:, None], jnp.broadcast_to(w[:, None], F.shape)

    def tree_round(F, key):
        krow, kcol = jax.random.split(key)
        g, h = grad_hess(F)
        if subsample < 1.0:
            keep = jax.random.bernoulli(krow, subsample, (N,)).astype(jnp.float32)
            g, h = g * keep[:, None], h * keep[:, None]
        fmask = (
            jax.random.bernoulli(kcol, colsample, (D,)) if colsample < 1.0 else None
        )
        sf, st, lv, leaf, fg = grow_tree(
            Xb, edges, g, h, max_depth, reg_lambda, min_child_weight, min_gain,
            fmask, reg_alpha=reg_alpha if use_l1 else 0.0,  # literal 0 -> skip
            hist_mode=hist_mode, split_mode=split_mode,
            data_mesh=mesh if data_sharded else None,
            data_feature_sharded=model_sharded, row_tile=rt,
        )
        lv = lv * learning_rate
        return F + lv[leaf], (sf, st, lv, fg)

    F0 = jnp.broadcast_to(base[None, :], (N, C))
    keys = jax.random.split(jax.random.PRNGKey(seed), n_trees)
    _, (sfs, sts, lvs, fgs) = jax.lax.scan(tree_round, F0, keys)
    return TreeEnsembleParams(sfs, sts, lvs, base, fgs.sum(axis=0))


# --- bagged forests (RF / single decision tree) --------------------------------------
def fit_forest(X, y, sample_weight=None, **kw):
    """Public entry: resolves the TT_ROW_TILE / TT_SPLIT env knobs to
    literals OUTSIDE the jit boundary so they key the cache — see fit_gbt."""
    kw.setdefault("row_tile", _env_row_tile())
    kw.setdefault("split", os.environ.get("TT_SPLIT"))
    return _fit_forest(X, y, sample_weight, **kw)


@partial(
    jax.jit,
    static_argnames=(
        "objective", "num_classes", "n_trees", "max_depth", "n_bins",
        "colsample", "bootstrap", "seed", "mesh", "row_tile", "split",
    ),
)
def _fit_forest(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    *,
    objective: str = "classification",  # classification | regression
    num_classes: int = 2,
    n_trees: int = 50,
    max_depth: int = 5,
    reg_lambda=1e-3,
    min_child_weight=1.0,
    min_gain=0.0,
    colsample: float = 1.0,
    n_bins: int = 32,
    bootstrap: bool = True,
    seed: int = 7,
    mesh=None,
    row_tile: int = 0,
    split: Optional[str] = None,
) -> TreeEnsembleParams:
    """Bagged variance-reduction trees. With g = -Y*w, h = w the second-order leaf
    -G/(H+lambda) is the weighted target mean, and the gain is exactly the weighted
    variance reduction — one grower serves boosting and bagging. Classification
    targets are one-hot, so leaves hold class distributions (Gini-style splits).
    `mesh`: feature axis over MODEL_AXIS per _fit_gbt — every tree's histogram
    rounds partition across the model axis, and a data axis > 1 shards the
    gradient rows through the shard_map'd partial-histogram -> psum ->
    merged-scan split program (r14, see _fit_gbt; weight-0 row padding for
    non-dividing counts — NOTE the bootstrap poisson then draws over the
    padded row count, a stochastic difference from the unmeshed fit)."""
    X = jnp.asarray(X, jnp.float32)
    N, D = X.shape
    w = _weights(sample_weight, N)
    edges = quantile_bins(X, n_bins)
    Xb = bin_features(X, edges)
    if n_bins <= 127:
        Xb = Xb.astype(jnp.int8)  # see _fit_gbt: 4x less per-level HBM traffic

    from ..mesh import data_axis_size

    env_split = split if split is not None else os.environ.get("TT_SPLIT")
    rt = row_tile or None
    data_sharded = (data_axis_size(mesh) > 1 and n_bins >= 2
                    and env_split != "twopass"
                    and not _is_batched(X, y))
    Xb, edges, model_sharded = _model_axis_constraint(mesh, Xb, edges)
    hist_mode = (("binmm" if backend_is_tpu() else "segsum")
                 if model_sharded and not data_sharded else None)
    split_mode = ("twopass" if model_sharded and not data_sharded
                  else env_split)

    if objective == "classification":
        Y = jax.nn.one_hot(jnp.asarray(y, jnp.int32), num_classes)
    else:
        Y = jnp.asarray(y, jnp.float32)[:, None]
    C = Y.shape[1]

    if data_sharded:
        pad = (-N) % data_axis_size(mesh)
        if pad:
            Xb, Y, w = _pad_rows_weight0(Xb, Y, w, pad)
            N = N + pad
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..mesh import DATA_AXIS, MODEL_AXIS

        Xb = jax.lax.with_sharding_constraint(
            Xb, NamedSharding(mesh, P(
                DATA_AXIS, MODEL_AXIS if model_sharded else None)))
        Y = jax.lax.with_sharding_constraint(
            Y, NamedSharding(mesh, P(DATA_AXIS, None)))
        w = jax.lax.with_sharding_constraint(
            w, NamedSharding(mesh, P(DATA_AXIS)))

    def one_tree(key):
        krow, kcol = jax.random.split(key)
        boot = (
            jax.random.poisson(krow, 1.0, (N,)).astype(jnp.float32) * w
            if bootstrap
            else w
        )
        g = -Y * boot[:, None]
        h = jnp.broadcast_to(boot[:, None], (N, C))
        fmask = (
            jax.random.bernoulli(kcol, colsample, (D,)) if colsample < 1.0 else None
        )
        sf, st, lv, _, fg = grow_tree(
            Xb, edges, g, h, max_depth, reg_lambda, min_child_weight, min_gain,
            fmask, hist_mode=hist_mode, split_mode=split_mode,
            data_mesh=mesh if data_sharded else None,
            data_feature_sharded=model_sharded, row_tile=rt,
        )
        return sf, st, lv, fg

    keys = jax.random.split(jax.random.PRNGKey(seed), n_trees)
    # bagged trees are independent, but growing them under vmap multiplies the
    # per-level histogram memory by n_trees ON TOP of the selector's folds x grid
    # vmap — measured 18G of HBM for an 80-row dataset. lax.scan keeps one tree's
    # temps live; with the bin-wise-matmul histogram the per-step device cost is
    # small enough that scan is within ~12% of full vmap anyway (re-measured in
    # r5: a tree-axis vmap for small fits was WITHIN NOISE on the iris search).
    _, (sfs, sts, lvs, fgs) = jax.lax.scan(
        lambda _, k: (None, one_tree(k)), None, keys
    )
    return TreeEnsembleParams(sfs, sts, lvs, jnp.zeros(C, jnp.float32),
                              fgs.sum(axis=0))


# --- prediction heads ----------------------------------------------------------------
@jax.jit
def predict_gbt_binary(params: TreeEnsembleParams, X):
    z = predict_ensemble(params, X)[:, 0]
    p1 = jax.nn.sigmoid(z)
    prob = jnp.stack([1.0 - p1, p1], axis=1)
    raw = jnp.stack([-z, z], axis=1)
    return (p1 >= 0.5).astype(jnp.float32), raw, prob


@jax.jit
def predict_gbt_multiclass(params: TreeEnsembleParams, X):
    logits = predict_ensemble(params, X)
    prob = jax.nn.softmax(logits, axis=1)
    return jnp.argmax(logits, axis=1).astype(jnp.float32), logits, prob


@jax.jit
def predict_gbt_regression(params: TreeEnsembleParams, X):
    z = predict_ensemble(params, X)[:, 0]
    return z, z[:, None], z[:, None]


@jax.jit
def predict_forest_classification(params: TreeEnsembleParams, X):
    # one program end-to-end: eager clip/divide/log glue would otherwise dispatch
    # 4+ separate tiny compiles per new shape (each a remote round trip on a
    # tunneled device)
    dist = jnp.clip(predict_ensemble(params, X, average=True), 0.0, None)
    prob = dist / jnp.clip(dist.sum(axis=1, keepdims=True), _EPS, None)
    raw = jnp.log(jnp.clip(prob, 1e-12, None))
    return jnp.argmax(prob, axis=1).astype(jnp.float32), raw, prob


@jax.jit
def predict_forest_regression(params: TreeEnsembleParams, X):
    z = predict_ensemble(params, X, average=True)[:, 0]
    return z, z[:, None], z[:, None]
