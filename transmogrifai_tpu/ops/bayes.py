"""Naive Bayes trainers: count reductions in pure jnp.

Compute core of OpNaiveBayes (reference core/.../impl/classification/OpNaiveBayes.scala,
wrapping Spark MLlib NaiveBayes — multinomial with additive smoothing, plus a Gaussian
variant). Fit is a handful of one-hot matmul reductions (class-conditional sums on the
MXU, psum'd when rows are sharded); there is no iteration at all.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class NaiveBayesParams(NamedTuple):
    """log_prior [C]; multinomial: log_theta [C, D]; gaussian: mean/var [C, D]."""

    log_prior: jnp.ndarray
    log_theta: jnp.ndarray
    mean: jnp.ndarray
    var: jnp.ndarray


@partial(jax.jit, static_argnames=("num_classes", "model_type"))
def fit_naive_bayes(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    *,
    num_classes: int = 2,
    smoothing=1.0,
    model_type: str = "multinomial",
) -> NaiveBayesParams:
    """Multinomial (counts, nonneg features — negatives are clipped to 0, the Spark
    analog rejects them outright) or Gaussian (per-class feature moments)."""
    X = jnp.asarray(X, jnp.float32)
    n, d = X.shape
    w = jnp.ones(n, jnp.float32) if sample_weight is None else jnp.asarray(sample_weight, jnp.float32)
    Y = jax.nn.one_hot(jnp.asarray(y, jnp.int32), num_classes) * w[:, None]  # [N, C]
    class_w = Y.sum(0)  # [C]
    log_prior = jnp.log(jnp.clip(class_w, 1e-12, None) / jnp.clip(class_w.sum(), 1e-12, None))
    if model_type == "multinomial":
        Xc = jnp.clip(X, 0.0, None)
        counts = Y.T @ Xc  # [C, D] class-conditional feature mass
        sm = jnp.asarray(smoothing, jnp.float32)
        log_theta = jnp.log(counts + sm) - jnp.log(
            (counts.sum(1, keepdims=True) + sm * d)
        )
        zeros = jnp.zeros((num_classes, d), jnp.float32)
        return NaiveBayesParams(log_prior, log_theta, zeros, zeros)
    if model_type == "gaussian":
        denom = jnp.clip(class_w, 1e-12, None)[:, None]
        mean = Y.T @ X / denom
        ex2 = Y.T @ (X ** 2) / denom
        var = jnp.clip(ex2 - mean ** 2, 1e-6, None) + jnp.asarray(smoothing, jnp.float32) * 1e-9
        zeros = jnp.zeros((num_classes, d), jnp.float32)
        return NaiveBayesParams(log_prior, zeros, mean, var)
    raise ValueError(f"unknown model_type {model_type!r}")  # pragma: no cover


@partial(jax.jit, static_argnames=("model_type",))
def predict_naive_bayes(params: NaiveBayesParams, X: jnp.ndarray,
                        model_type: str = "multinomial"):
    X = jnp.asarray(X, jnp.float32)
    if model_type == "multinomial":
        logp = jnp.clip(X, 0.0, None) @ params.log_theta.T + params.log_prior[None, :]
    else:
        diff = X[:, None, :] - params.mean[None, :, :]
        logp = (
            -0.5 * (diff ** 2 / params.var[None, :, :]).sum(-1)
            - 0.5 * jnp.log(2 * jnp.pi * params.var).sum(-1)[None, :]
            + params.log_prior[None, :]
        )
    prob = jax.nn.softmax(logp, axis=1)
    return jnp.argmax(logp, axis=1).astype(jnp.float32), logp, prob
