"""Multilayer perceptron trainers: fixed-step Adam, static layer shapes, and
ZeRO-style sharded optimizer state on a mesh.

Compute core of OpMultilayerPerceptronClassifier (reference core/.../impl/
classification/OpMultilayerPerceptronClassifier.scala wrapping Spark's MLP with L-BFGS).
Layer widths are static, so every (fold, grid-point) fit shares one compiled program;
the forward pass is a chain of MXU matmuls and XLA fuses activations into them.

Sharded optimizer (r10, arXiv 2004.13336 / ops/optimizer.py): every trainer
here takes `mesh=None, shard_optimizer="auto"`. On a mesh with data axis N > 1
(and outside the selector's vmap batching) the f32 master params and Adam
(m, v) live SHARDED 1/N-per-device over the data axis; each step is
psum_scatter(grads) -> local shard Adam update -> all_gather of compute params
(bf16 on the minibatch/scan lanes), expressed with `shard_map` so XLA overlaps
layer k's reduce with layer k+1's update math. Per-device optimizer state
drops from 12*P to 12*ceil(P/N) bytes — the model-size ceiling becomes the
MESH's memory, not one chip's. With no mesh (or one device, or "off") every
entry point runs the EXACT pre-r10 replicated path: same function objects,
same jit caches, bitwise-identical results.
"""
from __future__ import annotations

import functools
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .optimizer import (
    adam_update,
    flatten_pad,
    gather_compute,
    optimizer_state_bytes,
    record_state_bytes,
    resolve_shard_optimizer,
    shard_width,
    unflatten,
)


def _layer_shapes(d: int, hidden: Sequence[int], num_classes: int):
    sizes = (d, *hidden, num_classes)
    return ([(i, o) for i, o in zip(sizes[:-1], sizes[1:])],
            [(o,) for o in sizes[1:]])


def _n_params(d: int, hidden: Sequence[int], num_classes: int) -> int:
    w_shapes, b_shapes = _layer_shapes(d, hidden, num_classes)
    return sum(i * o for i, o in w_shapes) + sum(o for (o,) in b_shapes)


def mlp_collective_bytes(d: int, hidden: Sequence[int], num_classes: int, *,
                         n_data: int, max_iter: int) -> int:
    """Modeled ICI payload of ONE sharded full-batch fit, in logical tensor
    bytes (the Alpa counting convention, arXiv 2201.12023): per step every
    layer's flat-padded f32 leaf is all_gathered for the forward pass and
    its gradient psum_scattered by gather_compute's vjp, and the fitted
    params all_gather once at the end. Mirrors _fullbatch_program_sharded
    term-for-term; the static resource model and the runtime
    `mesh_collective_bytes_total` counter both call THIS function, with
    independently-derived shapes, so parity tests catch drift in either."""
    n_data = int(n_data)
    if n_data <= 1:
        return 0
    w_shapes, b_shapes = _layer_shapes(int(d), tuple(hidden),
                                       int(num_classes))
    def leaf(size: int) -> int:
        return n_data * shard_width(size, n_data) * 4  # padded flat f32

    per_step = (sum(leaf(i * o) for i, o in w_shapes)
                + sum(leaf(o) for (o,) in b_shapes))
    # gather + scatter per step, one final tiled all_gather of the result
    return (2 * int(max_iter) + 1) * per_step


def mlp_resource_profile(*, d: int, hidden: Sequence[int], num_classes: int,
                         max_iter: int, n_rows, n_data: int,
                         shard_optimizer="auto") -> dict:
    """Static per-device footprint of one fit_mlp call at a mesh data axis of
    `n_data` — the stage-hook payload behind `op explain` (see
    analyze/shard_model.py for the key contract). Shares every byte formula
    with the runtime: optimizer_state_bytes for the ZeRO shard math,
    mlp_collective_bytes for the ICI payload."""
    d, num_classes = int(d), max(int(num_classes), 2)
    hidden = tuple(int(h) for h in hidden)
    n_data = max(1, int(n_data))
    P = _n_params(d, hidden, num_classes)
    knob_off = (shard_optimizer in (False, None)
                or str(shard_optimizer) in ("off", "0"))
    sharded = n_data > 1 and not knob_off
    pad = ((-int(n_rows)) % n_data if (sharded and n_rows) else 0)
    rows_dev = None
    if n_rows:
        rows_dev = (-(-(int(n_rows) + pad) // n_data)
                    if (sharded or (n_data > 1 and int(n_rows) % n_data == 0))
                    else int(n_rows))
    w_sizes = sum(i * o for i, o in _layer_shapes(d, hidden, num_classes)[0])
    act = (rows_dev * (d + sum(hidden) + num_classes) * 4
           if rows_dev is not None else 0)
    return {
        "params_bytes": 4 * P,
        "opt_state_bytes": optimizer_state_bytes(
            P, sharded, n_data if sharded else 1),
        "activation_bytes": act,
        "collective_bytes": (mlp_collective_bytes(
            d, hidden, num_classes, n_data=n_data, max_iter=max_iter)
            if sharded else 0),
        "flops": (6 * rows_dev * w_sizes * int(max_iter)
                  if rows_dev is not None else 0),
        "pad_rows": pad,
        "rows_per_device": rows_dev,
        "rows_sharded": bool(rows_dev is not None and n_data > 1
                             and rows_dev < int(n_rows)),
        "opt_sharded": sharded,
        "notes": (("shard_optimizer=off: state replicates",) if knob_off
                  and n_data > 1 else ()),
    }


def _adam_fullbatch(X, y, w, params, *, num_classes: int, max_iter: int,
                    lr, l2) -> list:
    """THE full-batch Adam training body (forward/loss/step/scan), shared by
    the seeded cold trainer and the warm-start trainer below so their loss
    surface and update rule can never drift apart — warm-vs-cold convergence
    parity is a pinned contract. Traced inline by both jits; the op order is
    byte-identical to the pre-refactor `_fit_mlp_replicated` body."""
    wsum = w.sum() + 1e-12
    Y = jax.nn.one_hot(jnp.asarray(y, jnp.int32), num_classes)

    def forward(params, X):
        h = X
        for W, b in params[:-1]:
            h = jnp.tanh(h @ W + b)  # Spark MLP uses sigmoid-family hidden units
        W, b = params[-1]
        return h @ W + b

    def loss_fn(params):
        logits = forward(params, X)
        ll = (w * (jax.nn.log_softmax(logits) * Y).sum(1)).sum() / wsum
        reg = sum((W ** 2).sum() for W, _ in params)
        return -ll + 0.5 * l2 * reg

    grad_fn = jax.grad(loss_fn)

    def step(carry, i):
        params, m, v = carry
        g = grad_fn(params)
        lr_t = lr * 0.5 * (1 + jnp.cos(jnp.pi * i / max_iter))
        params, m, v = adam_update(params, m, v, g, i + 1, lr_t)
        return (params, m, v), None

    zeros = jax.tree.map(jnp.zeros_like, params)
    (params, _, _), _ = jax.lax.scan(
        step, (params, zeros, jax.tree.map(jnp.zeros_like, params)),
        jnp.arange(max_iter),
    )
    return params


@partial(jax.jit, static_argnames=("num_classes", "hidden", "max_iter", "seed"))
def _fit_mlp_replicated(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    *,
    num_classes: int = 2,
    hidden: Sequence[int] = (10,),
    max_iter: int = 200,
    lr=0.01,
    l2=0.0,
    seed: int = 0,
) -> list:
    """The single-program full-batch trainer (pre-r10 `fit_mlp` body): f32
    math end to end, optimizer state replicated on every device."""
    X = jnp.asarray(X, jnp.float32)
    n, d = X.shape
    w = jnp.ones(n, jnp.float32) if sample_weight is None else jnp.asarray(sample_weight, jnp.float32)
    params = _mlp_init(d, hidden, num_classes, seed)
    return _adam_fullbatch(X, y, w, params, num_classes=num_classes,
                           max_iter=max_iter, lr=lr, l2=l2)


@partial(jax.jit, static_argnames=("num_classes", "max_iter"))
def _fit_mlp_warm(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight,
    init_params,
    *,
    num_classes: int = 2,
    max_iter: int = 200,
    lr=0.01,
    l2=0.0,
) -> list:
    """Warm-started full-batch trainer: the SAME `_adam_fullbatch` body as
    `_fit_mlp_replicated` (shared — the loss surface and update rule cannot
    drift apart), but the initial parameters ride as ARGUMENTS (the previous
    champion's fitted layers) instead of a seeded random init — the
    autopilot's drift-retrain path. Layer shapes come from `init_params`, so
    one compiled program serves every retrain of a given architecture. At
    convergence (enough steps on the same data) the loss optimum reached
    matches the cold fit's; on incrementally-drifted data it is reached in
    far fewer effective steps."""
    X = jnp.asarray(X, jnp.float32)
    n, _ = X.shape
    w = (jnp.ones(n, jnp.float32) if sample_weight is None
         else jnp.asarray(sample_weight, jnp.float32))
    params = [(jnp.asarray(W, jnp.float32), jnp.asarray(b, jnp.float32))
              for W, b in init_params]
    return _adam_fullbatch(X, y, w, params, num_classes=num_classes,
                           max_iter=max_iter, lr=lr, l2=l2)


def fit_mlp(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    *,
    num_classes: int = 2,
    hidden: Sequence[int] = (10,),
    max_iter: int = 200,
    lr=0.01,
    l2=0.0,
    seed: int = 0,
    mesh=None,
    shard_optimizer="auto",
    init_params=None,
) -> list:
    """-> params: list of (W [in, out], b [out]) per layer, softmax head included.

    `mesh` + `shard_optimizer="auto"`: on a data axis > 1 the optimizer state
    shards per ops/optimizer.py (f32 compute-param gathers on this full-batch
    f32 lane); rows pad to the axis with weight 0, so the weighted loss is
    exact at any row count. Unmeshed/1-device/vmapped fits run the replicated
    program unchanged.

    `init_params`: optional list of (W, b) layers to warm-start from (a
    previous fit of the SAME architecture — the autopilot's drift retrain).
    Warm starts run the replicated program (`_fit_mlp_warm`); a fit that
    resolves to the SHARDED optimizer path ignores them and cold-fits
    sharded instead — the sharding contract (including the binding
    `shard_optimizer="on"` error for ineligible fits) outranks the
    warm-start optimization, which is best-effort by definition. Shapes
    that disagree with (X width, hidden, num_classes) raise at trace time,
    so a caller warm-starting across a schema change fails loudly, not
    wrongly."""
    hidden = tuple(int(h) for h in hidden)
    # lr/l2 ride the batched check too: a vmapped hyperparameter axis (the
    # selector's grid stacks) must keep the replicated program. Resolved
    # FIRST: "on" must keep raising for ineligible fits, and a sharded fit
    # must stay sharded (cold), even when init_params ride along.
    if resolve_shard_optimizer(mesh, shard_optimizer, X, y, sample_weight,
                               lr, l2):
        return _fit_mlp_sharded(
            X, y, sample_weight, num_classes=num_classes, hidden=hidden,
            max_iter=int(max_iter), lr=lr, l2=l2, seed=int(seed), mesh=mesh)
    if init_params is not None:
        w_shapes, _ = _layer_shapes(np.shape(X)[1], hidden, num_classes)
        got_w = [tuple(np.shape(W)) for W, _ in init_params]
        if got_w != w_shapes:
            raise ValueError(
                f"init_params layer shapes {got_w} do not match the "
                f"requested architecture {w_shapes} — warm starts require "
                "an identical (width, hidden, num_classes) layout")
        record_state_bytes(_n_params(np.shape(X)[1], hidden, num_classes),
                           sharded=False)
        return _fit_mlp_warm(X, y, sample_weight, list(init_params),
                             num_classes=num_classes, max_iter=int(max_iter),
                             lr=lr, l2=l2)
    record_state_bytes(_n_params(np.shape(X)[1], hidden, num_classes),
                       sharded=False)
    return _fit_mlp_replicated(
        X, y, sample_weight, num_classes=num_classes, hidden=hidden,
        max_iter=int(max_iter), lr=lr, l2=l2, seed=int(seed))


@functools.lru_cache(maxsize=32)
def _fullbatch_program_sharded(mesh, num_classes: int, hidden: tuple, d: int,
                               max_iter: int, seed: int):
    """The ZeRO full-batch trainer: one jitted shard_map program per
    (mesh, layer config). lr/l2 ride as traced scalars so hyperparameter
    changes never recompile; row count keys the inner jit as usual."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..mesh import DATA_AXIS

    n_data = int(mesh.shape[DATA_AXIS])
    w_shapes, b_shapes = _layer_shapes(d, hidden, num_classes)

    def body(Xl, yl, wl, lr, l2):
        idx = jax.lax.axis_index(DATA_AXIS)
        params0 = _mlp_init(d, hidden, num_classes, seed)
        # every device deterministically computes the tiny full init, then
        # keeps only its 1/N shard of each flat leaf — no init broadcast
        shards0 = []
        for W, b in params0:
            fw = flatten_pad(W, n_data)
            fb = flatten_pad(b, n_data)
            sw = fw.shape[0] // n_data
            sb = fb.shape[0] // n_data
            shards0.append((
                jax.lax.dynamic_slice(fw, (idx * sw,), (sw,)),
                jax.lax.dynamic_slice(fb, (idx * sb,), (sb,)),
            ))
        Y = jax.nn.one_hot(jnp.asarray(yl, jnp.int32), num_classes)
        wsum = jax.lax.psum(wl.sum(), DATA_AXIS) + 1e-12
        Xf = jnp.asarray(Xl, jnp.float32)

        def gather_params(shards, dtype):
            return [
                (unflatten(gather_compute(sw_, DATA_AXIS, dtype), ws),
                 unflatten(gather_compute(sb_, DATA_AXIS, jnp.float32), bs))
                for (sw_, sb_), ws, bs in zip(shards, w_shapes, b_shapes)
            ]

        def data_loss(shards):
            params = gather_params(shards, jnp.float32)  # f32 lane
            h = Xf
            for W, b in params[:-1]:
                h = jnp.tanh(h @ W + b)
            W, b = params[-1]
            logits = h @ W + b
            ll = (wl * (jax.nn.log_softmax(logits) * Y).sum(1)).sum() / wsum
            return -ll

        def step(carry, i):
            shards, m, v = carry
            g = jax.grad(data_loss)(shards)  # <- psum_scatter via gather vjp
            # L2 term applied analytically on the f32 master shard: identical
            # to the replicated grad of 0.5*l2*sum(W^2) (weights only)
            g = [(gw + l2 * sw_, gb) for (gw, gb), (sw_, _sb)
                 in zip(g, shards)]
            lr_t = lr * 0.5 * (1 + jnp.cos(jnp.pi * i / max_iter))
            shards, m, v = adam_update(shards, m, v, g, i + 1, lr_t)
            return (shards, m, v), None

        zeros = jax.tree.map(jnp.zeros_like, shards0)
        (shards, _, _), _ = jax.lax.scan(
            step, (shards0, zeros, jax.tree.map(jnp.zeros_like, shards0)),
            jnp.arange(max_iter))
        return [
            (unflatten(jax.lax.all_gather(sw_, DATA_AXIS, tiled=True), ws),
             unflatten(jax.lax.all_gather(sb_, DATA_AXIS, tiled=True), bs))
            for (sw_, sb_), ws, bs in zip(shards, w_shapes, b_shapes)
        ]

    specs = [(P(), P())] * len(w_shapes)
    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS), P(), P()),
        out_specs=specs, check_rep=False))


def _fit_mlp_sharded(X, y, sample_weight, *, num_classes, hidden, max_iter,
                     lr, l2, seed, mesh) -> list:
    from ..mesh import DATA_AXIS, record_sharded_dispatch, shard_batch

    n_data = int(mesh.shape[DATA_AXIS])
    X = jnp.asarray(X, jnp.float32)
    n, d = X.shape
    w = (jnp.ones(n, jnp.float32) if sample_weight is None
         else jnp.asarray(sample_weight, jnp.float32))
    y = jnp.asarray(y, jnp.float32)
    pad = (-n) % n_data
    if pad:  # weight-0 repeat-row-0 padding: exact for the weighted loss
        X = jnp.concatenate([X, jnp.repeat(X[:1], pad, axis=0)])
        y = jnp.concatenate([y, jnp.repeat(y[:1], pad)])
        w = jnp.concatenate([w, jnp.zeros(pad, jnp.float32)])
    prog = _fullbatch_program_sharded(mesh, int(num_classes), tuple(hidden),
                                      int(d), int(max_iter), int(seed))
    record_state_bytes(_n_params(d, hidden, num_classes), sharded=True,
                       n_shards=n_data)
    from ..mesh import record_collective
    record_collective(mlp_collective_bytes(d, hidden, num_classes,
                                           n_data=n_data,
                                           max_iter=int(max_iter)))
    record_sharded_dispatch()
    return prog(shard_batch(mesh, X), shard_batch(mesh, y),
                shard_batch(mesh, w), jnp.float32(lr), jnp.float32(l2))


def _mlp_init(d: int, hidden: Sequence[int], num_classes: int, seed: int) -> list:
    sizes = (d, *hidden, num_classes)
    keys = jax.random.split(jax.random.PRNGKey(seed), len(sizes) - 1)
    return [
        (
            jax.random.normal(k, (i, o), jnp.float32) * jnp.sqrt(2.0 / i),
            jnp.zeros(o, jnp.float32),
        )
        for k, i, o in zip(keys, sizes[:-1], sizes[1:])
    ]


def _matmul_mp(h, W, compute_dtype):
    """Mixed-precision matmul: operands in compute_dtype (bf16 = MXU native),
    accumulation and OUTPUT in f32 via preferred_element_type — one op, no
    separate output-cast pass over the [B, width] activation (the bf16->f32
    astype after each layer materialized an extra activation-sized write)."""
    return jax.lax.dot_general(
        h.astype(compute_dtype), W.astype(compute_dtype),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def _mlp_forward(params: list, X, compute_dtype):
    """Mixed-precision forward: matmuls in compute_dtype on the MXU with f32
    accumulation, bias+activation computed in f32 but STORED in compute_dtype.
    The store dtype matters more than the math dtype here: at deep-tabular
    widths the per-row intensity with f32 activations sits at the HBM ridge
    (~240 FLOP/byte on v5e), so halving activation traffic (bf16 residency for
    both the forward value and the autodiff residual tanh keeps) is what moves
    the step from bandwidth-bound to compute-bound. Bias+tanh+cast fuse into
    the matmul epilogue — no extra activation-sized pass."""
    h = X
    for W, b in params[:-1]:
        h = jnp.tanh(_matmul_mp(h, W, compute_dtype) + b).astype(compute_dtype)
    W, b = params[-1]
    return _matmul_mp(h, W, compute_dtype) + b


def _mlp_loss(params: list, X, Y, l2, compute_dtype):
    ll = (jax.nn.log_softmax(_mlp_forward(params, X, compute_dtype)) * Y).sum(1).mean()
    reg = sum((W ** 2).sum() for W, _ in params)
    return -ll + 0.5 * l2 * reg


def _adam_update(state: tuple, g, lr):
    """One bias-corrected Adam update on (params, m, v, t) — THE update rule shared
    by the streamed and in-HBM minibatch trainers (they must never diverge).
    Delegates to the shared ops/optimizer.py rule (the one the sharded-state
    path updates SHARDS with)."""
    params, m, v, t = state
    t = t + 1.0
    params, m, v = adam_update(params, m, v, g, t, lr)
    return (params, m, v, t)


@functools.lru_cache(maxsize=64)
def _minibatch_step(num_classes: int, lr: float, l2: float, compute_dtype):
    """The compiled streamed-chunk Adam step, memoized on its hyperparams so
    repeated fit_mlp_minibatch calls (warmup, then timed/real run) share one jit
    cache instead of retracing per call."""
    from ..utils.sanitize import donating_jit

    def adam_step(state, X, y):
        Y = jax.nn.one_hot(jnp.asarray(y, jnp.int32), num_classes)
        g = jax.grad(_mlp_loss)(state[0], jnp.asarray(X, jnp.float32), Y, l2,
                                compute_dtype)
        return _adam_update(state, g, lr)

    return donating_jit(adam_step, donate_argnums=0)


@functools.lru_cache(maxsize=64)
def _window_step(num_classes: int, lr: float, l2: float, compute_dtype):
    """One jitted program consuming a STACK of chunks [W, B, d] via lax.scan —
    identical math to W sequential _minibatch_step calls, 1 dispatch instead
    of W (per-dispatch RPC latency dominated the streamed path: measured
    ~7-16 ms/chunk over a tunneled device). Memoized like _minibatch_step."""
    from ..utils.sanitize import donating_jit

    def body(state, xy):
        X, y = xy
        Y = jax.nn.one_hot(jnp.asarray(y, jnp.int32), num_classes)
        g = jax.grad(_mlp_loss)(state[0], jnp.asarray(X, jnp.float32), Y, l2,
                                compute_dtype)
        return _adam_update(state, g, lr), None

    def win(state, Xs, ys):
        state, _ = jax.lax.scan(body, state, (Xs, ys))
        return state

    return donating_jit(win, donate_argnums=0)


@functools.lru_cache(maxsize=64)
def _minibatch_step_sharded(mesh, num_classes: int, hidden: tuple, d: int,
                            lr: float, l2: float, compute_dtype):
    """The ZeRO streamed-chunk step: state = (param/m/v shards, t) with every
    shard a flat [N * width] array laid P(DATA_AXIS); rows of the chunk ride
    the data axis. The loss gathers bf16 compute params (gather_compute), its
    gradient psum_scatters in f32 via the custom vjp, and the Adam update runs
    on the local shard — per-leaf collectives, so XLA overlaps one layer's
    reduce with the next layer's update. Donation preserved: state updates in
    place in HBM exactly like the replicated step."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..mesh import DATA_AXIS
    from ..utils.sanitize import donating_jit

    w_shapes, b_shapes = _layer_shapes(d, hidden, num_classes)

    def local_step(state, X, y, w):
        shards, m, v, t = state
        Y = jax.nn.one_hot(jnp.asarray(y, jnp.int32), num_classes)
        # denominator = real (unpadded) global rows: equals _mlp_loss's .mean()
        bsum = jax.lax.psum(w.sum(), DATA_AXIS)

        def data_loss(shards):
            params = [
                (unflatten(gather_compute(sw, DATA_AXIS, compute_dtype), ws),
                 unflatten(gather_compute(sb, DATA_AXIS, jnp.float32), bs))
                for (sw, sb), ws, bs in zip(shards, w_shapes, b_shapes)
            ]
            logits = _mlp_forward(params, jnp.asarray(X, jnp.float32),
                                  compute_dtype)
            ll = (w * (jax.nn.log_softmax(logits) * Y).sum(1)).sum() / bsum
            return -ll

        g = jax.grad(data_loss)(shards)
        g = [(gw + l2 * sw, gb) for (gw, gb), (sw, _sb) in zip(g, shards)]
        t = t + 1.0
        shards, m, v = adam_update(shards, m, v, g, t, lr)
        return (shards, m, v, t)

    state_spec = (P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P())
    mapped = shard_map(
        local_step, mesh=mesh,
        in_specs=(state_spec, P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=state_spec, check_rep=False)
    return donating_jit(mapped, donate_argnums=0)


def _init_sharded_state(mesh, d: int, hidden, num_classes: int, seed: int):
    """Sharded (params, m, v, t): flat f32 leaves laid over DATA_AXIS."""
    from .optimizer import shard_state_leaf

    params = _mlp_init(d, hidden, num_classes, seed)
    shards = [(shard_state_leaf(mesh, W), shard_state_leaf(mesh, b))
              for W, b in params]
    return (shards, jax.tree.map(jnp.zeros_like, shards),
            jax.tree.map(jnp.zeros_like, shards), jnp.float32(0.0))


def _sharded_state_params(state, d: int, hidden, num_classes: int) -> list:
    """Final full f32 params from sharded state — the state leaves are GLOBAL
    jax arrays (sharded storage), so this is a slice+reshape, no collective."""
    w_shapes, b_shapes = _layer_shapes(d, hidden, num_classes)
    return [(unflatten(sw, ws), unflatten(sb, bs))
            for (sw, sb), ws, bs in zip(state[0], w_shapes, b_shapes)]


def fit_mlp_minibatch(
    chunk_fn,
    n_chunks: int,
    d: int,
    *,
    num_classes: int = 2,
    hidden: Sequence[int] = (256, 128),
    epochs: int = 1,
    lr=1e-3,
    l2=0.0,
    seed: int = 0,
    compute_dtype=jnp.bfloat16,
    dispatch_window: int = 1,
    prefetch: int = 2,
    mesh=None,
    shard_optimizer="auto",
) -> list:
    """Minibatch-SGD (Adam) MLP over streamed chunks — the deep-tabular regime
    (BASELINE.json config 5): data that never sits in HBM at once. `chunk_fn(i)`
    yields (X [B, d], y [B]) for chunk i. Two overlap mechanisms (r5):

    - `prefetch`: the shared input executor (readers/pipeline.py Prefetcher —
      this trainer's private loop was its prototype) runs chunk_fn and starts
      the async host->device transfer (`jax.device_put`) for upcoming chunks
      while the device trains on the current ones — the tf.data-style double
      buffering; device-resident chunks pass through untouched.
    - `dispatch_window`: W prefetched chunks stack into ONE jitted
      scan-of-Adam-steps program (identical update math, 1 RPC dispatch
      instead of W). The ragged tail falls back to the per-chunk step so no
      extra program shapes compile. Default 1: windows hold 2*W chunks in HBM
      (the stack copies), and on the measured tunnel the stack dispatches cost
      as much as the step dispatches they replace — raise it only when HBM is
      ample and per-dispatch latency is the proven bottleneck.

    Parameter/optimizer state is donated between dispatches (in-place in HBM);
    matmuls run in `compute_dtype` (bf16 = MXU-native; master params/optimizer
    state stay f32). Multi-chip (`mesh`, r10): with `shard_optimizer="auto"`
    and a data axis N > 1 the master params and Adam moments live sharded 1/N
    per device, chunk rows shard the data axis (weight-0 pad rows for
    non-dividing chunks — exact), grads psum_scatter, and bf16 compute params
    all_gather per layer (ops/optimizer.py). The sharded path dispatches per
    chunk (`dispatch_window` applies to the replicated path)."""
    from ..readers.pipeline import Prefetcher

    hidden = tuple(int(h) for h in hidden)
    if resolve_shard_optimizer(mesh, shard_optimizer):
        return _fit_mlp_minibatch_sharded(
            chunk_fn, n_chunks, d, num_classes=num_classes, hidden=hidden,
            epochs=epochs, lr=lr, l2=l2, seed=seed,
            compute_dtype=compute_dtype, prefetch=prefetch, mesh=mesh)
    record_state_bytes(_n_params(d, hidden, num_classes), sharded=False)
    params = _mlp_init(d, hidden, num_classes, seed)
    step = _minibatch_step(num_classes, float(lr), float(l2), compute_dtype)
    win = _window_step(num_classes, float(lr), float(l2), compute_dtype)
    zeros = jax.tree.map(jnp.zeros_like, params)
    state = (params, zeros, jax.tree.map(jnp.zeros_like, params), jnp.float32(0.0))
    W = max(1, int(dispatch_window))
    seq = [i for _ in range(epochs) for i in range(n_chunks)]

    def load(i):
        X, y = chunk_fn(i)
        if isinstance(X, np.ndarray):  # host chunk: start the transfer now
            X = jax.device_put(X)
        if isinstance(y, np.ndarray):
            y = jax.device_put(y)
        return X, y

    pending: list = []
    with Prefetcher(seq, load, depth=max(W, int(prefetch)),
                    name="mlp_chunk") as pf:
        for xy in pf:
            pending.append(xy)
            if len(pending) == W:
                if W == 1:
                    state = step(state, *pending[0])
                else:
                    Xs = jnp.stack([X for X, _ in pending])
                    ys = jnp.stack([y for _, y in pending])
                    state = win(state, Xs, ys)
                pending = []
    for X, y in pending:  # ragged tail: per-chunk steps, no new shapes
        state = step(state, X, y)
    return state[0]


def _fit_mlp_minibatch_sharded(chunk_fn, n_chunks: int, d: int, *, num_classes,
                               hidden, epochs, lr, l2, seed, compute_dtype,
                               prefetch, mesh) -> list:
    from ..mesh import DATA_AXIS, record_sharded_dispatch, shard_batch
    from ..readers.pipeline import Prefetcher

    n_data = int(mesh.shape[DATA_AXIS])
    step = _minibatch_step_sharded(mesh, num_classes, hidden, int(d),
                                   float(lr), float(l2), compute_dtype)
    state = _init_sharded_state(mesh, d, hidden, num_classes, seed)
    record_state_bytes(_n_params(d, hidden, num_classes), sharded=True,
                       n_shards=n_data)
    seq = [i for _ in range(epochs) for i in range(n_chunks)]

    def load(i):
        """Producer-thread work: pad rows to the data axis (weight-0 mask) and
        land the chunk PRE-SHARDED over DATA_AXIS."""
        X, y = chunk_fn(i)
        B = int(np.shape(X)[0])
        pad = (-B) % n_data
        w = np.ones(B + pad, np.float32)
        if pad:
            w[B:] = 0.0
            X = jnp.concatenate([jnp.asarray(X),
                                 jnp.zeros((pad, d), jnp.asarray(X).dtype)])
            y = jnp.concatenate([jnp.asarray(y, jnp.float32),
                                 jnp.zeros(pad, jnp.float32)])
        return (shard_batch(mesh, X), shard_batch(mesh, y),
                shard_batch(mesh, w))

    with Prefetcher(seq, load, depth=max(1, int(prefetch)),
                    name="mlp_chunk") as pf:
        for X, y, w in pf:
            state = step(state, X, y, w)
            record_sharded_dispatch()
    return _sharded_state_params(state, d, hidden, num_classes)


@partial(jax.jit, static_argnames=("batch_size", "num_classes", "hidden", "epochs",
                                   "seed", "compute_dtype"))
def _fit_mlp_scan_replicated(
    X: jnp.ndarray,
    y: jnp.ndarray,
    *,
    batch_size: int,
    num_classes: int = 2,
    hidden: Sequence[int] = (256, 128),
    epochs: int = 1,
    lr=1e-3,
    l2=0.0,
    seed: int = 0,
    compute_dtype=jnp.bfloat16,
) -> list:
    X = jnp.asarray(X)
    n, d = X.shape
    steps = n // batch_size
    Xb = X[: steps * batch_size].reshape(steps, batch_size, d)
    Yb = jax.nn.one_hot(
        jnp.asarray(y[: steps * batch_size], jnp.int32), num_classes
    ).reshape(steps, batch_size, num_classes)

    params = _mlp_init(d, hidden, num_classes, seed)

    def step(carry, batch):
        Xc, Yc = batch
        g = jax.grad(_mlp_loss)(carry[0], Xc, Yc, l2, compute_dtype)
        return _adam_update(carry, g, lr), None

    def epoch(carry, _):
        carry, _ = jax.lax.scan(step, carry, (Xb, Yb))
        return carry, None

    zeros = jax.tree.map(jnp.zeros_like, params)
    carry = (params, zeros, jax.tree.map(jnp.zeros_like, params), jnp.float32(0.0))
    # nested scan: program size is O(1) in epochs (a Python loop would trace
    # `epochs` copies of the step and recompile per distinct epoch count)
    carry, _ = jax.lax.scan(epoch, carry, None, length=epochs)
    return carry[0]


@functools.lru_cache(maxsize=32)
def _scan_program_sharded(mesh, num_classes: int, hidden: tuple, d: int,
                          epochs: int, seed: int, compute_dtype):
    """Whole-training-run sharded program: the epochs x steps Adam loop runs
    as lax.scan INSIDE one shard_map-partitioned jit — zero host round-trips
    between steps AND sharded optimizer state, composed. Batch rows ride
    DATA_AXIS; each step gathers bf16 compute params and psum_scatters grads
    exactly like the streamed step."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..mesh import DATA_AXIS

    n_data = int(mesh.shape[DATA_AXIS])
    w_shapes, b_shapes = _layer_shapes(d, hidden, num_classes)

    def body(Xb, yb, lr, l2):
        # Xb local [steps, B/n, d]; yb local [steps, B/n]
        idx = jax.lax.axis_index(DATA_AXIS)
        B_total = Xb.shape[1] * n_data
        params0 = _mlp_init(d, hidden, num_classes, seed)
        shards0 = []
        for W, b in params0:
            fw, fb = flatten_pad(W, n_data), flatten_pad(b, n_data)
            sw, sb = fw.shape[0] // n_data, fb.shape[0] // n_data
            shards0.append((jax.lax.dynamic_slice(fw, (idx * sw,), (sw,)),
                            jax.lax.dynamic_slice(fb, (idx * sb,), (sb,))))

        def data_loss(shards, Xc, Yc):
            params = [
                (unflatten(gather_compute(sw, DATA_AXIS, compute_dtype), ws),
                 unflatten(gather_compute(sb, DATA_AXIS, jnp.float32), bs))
                for (sw, sb), ws, bs in zip(shards, w_shapes, b_shapes)
            ]
            logits = _mlp_forward(params, Xc, compute_dtype)
            ll = (jax.nn.log_softmax(logits) * Yc).sum() / B_total
            return -ll

        def step(carry, batch):
            Xc, yc = batch
            shards, m, v, t = carry
            Yc = jax.nn.one_hot(jnp.asarray(yc, jnp.int32), num_classes)
            g = jax.grad(data_loss)(shards, Xc, Yc)
            g = [(gw + l2 * sw, gb) for (gw, gb), (sw, _sb) in zip(g, shards)]
            t = t + 1.0
            shards, m, v = adam_update(shards, m, v, g, t, lr)
            return (shards, m, v, t), None

        def epoch(carry, _):
            carry, _ = jax.lax.scan(step, carry, (Xb, yb))
            return carry, None

        zeros = jax.tree.map(jnp.zeros_like, shards0)
        carry = (shards0, zeros, jax.tree.map(jnp.zeros_like, shards0),
                 jnp.float32(0.0))
        carry, _ = jax.lax.scan(epoch, carry, None, length=epochs)
        return [
            (unflatten(jax.lax.all_gather(sw, DATA_AXIS, tiled=True), ws),
             unflatten(jax.lax.all_gather(sb, DATA_AXIS, tiled=True), bs))
            for (sw, sb), ws, bs in zip(carry[0], w_shapes, b_shapes)
        ]

    specs = [(P(), P())] * len(w_shapes)
    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(None, DATA_AXIS, None), P(None, DATA_AXIS), P(), P()),
        out_specs=specs, check_rep=False))


def fit_mlp_scan(
    X: jnp.ndarray,
    y: jnp.ndarray,
    *,
    batch_size: int,
    num_classes: int = 2,
    hidden: Sequence[int] = (256, 128),
    epochs: int = 1,
    lr=1e-3,
    l2=0.0,
    seed: int = 0,
    compute_dtype=jnp.bfloat16,
    mesh=None,
    shard_optimizer="auto",
) -> list:
    """Whole-training-run-in-one-program minibatch MLP: the data already sits in
    HBM, so the epochs x steps Adam loop runs as `lax.scan` inside ONE jit — zero
    host round-trips between steps (the dispatch-bound regime of per-step stepping
    disappears; on a tunneled device this is the difference between dispatch
    latency x steps and pure device time). Same update rule as fit_mlp_minibatch;
    use that one when data streams from host and this one when it fits in HBM.

    Static-shape discipline: the tail `n % batch_size` rows are dropped each
    epoch (shuffle or pad upstream if every row must be seen); batch_size > n is
    an error rather than a silent no-op.

    Multi-chip (r10): with a mesh and `shard_optimizer="auto"`, batch rows
    shard DATA_AXIS and the optimizer state shards ZeRO-style — one partitioned
    program, still zero host round-trips. Requires batch_size to divide the
    data axis (it always does for the pow2 defaults); otherwise the replicated
    program runs unchanged."""
    hidden = tuple(int(h) for h in hidden)
    n, d = np.shape(X)
    steps = n // batch_size
    if steps == 0:
        raise ValueError(
            f"batch_size={batch_size} exceeds n={n} rows — zero scan steps would "
            "silently return the random initialization; lower batch_size (or use "
            "fit_mlp for full-batch training)"
        )
    sharded = resolve_shard_optimizer(mesh, shard_optimizer, X, y, lr, l2)
    if sharded:
        from ..mesh import DATA_AXIS as _DA

        sharded = batch_size % int(mesh.shape[_DA]) == 0
    if not sharded:
        record_state_bytes(_n_params(d, hidden, num_classes), sharded=False)
        return _fit_mlp_scan_replicated(
            X, y, batch_size=batch_size, num_classes=num_classes,
            hidden=hidden, epochs=epochs, lr=lr, l2=l2, seed=seed,
            compute_dtype=compute_dtype)
    from ..mesh import DATA_AXIS, record_sharded_dispatch, shard_batch

    n_data = int(mesh.shape[DATA_AXIS])
    X = jnp.asarray(X)
    Xb = X[: steps * batch_size].reshape(steps, batch_size, d)
    yb = jnp.asarray(y, jnp.float32)[: steps * batch_size].reshape(
        steps, batch_size)
    prog = _scan_program_sharded(mesh, int(num_classes), hidden, int(d),
                                 int(epochs), int(seed), compute_dtype)
    record_state_bytes(_n_params(d, hidden, num_classes), sharded=True,
                       n_shards=n_data)
    record_sharded_dispatch()
    return prog(shard_batch(mesh, Xb, batch_dim=1),
                shard_batch(mesh, yb, batch_dim=1),
                jnp.float32(lr), jnp.float32(l2))


@jax.jit
def predict_mlp(params: list, X: jnp.ndarray):
    h = jnp.asarray(X, jnp.float32)
    for W, b in params[:-1]:
        h = jnp.tanh(h @ W + b)
    W, b = params[-1]
    logits = h @ W + b
    prob = jax.nn.softmax(logits, axis=1)
    return jnp.argmax(logits, axis=1).astype(jnp.float32), logits, prob
