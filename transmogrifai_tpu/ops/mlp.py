"""Multilayer perceptron trainer: fixed-step full-batch Adam, static layer shapes.

Compute core of OpMultilayerPerceptronClassifier (reference core/.../impl/
classification/OpMultilayerPerceptronClassifier.scala wrapping Spark's MLP with L-BFGS).
Layer widths are static, so every (fold, grid-point) fit shares one compiled program;
the forward pass is a chain of MXU matmuls and XLA fuses activations into them.
"""
from __future__ import annotations

import functools
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("num_classes", "hidden", "max_iter", "seed"))
def fit_mlp(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    *,
    num_classes: int = 2,
    hidden: Sequence[int] = (10,),
    max_iter: int = 200,
    lr=0.01,
    l2=0.0,
    seed: int = 0,
) -> list:
    """-> params: list of (W [in, out], b [out]) per layer, softmax head included."""
    X = jnp.asarray(X, jnp.float32)
    n, d = X.shape
    w = jnp.ones(n, jnp.float32) if sample_weight is None else jnp.asarray(sample_weight, jnp.float32)
    wsum = w.sum() + 1e-12
    Y = jax.nn.one_hot(jnp.asarray(y, jnp.int32), num_classes)
    sizes = (d, *hidden, num_classes)
    keys = jax.random.split(jax.random.PRNGKey(seed), len(sizes) - 1)
    params = [
        (
            jax.random.normal(k, (i, o), jnp.float32) * jnp.sqrt(2.0 / i),
            jnp.zeros(o, jnp.float32),
        )
        for k, i, o in zip(keys, sizes[:-1], sizes[1:])
    ]

    def forward(params, X):
        h = X
        for W, b in params[:-1]:
            h = jnp.tanh(h @ W + b)  # Spark MLP uses sigmoid-family hidden units
        W, b = params[-1]
        return h @ W + b

    def loss_fn(params):
        logits = forward(params, X)
        ll = (w * (jax.nn.log_softmax(logits) * Y).sum(1)).sum() / wsum
        reg = sum((W ** 2).sum() for W, _ in params)
        return -ll + 0.5 * l2 * reg

    grad_fn = jax.grad(loss_fn)

    def step(carry, i):
        params, m, v = carry
        g = grad_fn(params)
        t = i + 1
        b1, b2, eps = 0.9, 0.999, 1e-8
        lr_t = lr * 0.5 * (1 + jnp.cos(jnp.pi * i / max_iter))
        m = jax.tree.map(lambda a, b_: b1 * a + (1 - b1) * b_, m, g)
        v = jax.tree.map(lambda a, b_: b2 * a + (1 - b2) * b_ ** 2, v, g)
        params = jax.tree.map(
            lambda p, mm, vv: p
            - lr_t * (mm / (1 - b1 ** t)) / (jnp.sqrt(vv / (1 - b2 ** t)) + eps),
            params, m, v,
        )
        return (params, m, v), None

    zeros = jax.tree.map(jnp.zeros_like, params)
    (params, _, _), _ = jax.lax.scan(
        step, (params, zeros, jax.tree.map(jnp.zeros_like, params)),
        jnp.arange(max_iter),
    )
    return params


def _mlp_init(d: int, hidden: Sequence[int], num_classes: int, seed: int) -> list:
    sizes = (d, *hidden, num_classes)
    keys = jax.random.split(jax.random.PRNGKey(seed), len(sizes) - 1)
    return [
        (
            jax.random.normal(k, (i, o), jnp.float32) * jnp.sqrt(2.0 / i),
            jnp.zeros(o, jnp.float32),
        )
        for k, i, o in zip(keys, sizes[:-1], sizes[1:])
    ]


def _matmul_mp(h, W, compute_dtype):
    """Mixed-precision matmul: operands in compute_dtype (bf16 = MXU native),
    accumulation and OUTPUT in f32 via preferred_element_type — one op, no
    separate output-cast pass over the [B, width] activation (the bf16->f32
    astype after each layer materialized an extra activation-sized write)."""
    return jax.lax.dot_general(
        h.astype(compute_dtype), W.astype(compute_dtype),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def _mlp_forward(params: list, X, compute_dtype):
    """Mixed-precision forward: matmuls in compute_dtype on the MXU with f32
    accumulation, bias+activation computed in f32 but STORED in compute_dtype.
    The store dtype matters more than the math dtype here: at deep-tabular
    widths the per-row intensity with f32 activations sits at the HBM ridge
    (~240 FLOP/byte on v5e), so halving activation traffic (bf16 residency for
    both the forward value and the autodiff residual tanh keeps) is what moves
    the step from bandwidth-bound to compute-bound. Bias+tanh+cast fuse into
    the matmul epilogue — no extra activation-sized pass."""
    h = X
    for W, b in params[:-1]:
        h = jnp.tanh(_matmul_mp(h, W, compute_dtype) + b).astype(compute_dtype)
    W, b = params[-1]
    return _matmul_mp(h, W, compute_dtype) + b


def _mlp_loss(params: list, X, Y, l2, compute_dtype):
    ll = (jax.nn.log_softmax(_mlp_forward(params, X, compute_dtype)) * Y).sum(1).mean()
    reg = sum((W ** 2).sum() for W, _ in params)
    return -ll + 0.5 * l2 * reg


def _adam_update(state: tuple, g, lr):
    """One bias-corrected Adam update on (params, m, v, t) — THE update rule shared
    by the streamed and in-HBM minibatch trainers (they must never diverge)."""
    params, m, v, t = state
    b1, b2, eps = 0.9, 0.999, 1e-8
    t = t + 1.0
    m = jax.tree.map(lambda a, b_: b1 * a + (1 - b1) * b_, m, g)
    v = jax.tree.map(lambda a, b_: b2 * a + (1 - b2) * b_ ** 2, v, g)
    params = jax.tree.map(
        lambda p, mm, vv: p
        - lr * (mm / (1 - b1 ** t)) / (jnp.sqrt(vv / (1 - b2 ** t)) + eps),
        params, m, v,
    )
    return (params, m, v, t)


@functools.lru_cache(maxsize=64)
def _minibatch_step(num_classes: int, lr: float, l2: float, compute_dtype):
    """The compiled streamed-chunk Adam step, memoized on its hyperparams so
    repeated fit_mlp_minibatch calls (warmup, then timed/real run) share one jit
    cache instead of retracing per call."""
    from ..utils.sanitize import donating_jit

    def adam_step(state, X, y):
        Y = jax.nn.one_hot(jnp.asarray(y, jnp.int32), num_classes)
        g = jax.grad(_mlp_loss)(state[0], jnp.asarray(X, jnp.float32), Y, l2,
                                compute_dtype)
        return _adam_update(state, g, lr)

    return donating_jit(adam_step, donate_argnums=0)


@functools.lru_cache(maxsize=64)
def _window_step(num_classes: int, lr: float, l2: float, compute_dtype):
    """One jitted program consuming a STACK of chunks [W, B, d] via lax.scan —
    identical math to W sequential _minibatch_step calls, 1 dispatch instead
    of W (per-dispatch RPC latency dominated the streamed path: measured
    ~7-16 ms/chunk over a tunneled device). Memoized like _minibatch_step."""
    from ..utils.sanitize import donating_jit

    def body(state, xy):
        X, y = xy
        Y = jax.nn.one_hot(jnp.asarray(y, jnp.int32), num_classes)
        g = jax.grad(_mlp_loss)(state[0], jnp.asarray(X, jnp.float32), Y, l2,
                                compute_dtype)
        return _adam_update(state, g, lr), None

    def win(state, Xs, ys):
        state, _ = jax.lax.scan(body, state, (Xs, ys))
        return state

    return donating_jit(win, donate_argnums=0)


def fit_mlp_minibatch(
    chunk_fn,
    n_chunks: int,
    d: int,
    *,
    num_classes: int = 2,
    hidden: Sequence[int] = (256, 128),
    epochs: int = 1,
    lr=1e-3,
    l2=0.0,
    seed: int = 0,
    compute_dtype=jnp.bfloat16,
    dispatch_window: int = 1,
    prefetch: int = 2,
) -> list:
    """Minibatch-SGD (Adam) MLP over streamed chunks — the deep-tabular regime
    (BASELINE.json config 5): data that never sits in HBM at once. `chunk_fn(i)`
    yields (X [B, d], y [B]) for chunk i. Two overlap mechanisms (r5):

    - `prefetch`: the shared input executor (readers/pipeline.py Prefetcher —
      this trainer's private loop was its prototype) runs chunk_fn and starts
      the async host->device transfer (`jax.device_put`) for upcoming chunks
      while the device trains on the current ones — the tf.data-style double
      buffering; device-resident chunks pass through untouched.
    - `dispatch_window`: W prefetched chunks stack into ONE jitted
      scan-of-Adam-steps program (identical update math, 1 RPC dispatch
      instead of W). The ragged tail falls back to the per-chunk step so no
      extra program shapes compile. Default 1: windows hold 2*W chunks in HBM
      (the stack copies), and on the measured tunnel the stack dispatches cost
      as much as the step dispatches they replace — raise it only when HBM is
      ample and per-dispatch latency is the proven bottleneck.

    Parameter/optimizer state is donated between dispatches (in-place in HBM);
    matmuls run in `compute_dtype` (bf16 = MXU-native; master params/optimizer
    state stay f32). Multi-chip: shard the batch axis of each chunk over the
    mesh data axis and the grads psum (the minibatch-SGD-over-ICI path; the
    single-chip program is unchanged)."""
    from ..readers.pipeline import Prefetcher

    params = _mlp_init(d, hidden, num_classes, seed)
    step = _minibatch_step(num_classes, float(lr), float(l2), compute_dtype)
    win = _window_step(num_classes, float(lr), float(l2), compute_dtype)
    zeros = jax.tree.map(jnp.zeros_like, params)
    state = (params, zeros, jax.tree.map(jnp.zeros_like, params), jnp.float32(0.0))
    W = max(1, int(dispatch_window))
    seq = [i for _ in range(epochs) for i in range(n_chunks)]

    def load(i):
        X, y = chunk_fn(i)
        if isinstance(X, np.ndarray):  # host chunk: start the transfer now
            X = jax.device_put(X)
        if isinstance(y, np.ndarray):
            y = jax.device_put(y)
        return X, y

    pending: list = []
    with Prefetcher(seq, load, depth=max(W, int(prefetch)),
                    name="mlp_chunk") as pf:
        for xy in pf:
            pending.append(xy)
            if len(pending) == W:
                if W == 1:
                    state = step(state, *pending[0])
                else:
                    Xs = jnp.stack([X for X, _ in pending])
                    ys = jnp.stack([y for _, y in pending])
                    state = win(state, Xs, ys)
                pending = []
    for X, y in pending:  # ragged tail: per-chunk steps, no new shapes
        state = step(state, X, y)
    return state[0]


@partial(jax.jit, static_argnames=("batch_size", "num_classes", "hidden", "epochs",
                                   "seed", "compute_dtype"))
def fit_mlp_scan(
    X: jnp.ndarray,
    y: jnp.ndarray,
    *,
    batch_size: int,
    num_classes: int = 2,
    hidden: Sequence[int] = (256, 128),
    epochs: int = 1,
    lr=1e-3,
    l2=0.0,
    seed: int = 0,
    compute_dtype=jnp.bfloat16,
) -> list:
    """Whole-training-run-in-one-program minibatch MLP: the data already sits in
    HBM, so the epochs x steps Adam loop runs as `lax.scan` inside ONE jit — zero
    host round-trips between steps (the dispatch-bound regime of per-step stepping
    disappears; on a tunneled device this is the difference between dispatch
    latency x steps and pure device time). Same update rule as fit_mlp_minibatch;
    use that one when data streams from host and this one when it fits in HBM.

    Static-shape discipline: the tail `n % batch_size` rows are dropped each
    epoch (shuffle or pad upstream if every row must be seen); batch_size > n is
    an error rather than a silent no-op."""
    X = jnp.asarray(X)
    n, d = X.shape
    steps = n // batch_size
    if steps == 0:
        raise ValueError(
            f"batch_size={batch_size} exceeds n={n} rows — zero scan steps would "
            "silently return the random initialization; lower batch_size (or use "
            "fit_mlp for full-batch training)"
        )
    Xb = X[: steps * batch_size].reshape(steps, batch_size, d)
    Yb = jax.nn.one_hot(
        jnp.asarray(y[: steps * batch_size], jnp.int32), num_classes
    ).reshape(steps, batch_size, num_classes)

    params = _mlp_init(d, hidden, num_classes, seed)

    def step(carry, batch):
        Xc, Yc = batch
        g = jax.grad(_mlp_loss)(carry[0], Xc, Yc, l2, compute_dtype)
        return _adam_update(carry, g, lr), None

    def epoch(carry, _):
        carry, _ = jax.lax.scan(step, carry, (Xb, Yb))
        return carry, None

    zeros = jax.tree.map(jnp.zeros_like, params)
    carry = (params, zeros, jax.tree.map(jnp.zeros_like, params), jnp.float32(0.0))
    # nested scan: program size is O(1) in epochs (a Python loop would trace
    # `epochs` copies of the step and recompile per distinct epoch count)
    carry, _ = jax.lax.scan(epoch, carry, None, length=epochs)
    return carry[0]


@jax.jit
def predict_mlp(params: list, X: jnp.ndarray):
    h = jnp.asarray(X, jnp.float32)
    for W, b in params[:-1]:
        h = jnp.tanh(h @ W + b)
    W, b = params[-1]
    logits = h @ W + b
    prob = jax.nn.softmax(logits, axis=1)
    return jnp.argmax(logits, axis=1).astype(jnp.float32), logits, prob
