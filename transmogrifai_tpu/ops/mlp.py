"""Multilayer perceptron trainer: fixed-step full-batch Adam, static layer shapes.

Compute core of OpMultilayerPerceptronClassifier (reference core/.../impl/
classification/OpMultilayerPerceptronClassifier.scala wrapping Spark's MLP with L-BFGS).
Layer widths are static, so every (fold, grid-point) fit shares one compiled program;
the forward pass is a chain of MXU matmuls and XLA fuses activations into them.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("num_classes", "hidden", "max_iter", "seed"))
def fit_mlp(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    *,
    num_classes: int = 2,
    hidden: Sequence[int] = (10,),
    max_iter: int = 200,
    lr=0.01,
    l2=0.0,
    seed: int = 0,
) -> list:
    """-> params: list of (W [in, out], b [out]) per layer, softmax head included."""
    X = jnp.asarray(X, jnp.float32)
    n, d = X.shape
    w = jnp.ones(n, jnp.float32) if sample_weight is None else jnp.asarray(sample_weight, jnp.float32)
    wsum = w.sum() + 1e-12
    Y = jax.nn.one_hot(jnp.asarray(y, jnp.int32), num_classes)
    sizes = (d, *hidden, num_classes)
    keys = jax.random.split(jax.random.PRNGKey(seed), len(sizes) - 1)
    params = [
        (
            jax.random.normal(k, (i, o), jnp.float32) * jnp.sqrt(2.0 / i),
            jnp.zeros(o, jnp.float32),
        )
        for k, i, o in zip(keys, sizes[:-1], sizes[1:])
    ]

    def forward(params, X):
        h = X
        for W, b in params[:-1]:
            h = jnp.tanh(h @ W + b)  # Spark MLP uses sigmoid-family hidden units
        W, b = params[-1]
        return h @ W + b

    def loss_fn(params):
        logits = forward(params, X)
        ll = (w * (jax.nn.log_softmax(logits) * Y).sum(1)).sum() / wsum
        reg = sum((W ** 2).sum() for W, _ in params)
        return -ll + 0.5 * l2 * reg

    grad_fn = jax.grad(loss_fn)

    def step(carry, i):
        params, m, v = carry
        g = grad_fn(params)
        t = i + 1
        b1, b2, eps = 0.9, 0.999, 1e-8
        lr_t = lr * 0.5 * (1 + jnp.cos(jnp.pi * i / max_iter))
        m = jax.tree.map(lambda a, b_: b1 * a + (1 - b1) * b_, m, g)
        v = jax.tree.map(lambda a, b_: b2 * a + (1 - b2) * b_ ** 2, v, g)
        params = jax.tree.map(
            lambda p, mm, vv: p
            - lr_t * (mm / (1 - b1 ** t)) / (jnp.sqrt(vv / (1 - b2 ** t)) + eps),
            params, m, v,
        )
        return (params, m, v), None

    zeros = jax.tree.map(jnp.zeros_like, params)
    (params, _, _), _ = jax.lax.scan(
        step, (params, zeros, jax.tree.map(jnp.zeros_like, params)),
        jnp.arange(max_iter),
    )
    return params


@jax.jit
def predict_mlp(params: list, X: jnp.ndarray):
    h = jnp.asarray(X, jnp.float32)
    for W, b in params[:-1]:
        h = jnp.tanh(h @ W + b)
    W, b = params[-1]
    logits = h @ W + b
    prob = jax.nn.softmax(logits, axis=1)
    return jnp.argmax(logits, axis=1).astype(jnp.float32), logits, prob
