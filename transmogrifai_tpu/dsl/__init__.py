"""Feature algebra: rich methods and operators on Feature.

TPU-native analog of the reference dsl layer (core/src/main/scala/com/salesforce/op/dsl/:
RichNumericFeature.scala:70-228,247,263-288,315,377,469; RichTextFeature.scala:58-747;
RichFeature.scala:61-215; RichFeaturesCollection.scala:69). Scala implicit enrichments
become methods attached to `Feature` at import time — `import transmogrifai_tpu` is all
the user needs for `f1 + f2`, `f.tokenize()`, `transmogrify([...])` to work.

Every method follows the reference's one-shortcut-per-stage convention: it instantiates
the corresponding stage and wires this feature (plus any others) as inputs, returning
the new output Feature.
"""
from __future__ import annotations

from typing import Callable, Iterable, Optional, Sequence

from ..graph.feature import Feature
from ..stages.base import LambdaTransformer, Stage
from ..stages.feature.categorical import IndexToString, OneHotVectorizer, StringIndexer
from ..stages.feature.date import DateToUnitCircleVectorizer
from ..stages.feature.math import (
    BinaryMathTransformer,
    ScalarMathTransformer,
    UnaryMathTransformer,
)
from ..stages.feature.misc import AliasTransformer, ToOccurTransformer
from ..stages.feature.numeric import (
    FillMissingWithMean,
    NumericBucketizer,
    StandardScaler,
)
from ..stages.feature.text import (
    HashingVectorizer,
    SmartTextVectorizer,
    TextLenTransformer,
    TextTokenizer,
)
from ..stages.feature.transmogrify import DEFAULTS, transmogrify


def _binary_op(op: str):
    def method(self: Feature, other):
        if isinstance(other, Feature):
            return BinaryMathTransformer(op)(self, other)
        if not isinstance(other, (int, float)):
            return NotImplemented  # let Python try the other operand's reflected op
        return ScalarMathTransformer(op, float(other))(self)

    return method


def _reverse_op(op: str):
    def method(self: Feature, other):
        # other is always a scalar here: Feature.op(Feature) resolves via _binary_op
        if not isinstance(other, (int, float)):
            return NotImplemented
        return ScalarMathTransformer(op, float(other), reverse=True)(self)

    return method


# --- generic enrichments (RichFeature.scala:61-215) ---------------------------------------
def alias(self: Feature, name: str) -> Feature:
    return AliasTransformer(name)(self)


def occurs(self: Feature, match_fn: Optional[Callable] = None) -> Feature:
    return ToOccurTransformer(match_fn)(self)


def map_via(self: Feature, fn: Callable, out_kind: str, *, device_op: bool = False,
            fn_name: Optional[str] = None) -> Feature:
    """Ad-hoc unary transform (reference `map`); fn: Column -> Column."""
    return LambdaTransformer(fn, out_kind, device_op=device_op, n_inputs=1,
                             fn_name=fn_name)(self)


def transform_with(self: Feature, stage: Stage, *others: Feature) -> Feature:
    """Apply an explicit stage instance to this feature (+ any extra inputs)
    (reference `transformWith`)."""
    return stage(self, *others)


# --- numeric enrichments (RichNumericFeature.scala) ---------------------------------------
def fill_missing_with_mean(self: Feature, default: float = 0.0) -> Feature:
    return FillMissingWithMean(default=default)(self)


def bucketize(self: Feature, splits: Sequence[float],
              bucket_labels: Optional[Sequence[str]] = None,
              track_nulls: bool = True, track_invalid: bool = False) -> Feature:
    return NumericBucketizer(splits, bucket_labels=bucket_labels,
                             track_nulls=track_nulls, track_invalid=track_invalid)(self)


def auto_bucketize(self: Feature, label: Feature, track_nulls: bool = True,
                   max_splits: int = 16, min_info_gain: float = 0.01) -> Feature:
    """Label-aware decision-tree bucketization (reference
    DecisionTreeNumericBucketizer.scala; dsl autoBucketize)."""
    from ..stages.feature.calibration import DecisionTreeNumericBucketizer

    return DecisionTreeNumericBucketizer(
        track_nulls=track_nulls, max_splits=max_splits, min_info_gain=min_info_gain
    )(label, self)


def z_normalize(self: Feature, with_mean: bool = True, with_std: bool = True) -> Feature:
    return StandardScaler(with_mean=with_mean, with_std=with_std)(self)


def vectorize_feature(self: Feature, **overrides) -> Feature:
    """Default per-kind vectorization of a single feature (dsl `vectorize`)."""
    return transmogrify([self], **overrides)


def sanity_check(self: Feature, label: Feature, **params) -> Feature:
    """Feature-vector validation against the label (dsl sanityCheck
    RichNumericFeature.scala:469). self must be an OPVector feature."""
    from ..check.sanity_checker import SanityChecker

    return SanityChecker(**params)(label, self)


# --- text enrichments (RichTextFeature.scala) ---------------------------------------------
def tokenize_feature(self: Feature, to_lower: bool = True, min_token_len: int = 1) -> Feature:
    return TextTokenizer(to_lower=to_lower, min_token_len=min_token_len)(self)


def pivot(self: Feature, top_k: int = DEFAULTS.top_k,
          min_support: int = DEFAULTS.min_support, clean_text: bool = True,
          track_nulls: bool = True) -> Feature:
    return OneHotVectorizer(top_k=top_k, min_support=min_support, clean_text=clean_text,
                            track_nulls=track_nulls)(self)


def smart_vectorize(self: Feature, *others: Feature, **params) -> Feature:
    return SmartTextVectorizer(**params)(self, *others)


def index_string(self: Feature, handle_invalid: str = "error") -> Feature:
    return StringIndexer(handle_invalid=handle_invalid)(self)


def text_len(self: Feature, *others: Feature) -> Feature:
    return TextLenTransformer()(self, *others)


def hash_vectorize(self: Feature, *others: Feature, **params) -> Feature:
    return HashingVectorizer(**params)(self, *others)


def ngram(self: Feature, n: int = 2, sep: str = " ") -> Feature:
    from ..stages.feature.text_advanced import NGram

    return NGram(n=n, sep=sep)(self)


def remove_stop_words(self: Feature, stop_words: Optional[Sequence[str]] = None,
                      case_sensitive: bool = False) -> Feature:
    from ..stages.feature.text_advanced import StopWordsRemover

    return StopWordsRemover(stop_words=stop_words, case_sensitive=case_sensitive)(self)


def count_vectorize(self: Feature, *others: Feature, **params) -> Feature:
    from ..stages.feature.text_advanced import CountVectorizer

    return CountVectorizer(**params)(self, *others)


def ngram_similarity(self: Feature, other: Feature, n: int = 3) -> Feature:
    from ..stages.feature.text_advanced import NGramSimilarity

    return NGramSimilarity(n=n)(self, other)


def jaccard_similarity(self: Feature, other: Feature) -> Feature:
    from ..stages.feature.text_advanced import JaccardSimilarity

    return JaccardSimilarity()(self, other)


def detect_languages(self: Feature, languages: Optional[Sequence[str]] = None,
                     top_k: int = 3) -> Feature:
    from ..stages.feature.text_advanced import LangDetector

    return LangDetector(languages=languages, top_k=top_k)(self)


def recognize_entities(self: Feature) -> Feature:
    from ..stages.feature.text_advanced import NameEntityRecognizer

    return NameEntityRecognizer()(self)


def detect_mime_types(self: Feature, type_hint: Optional[str] = None) -> Feature:
    from ..stages.feature.text_advanced import MimeTypeDetector

    return MimeTypeDetector(type_hint=type_hint)(self)


def word2vec(self: Feature, **params) -> Feature:
    from ..stages.feature.text_advanced import Word2Vec

    return Word2Vec(**params)(self)


def lda_topics(self: Feature, k: int = 10, **params) -> Feature:
    from ..stages.feature.text_advanced import LDA

    return LDA(k=k, **params)(self)


def to_email_domain(self: Feature) -> Feature:
    from ..stages.feature.parsers import EmailToDomain

    return EmailToDomain()(self)


def is_valid_email(self: Feature) -> Feature:
    from ..stages.feature.parsers import IsValidEmail

    return IsValidEmail()(self)


def parse_phone(self: Feature, default_region: str = "US") -> Feature:
    from ..stages.feature.parsers import ParsePhone

    return ParsePhone(default_region=default_region)(self)


def is_valid_phone(self: Feature, default_region: str = "US") -> Feature:
    from ..stages.feature.parsers import IsValidPhone

    return IsValidPhone(default_region=default_region)(self)


def to_url_domain(self: Feature) -> Feature:
    from ..stages.feature.parsers import UrlToDomain

    return UrlToDomain()(self)


def is_valid_url(self: Feature) -> Feature:
    from ..stages.feature.parsers import IsValidUrl

    return IsValidUrl()(self)


def b64_to_text(self: Feature) -> Feature:
    from ..stages.feature.parsers import Base64ToText

    return Base64ToText()(self)


def scale_feature(self: Feature, scaling_type: str = "linear", slope: float = 1.0,
                  intercept: float = 0.0) -> Feature:
    from ..stages.feature.misc import ScalerTransformer

    return ScalerTransformer(scaling_type=scaling_type, slope=slope,
                             intercept=intercept)(self)


def descale_feature(self: Feature, scaled: Feature) -> Feature:
    from ..stages.feature.misc import DescalerTransformer

    return DescalerTransformer()(self, scaled)


def filter_map(self: Feature, whitelist: Optional[Sequence[str]] = None,
               blacklist: Optional[Sequence[str]] = None,
               filter_empty: bool = True) -> Feature:
    from ..stages.feature.misc import FilterMap

    return FilterMap(whitelist=whitelist, blacklist=blacklist,
                     filter_empty=filter_empty)(self)


# --- date enrichments (RichDateFeature.scala) ---------------------------------------------
def to_unit_circle(self: Feature, time_periods: Optional[Sequence[str]] = None) -> Feature:
    kw = {} if time_periods is None else {"time_periods": tuple(time_periods)}
    return DateToUnitCircleVectorizer(**kw)(self)


def to_time_period(self: Feature, period: str = "DayOfWeek") -> Feature:
    from ..stages.feature.misc import TimePeriodTransformer

    return TimePeriodTransformer(period=period)(self)


# --- map enrichments (RichMapFeature.scala per-type vectorize overloads) ------------------
def vectorize_map(self: Feature, *others: Feature,
                  top_k: int = DEFAULTS.top_k,
                  min_support: int = DEFAULTS.min_support,
                  clean_text: bool = True, track_nulls: bool = True,
                  allow_keys: Sequence[str] = (),
                  block_keys: Sequence[str] = (),
                  max_cardinality: int = 30,
                  num_features: int = DEFAULTS.num_hash_features) -> Feature:
    """Kind-aware map vectorization (the RichMapFeature.vectorize overload
    family): text-valued maps take the smart categorical-vs-hashing path with
    its cardinality/width knobs; every other map kind pivots per (key, value)
    with top_k/min_support and optional key allow/block lists."""
    from ..stages.feature.collections import _TEXT_MAPS

    kind = self.kind.name
    if kind in _TEXT_MAPS:
        from ..stages.feature.collections import SmartTextMapVectorizer

        if allow_keys or block_keys:
            # the smart text-map path has no key filters — silently hashing a
            # blocked key would defeat the caller's exclusion; filter EVERY
            # map input (self and others alike) before recursing
            from ..stages.feature.misc import FilterMap

            def _filt(f: Feature) -> Feature:
                return FilterMap(whitelist=list(allow_keys) or None,
                                 blacklist=list(block_keys) or None)(f)

            return vectorize_map(
                _filt(self), *(_filt(o) for o in others),
                top_k=top_k, min_support=min_support,
                clean_text=clean_text, track_nulls=track_nulls,
                max_cardinality=max_cardinality, num_features=num_features)
        return SmartTextMapVectorizer(
            max_cardinality=max_cardinality, top_k=top_k,
            min_support=min_support, num_features=num_features,
            clean_text=clean_text, track_nulls=track_nulls)(self, *others)
    from ..stages.feature.collections import MapVectorizer

    if kind in ("DateMap", "DateTimeMap"):
        # circular encoding per period + days-since, combined — the reference's
        # RichDateMapFeature.vectorize shape (RichMapFeature.scala:757-782)
        from ..stages.feature.combiner import VectorsCombiner
        from ..stages.feature.date import TIME_PERIODS, DateMapToUnitCircleVectorizer

        circ_ins = (self,) + tuple(others)
        if allow_keys or block_keys:
            # the circular vectorizer has no key filters of its own
            from ..stages.feature.misc import FilterMap

            circ_ins = tuple(
                FilterMap(whitelist=list(allow_keys) or None,
                          blacklist=list(block_keys) or None)(f)
                for f in circ_ins)
        circ = DateMapToUnitCircleVectorizer(
            time_periods=list(TIME_PERIODS))(*circ_ins)
        days = MapVectorizer(
            top_k=top_k, min_support=min_support, clean_text=clean_text,
            track_nulls=track_nulls, allow_keys=allow_keys,
            block_keys=block_keys)(self, *others)
        return VectorsCombiner()(circ, days)

    return MapVectorizer(
        top_k=top_k, min_support=min_support, clean_text=clean_text,
        track_nulls=track_nulls, allow_keys=allow_keys,
        block_keys=block_keys)(self, *others)


# --- set enrichments (RichSetFeature.scala) -----------------------------------------------
def pivot_set(self: Feature, *others: Feature,
              top_k: int = DEFAULTS.top_k,
              min_support: int = DEFAULTS.min_support,
              clean_text: bool = True, track_nulls: bool = True) -> Feature:
    """MultiPickList -> multi-hot pivot over the fitted top-k values
    (RichSetFeature.pivot/vectorize)."""
    from ..stages.feature.collections import MultiPickListVectorizer

    return MultiPickListVectorizer(
        top_k=top_k, min_support=min_support, clean_text=clean_text,
        track_nulls=track_nulls)(self, *others)


# --- list enrichments (RichListFeature.scala) ---------------------------------------------
def vectorize_dates(self: Feature, *others: Feature,
                    reference_date_ms: Optional[int] = None,
                    track_nulls: bool = True) -> Feature:
    """DateList/DateTimeList -> time-since-last + count vector
    (RichListFeature.vectorize for date lists)."""
    from ..stages.feature.date import DateListVectorizer

    return DateListVectorizer(reference_date_ms=reference_date_ms,
                              track_nulls=track_nulls)(self, *others)


def vectorize_geolocation(self: Feature, *others: Feature,
                          track_nulls: bool = True) -> Feature:
    """Geolocation -> (lat, lon, accuracy) slots (RichLocationFeature)."""
    from ..stages.feature.collections import GeolocationVectorizer

    return GeolocationVectorizer(track_nulls=track_nulls)(self, *others)


def _attach() -> None:
    Feature.__add__ = _binary_op("+")
    Feature.__sub__ = _binary_op("-")
    Feature.__mul__ = _binary_op("*")
    Feature.__truediv__ = _binary_op("/")
    Feature.__radd__ = _reverse_op("+")
    Feature.__rsub__ = _reverse_op("-")
    Feature.__rmul__ = _reverse_op("*")
    Feature.__rtruediv__ = _reverse_op("/")
    Feature.__pow__ = lambda self, s: ScalarMathTransformer("**", float(s))(self)
    Feature.__rpow__ = _reverse_op("**")
    Feature.__neg__ = lambda self: UnaryMathTransformer("negate")(self)
    Feature.__abs__ = lambda self: UnaryMathTransformer("abs")(self)
    for fn in ("log", "sqrt", "exp", "floor", "ceil", "sigmoid"):
        setattr(Feature, fn, (lambda name: lambda self: UnaryMathTransformer(name)(self))(fn))
    Feature.alias = alias
    Feature.occurs = occurs
    Feature.map_via = map_via
    Feature.transform_with = transform_with
    Feature.fill_missing_with_mean = fill_missing_with_mean
    Feature.bucketize = bucketize
    Feature.auto_bucketize = auto_bucketize
    Feature.z_normalize = z_normalize
    Feature.vectorize = vectorize_feature
    Feature.sanity_check = sanity_check
    Feature.tokenize = tokenize_feature
    Feature.pivot = pivot
    Feature.smart_vectorize = smart_vectorize
    Feature.index_string = index_string
    Feature.text_len = text_len
    Feature.hash_vectorize = hash_vectorize
    Feature.to_unit_circle = to_unit_circle
    Feature.to_time_period = to_time_period
    Feature.ngram = ngram
    Feature.remove_stop_words = remove_stop_words
    Feature.count_vectorize = count_vectorize
    Feature.ngram_similarity = ngram_similarity
    Feature.jaccard_similarity = jaccard_similarity
    Feature.detect_languages = detect_languages
    Feature.recognize_entities = recognize_entities
    Feature.detect_mime_types = detect_mime_types
    Feature.word2vec = word2vec
    Feature.lda_topics = lda_topics
    Feature.to_email_domain = to_email_domain
    Feature.is_valid_email = is_valid_email
    Feature.parse_phone = parse_phone
    Feature.is_valid_phone = is_valid_phone
    Feature.to_url_domain = to_url_domain
    Feature.is_valid_url = is_valid_url
    Feature.b64_to_text = b64_to_text
    Feature.vectorize_map = vectorize_map
    Feature.pivot_set = pivot_set
    Feature.vectorize_dates = vectorize_dates
    Feature.vectorize_geolocation = vectorize_geolocation
    Feature.scale = scale_feature
    Feature.descale = descale_feature
    Feature.filter_map = filter_map


_attach()

__all__ = ["transmogrify"]
