"""SanityChecker: post-vectorization feature validation and automatic drop.

TPU-native analog of the reference SanityChecker (core/src/main/scala/com/salesforce/
op/stages/impl/preparators/SanityChecker.scala:236 class, :535 fitFn, :259/:366/:420
stats + drop + categorical tests, defaults :720-733) — the estimator stage
`(label RealNN, features OPVector) -> OPVector` that computes per-slot statistics and
label associations, drops offending slots, and records the reasons.

The reference runs MLlib colStats + Statistics.corr + per-group contingency jobs; here
the whole pass is fused jnp (ops/stats.py): moments and label correlations are one
X-sized reduction, categorical contingency tables are one-hot matmuls per indicator
group. Drop decisions and metadata assembly stay host-side. Reasons land in
SanityCheckerSummary (the SanityCheckerMetadata analog) carried by the fitted model.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.stats import (
    column_stats,
    contingency_table,
    pearson_with_label,
    spearman_with_label,
)
from ..stages.base import Estimator, Transformer, register_stage
from ..types import Column, kind_of
from ..types.vector_schema import SlotInfo, VectorSchema


@jax.jit
def _onehot_contingency(Xd, flat_idx, yd, uniq, w=None):
    """Indicator-slot gather + label one-hot + contingency tables as one
    program (the SanityChecker's warm-label path; see fit_columns). `w` masks
    mesh-padding rows (weight 0) out of the counts."""
    lab_oh = (yd[:, None] == uniq[None, :]).astype(jnp.float32)
    return contingency_table(jnp.take(Xd, flat_idx, axis=1), lab_oh, w)

_EPS = 1e-12


def _cramers_v_np(t: np.ndarray) -> float:
    """numpy mirror of ops.stats.cramers_v (host math on a small [K, C] table —
    per-group device dispatches here were the SanityChecker's dominant cost)."""
    t = np.asarray(t, np.float64)
    n = t.sum() + _EPS
    rows = t.sum(1, keepdims=True)
    cols = t.sum(0, keepdims=True)
    expected = rows @ cols / n
    chi2 = np.where(expected > _EPS,
                    (t - expected) ** 2 / np.clip(expected, _EPS, None), 0.0).sum()
    k = min((rows[:, 0] > 0).sum(), (cols[0] > 0).sum())
    dof = max(k - 1.0, 1e-6)
    return float(np.sqrt(chi2 / (n * dof)))


def _rule_confidence_np(t: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """numpy mirror of ops.stats.rule_confidence."""
    t = np.asarray(t, np.float64)
    n = t.sum() + _EPS
    row = t.sum(1)
    conf = np.where(row[:, None] > _EPS,
                    t / np.clip(row[:, None], _EPS, None), 0.0).max(1)
    return conf, row / n


def _pmi_np(t: np.ndarray) -> tuple[np.ndarray, float]:
    """numpy mirror of ops.stats.pointwise_mutual_info/mutual_information:
    (PMI matrix [K, C] in bits, total mutual information in bits) — the
    reference's OpStatistics.mutualInfo (OpStatistics.scala:234-271)."""
    t = np.asarray(t, np.float64)
    n = t.sum() + _EPS
    pxy = t / n
    px = pxy.sum(1, keepdims=True)
    py = pxy.sum(0, keepdims=True)
    safe = (pxy > _EPS) & (px > _EPS) & (py > _EPS)
    pmi = np.where(
        safe,
        np.log2(np.clip(pxy, _EPS, None) / np.clip(px * py, _EPS, None)), 0.0)
    mi = float((pmi * pxy).sum())
    return pmi, mi


@dataclass
class SlotStats:
    """Per-slot diagnostics (SanityCheckerMetadata column entries)."""

    name: str
    mean: float
    variance: float
    min: float
    max: float
    corr_with_label: float
    cramers_v: Optional[float] = None
    max_rule_confidence: Optional[float] = None
    support: Optional[float] = None
    #: this indicator's PMI with each label value (bits), label order = the
    #: group's "labels" list (OpStatistics pointwiseMutualInfo row)
    pmi_with_label: Optional[list] = None


@dataclass
class SanityCheckerSummary:
    """The training-time report (analog of SanityCheckerMetadata.scala)."""

    n_rows: int
    n_sampled: int
    slot_stats: list[SlotStats] = field(default_factory=list)
    dropped: list[dict] = field(default_factory=list)  # {"name", "reason"}
    categorical_groups: list[dict] = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "n_rows": self.n_rows,
            "n_sampled": self.n_sampled,
            "slot_stats": [vars(s) for s in self.slot_stats],
            "dropped": list(self.dropped),
            "categorical_groups": list(self.categorical_groups),
        }

    def pretty(self) -> str:
        from ..utils.table import pretty_table

        lines = [f"SanityChecker: {len(self.dropped)} of {len(self.slot_stats)} "
                 "slots dropped"]
        if self.dropped:
            lines.append(pretty_table(
                [[d["name"], d["reason"]] for d in self.dropped],
                headers=["slot", "reason"], max_col_width=64))
        return "\n".join(lines)


@register_stage
class SanityChecker(Estimator):
    """Estimator `(label, OPVector) -> OPVector` dropping low-signal / leaking slots.

    Drop rules (reference defaults, SanityChecker.scala:720-733):
      - variance < min_variance                      -> "zero/low variance"
      - |corr(label)| > max_correlation              -> label leakage
      - |corr(label)| < min_correlation              -> uninformative (off by default)
      - group Cramér's V > max_cramers_v             -> categorical leakage (whole group)
      - rule confidence > max_rule_confidence
        with support >= min_required_rule_support    -> degenerate indicator (off by default)
    """

    operation_name = "sanityChecker"
    arity = (2, 2)
    fit_only_inputs = (0,)  # the label drives drop decisions, never the output rows
    #: device mesh slot (None = unmeshed): the design-matrix stats pass then
    #: shards rows over DATA_AXIS (reductions psum over ICI); threaded in by
    #: Workflow.train's auto-mesh or set directly. Never serialized.
    mesh = None

    def __init__(self, check_sample: float = 1.0, sample_seed: int = 42,
                 max_correlation: float = 0.95, min_correlation: float = 0.0,
                 min_variance: float = 1e-5, max_cramers_v: float = 0.95,
                 remove_bad_features: bool = True, corr_type: str = "pearson",
                 max_rule_confidence: float = 1.0,
                 min_required_rule_support: float = 1.0,
                 categorical_label_cardinality: int = 30,
                 pad_to_bucket: bool = True):
        if corr_type not in ("pearson", "spearman"):
            raise ValueError("corr_type must be 'pearson' or 'spearman'")
        super().__init__(check_sample=float(check_sample), sample_seed=int(sample_seed),
                         max_correlation=float(max_correlation),
                         min_correlation=float(min_correlation),
                         min_variance=float(min_variance),
                         max_cramers_v=float(max_cramers_v),
                         remove_bad_features=bool(remove_bad_features),
                         corr_type=corr_type,
                         max_rule_confidence=float(max_rule_confidence),
                         min_required_rule_support=float(min_required_rule_support),
                         categorical_label_cardinality=int(categorical_label_cardinality),
                         pad_to_bucket=bool(pad_to_bucket))

    def out_kind(self, in_kinds):
        resp, feat = in_kinds
        if feat.name != "OPVector":
            raise TypeError(f"SanityChecker features input must be OPVector, got {feat.name}")
        return kind_of("OPVector")

    def static_width(self, in_widths):
        """`op explain` width hook: pass the vector input's width through —
        an upper bound when remove_bad_features can drop slots (see
        static_width_exact)."""
        return in_widths[-1]

    @property
    def static_width_exact(self) -> bool:
        return not self.params.get("remove_bad_features", False)

    def is_response_out(self) -> bool:
        return False

    def fit_columns(self, cols: Sequence[Column]) -> Transformer:
        p = self.params
        # the matrix NEVER visits the host: sampling is a device gather, the
        # stats/corr programs read the device-resident columns directly, and
        # the only host copies are the label (np.unique / one-hot) and the
        # per-column stat vectors — all in ONE fused device_get (eight serial
        # ~100ms fetches before; ~0.9s of every steady train on the tunnel)
        X_dev = jnp.asarray(cols[1].values, jnp.float32)
        y_dev = jnp.asarray(cols[0].filled(0.0), jnp.float32)
        n, d = X_dev.shape
        schema = cols[1].schema or VectorSchema(
            tuple(SlotInfo(f"f{i}", "Real") for i in range(d))
        )

        # --- sample (checkSample) ----------------------------------------------------
        if p["check_sample"] < 1.0:
            rng = np.random.default_rng(p["sample_seed"])
            take = max(2, int(round(n * p["check_sample"])))
            idx = jnp.asarray(rng.choice(n, size=take, replace=False))
            Xd, yd = jnp.take(X_dev, idx, axis=0), jnp.take(y_dev, idx)
        else:
            Xd, yd = X_dev, y_dev

        # --- mesh placement ----------------------------------------------------------
        # rows over DATA_AXIS: the moment/correlation/contingency reductions
        # below auto-partition and psum over ICI. Non-dividing row counts pad
        # by repeating row 0 at WEIGHT 0 (exact for every weighted reduction;
        # min/max see only existing values) — except spearman, whose ranks are
        # not pad-safe, so it shards only on even division. Device-side twin
        # of mesh.shard_rows_padded: the matrix is already device-resident,
        # so padding runs as jnp ops instead of a host round trip.
        n_stat = int(Xd.shape[0])
        ws = None
        mesh = self.mesh
        if mesh is not None:
            from ..mesh import DATA_AXIS, record_sharded_dispatch, shard_batch

            n_data = int(mesh.shape[DATA_AXIS])
            pad = (-n_stat) % n_data
            if n_data <= 1 or (pad and p["corr_type"] == "spearman"):
                mesh = None
            else:
                if pad:
                    Xd = jnp.concatenate(
                        [Xd, jnp.broadcast_to(Xd[:1], (pad, d))])
                    yd = jnp.concatenate(
                        [yd, jnp.broadcast_to(yd[:1], (pad,))])
                    ws = jnp.concatenate([jnp.ones(n_stat, jnp.float32),
                                          jnp.zeros(pad, jnp.float32)])
                Xd = shard_batch(mesh, Xd)
                yd = shard_batch(mesh, yd)
                if ws is not None:
                    ws = shard_batch(mesh, ws)
                record_sharded_dispatch()

        # --- fused stats pass --------------------------------------------------------
        # all programs dispatch async; ONE fetch returns stats + corr + label.
        # The contingency tables need the label's UNIQUE values (host), which
        # would force a SECOND fetch+dispatch+fetch (~0.13s of every steady
        # train on a tunneled device) — so uniq is memoized on the label
        # COLUMN object (the AutoML steady state re-trains fresh graphs on the
        # same table): warm trains build the label one-hot ON DEVICE and the
        # whole fit is ONE device_get.
        if mesh is None:
            # single-device stats ride the shared training AOT store: a warm
            # process hydrates the fused stats/correlation executables instead
            # of tracing + compiling them (utils/export_cache.py)
            from ..utils.export_cache import exec_cached_call

            stats = exec_cached_call(column_stats, "sanity|column_stats",
                                     args=(Xd, ws), label="stats:column_stats",
                                     lane="stats")
            if p["corr_type"] == "spearman":
                corr = exec_cached_call(spearman_with_label, "sanity|spearman",
                                        args=(Xd, yd),
                                        label="stats:spearman", lane="stats")
            else:
                corr = exec_cached_call(pearson_with_label, "sanity|pearson",
                                        args=(Xd, yd, ws),
                                        label="stats:pearson", lane="stats")
        else:
            stats = column_stats(Xd, ws)
            if p["corr_type"] == "spearman":
                corr = spearman_with_label(Xd, yd)
            else:
                corr = pearson_with_label(Xd, yd, ws)

        groups = schema.groups()
        ind_groups = [
            (key, [i for i in idxs if schema[i].indicator_value is not None])
            for key, idxs in groups.items()
        ]
        ind_groups = [(key, idxs) for key, idxs in ind_groups if idxs]
        flat_idx = [i for _, idxs in ind_groups for i in idxs]

        uniq_key = (p["check_sample"], p["sample_seed"],
                    p["categorical_label_cardinality"])
        cached = getattr(cols[0], "_sanity_label_uniq", None)
        uniq = cached[1] if cached is not None and cached[0] == uniq_key else None

        def is_categorical(u):
            return len(u) <= p["categorical_label_cardinality"]

        tables_dev = None
        if uniq is not None and flat_idx and is_categorical(uniq):
            # warm path: slot gather + label one-hot + contingency as ONE
            # jitted dispatch alongside the stats (eager jnp here would pay
            # 4-6 serial ~17ms dispatches on a tunneled device — measured
            # slower than the second fetch it replaces)
            tables_dev = _onehot_contingency(
                Xd, jnp.asarray(flat_idx), yd,
                jnp.asarray(uniq, jnp.float32), ws)
        # yd is only consumed by the cold path's np.unique — warm trains skip
        # its transfer entirely
        from .. import obs

        with obs.span("sanity_checker:stats_fetch"):
            mean, var, mn, mx, corr, ys, all_tables = jax.device_get(
                (stats.mean, stats.variance, stats.min, stats.max, corr,
                 yd if uniq is None else None, tables_dev))

        # --- categorical tests: per indicator group ----------------------------------
        if uniq is None:
            uniq = np.unique(ys)
            cols[0]._sanity_label_uniq = (uniq_key, uniq)
        label_is_categorical = is_categorical(uniq)
        group_cv: dict[tuple, float] = {}
        slot_conf = np.full(d, np.nan)
        slot_support = np.full(d, np.nan)
        slot_pmi: dict[int, list] = {}
        categorical_groups = []
        if label_is_categorical:
            if all_tables is None and flat_idx:
                # cold path (first train on this label column): host uniq was
                # not known at dispatch time, so the tables are a second
                # dispatch+fetch — through the SAME jitted program the warm
                # path uses, which also pre-compiles it at these shapes.
                # Contingency stats are defined over 0/1 indicator slots only —
                # a group can also carry continuous slots (e.g. a numeric value
                # next to its null indicator), which must not enter the table.
                # ALL groups' tables come from ONE device matmul (their rows
                # are disjoint slot sets); per-group Cramér's V / rule stats
                # are then O(K*C) numpy.
                all_tables = np.asarray(_onehot_contingency(
                    Xd, jnp.asarray(flat_idx), yd,
                    jnp.asarray(uniq, jnp.float32), ws))
            pos = 0
            for key, idxs in ind_groups:
                table = all_tables[pos:pos + len(idxs)]
                pos += len(idxs)
                cv = _cramers_v_np(table)
                conf, support = _rule_confidence_np(table)
                pmi, mi = _pmi_np(table)
                group_cv[key] = cv
                for j, i in enumerate(idxs):
                    slot_conf[i] = float(conf[j])
                    slot_support[i] = float(support[j])
                    slot_pmi[i] = [round(float(v), 6) for v in pmi[j]]
                categorical_groups.append(
                    {"group": "_".join(str(k) for k in key if k is not None),
                     "cramers_v": cv,
                     "mutual_info": mi,
                     "labels": [float(u) for u in uniq],
                     "pointwise_mutual_info": {
                         str(float(uniq[c])): [round(float(v), 6)
                                               for v in pmi[:, c]]
                         for c in range(pmi.shape[1])
                     },
                     "slots": [schema[i].column_name() for i in idxs]}
                )

        # --- drop decisions ----------------------------------------------------------
        # inert pad slots from upstream width bucketing are bookkeeping noise: never
        # kept (the model re-pads its own output), never reported as drops
        pad_idx = {i for i, s in enumerate(schema) if s.is_padding}
        names = schema.column_names()
        reasons: dict[int, str] = {}
        for i in range(d):
            if i in pad_idx:
                continue
            if var[i] < p["min_variance"]:
                reasons[i] = f"variance {var[i]:.2e} < min_variance {p['min_variance']:.2e}"
            elif abs(corr[i]) > p["max_correlation"]:
                reasons[i] = (f"|corr| {abs(corr[i]):.3f} > max_correlation "
                              f"{p['max_correlation']} (label leakage)")
            elif p["min_correlation"] > 0.0 and abs(corr[i]) < p["min_correlation"]:
                reasons[i] = f"|corr| {abs(corr[i]):.3f} < min_correlation {p['min_correlation']}"
            elif (p["max_rule_confidence"] < 1.0 and not np.isnan(slot_conf[i])
                  and slot_conf[i] > p["max_rule_confidence"]
                  and slot_support[i] >= p["min_required_rule_support"]):
                reasons[i] = (f"rule confidence {slot_conf[i]:.3f} > "
                              f"{p['max_rule_confidence']} at support {slot_support[i]:.3f}")
        for key, cv in group_cv.items():
            if cv > p["max_cramers_v"]:
                for i in groups[key]:
                    if schema[i].indicator_value is None:
                        continue
                    reasons.setdefault(
                        i, f"group Cramér's V {cv:.3f} > max_cramers_v {p['max_cramers_v']}"
                    )

        keep = [i for i in range(d) if i not in reasons and i not in pad_idx]
        if p["remove_bad_features"] and not keep:
            raise ValueError(
                "SanityChecker would drop every feature slot — check the label or relax "
                "thresholds (reference throws the same way)"
            )
        if not p["remove_bad_features"]:
            keep = [i for i in range(d) if i not in pad_idx]

        summary = SanityCheckerSummary(
            n_rows=n,
            n_sampled=n_stat,
            slot_stats=[
                SlotStats(
                    name=names[i], mean=float(mean[i]), variance=float(var[i]),
                    min=float(mn[i]), max=float(mx[i]), corr_with_label=float(corr[i]),
                    cramers_v=group_cv.get(schema[i].grouping_key()),
                    max_rule_confidence=(None if np.isnan(slot_conf[i]) else float(slot_conf[i])),
                    support=(None if np.isnan(slot_support[i]) else float(slot_support[i])),
                    pmi_with_label=slot_pmi.get(i),
                )
                for i in range(d) if i not in pad_idx
            ],
            dropped=[{"name": names[i], "reason": reasons[i]} for i in sorted(reasons)]
            if p["remove_bad_features"] else [],
            categorical_groups=categorical_groups,
        )
        from ..types import bucket_width

        model = SanityCheckerModel(
            keep_indices=keep,
            dropped=[d["name"] for d in summary.dropped],
            pad_to=bucket_width(len(keep)) if p.get("pad_to_bucket", True) else 0,
        )
        model.summary_ = summary
        return model


@partial(jax.jit, static_argnames=("pad_to",))
def _select_pad_kernel(vec, keep, pad_to):
    """Column subset + pad as one module-level shape-keyed program."""
    from ..types.vector_schema import pad_vector_values

    out = jnp.take(jnp.asarray(vec, jnp.float32), keep, axis=1)
    if pad_to > out.shape[1]:
        out = pad_vector_values(out, None, pad_to)[0]
    return out


@register_stage
class SanityCheckerModel(Transformer):
    """Fitted column-subset transform: keep the surviving slots, re-derive the schema."""

    operation_name = "sanityChecker"
    arity = (2, 2)
    device_op = True
    fit_only_inputs = (0,)  # transform reads only the vector input
    #: the device work dispatches to the module-level shape-keyed kernel above
    #: with keep-indices as an ARGUMENT. Fusing this stage into the per-plan
    #: jit instead keyed the program on its input's uid-suffixed name (the
    #: combiner's output) — a fresh ~60-90ms retrace+compile on EVERY steady
    #: train (caught by the round-5 compile-log soak; same class of offender
    #: as the r4 VectorsCombiner fix).
    kernel_jitted = True

    def __init__(self, keep_indices: Sequence[int] = (), dropped: Sequence[str] = (),
                 pad_to: int = 0):
        super().__init__(keep_indices=[int(i) for i in keep_indices],
                         dropped=list(dropped), pad_to=int(pad_to))
        self.summary_: Optional[SanityCheckerSummary] = None

    def out_kind(self, in_kinds):
        return kind_of("OPVector")

    def static_width(self, in_widths):
        return int(self.params["pad_to"]) or len(self.params["keep_indices"])

    def is_response_out(self) -> bool:
        return False

    def transform_columns(self, cols: Sequence[Column]) -> Column:
        vec = cols[1]
        keep = jnp.asarray(self.params["keep_indices"], jnp.int32)
        pad_to = self.params.get("pad_to", 0)
        out = _select_pad_kernel(vec.values, keep, pad_to)
        schema = vec.schema.select(self.params["keep_indices"]) if vec.schema else None
        if schema is not None and pad_to > schema.size:
            schema = schema.pad_to(pad_to)
        return Column.vector(out, schema=schema)
