from .sanity_checker import SanityChecker, SanityCheckerModel, SanityCheckerSummary

__all__ = ["SanityChecker", "SanityCheckerModel", "SanityCheckerSummary"]
