"""Mid-search checkpoint/resume for the ModelSelector (SURVEY §5.4).

The reference has no mid-train checkpointing (only model-level save); this closes the
gap the TPU build was asked to close: every completed (family, grid-group[, fold])
unit of the search appends its validation results to a JSONL file as soon as it
finishes, fsync'd, so a killed search resumes by skipping completed groups and
produces a bit-identical summary (fold assignment, balancing, and fit programs are
all seed-deterministic — the only state worth persisting is the completed results,
guarded by a fingerprint of everything that determines them).
"""
from __future__ import annotations

import hashlib
import json
from typing import Optional

import numpy as np

from ..utils.jsonl_checkpoint import JsonlCheckpoint


def search_fingerprint(X, y, weights, val_masks, keep, problem_type: str,
                       metric: str, candidates) -> str:
    """Digest of everything that determines the search results: the prepared data,
    fold layout, metric, and candidate descriptors. A checkpoint whose fingerprint
    differs is stale (different data/config) and is discarded."""
    h = hashlib.sha256()
    for arr in (X, y, weights, val_masks, keep):
        a = np.ascontiguousarray(np.asarray(arr, np.float32))
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    h.update(problem_type.encode())
    h.update(metric.encode())
    for template, grid in candidates:
        h.update(type(template).__name__.encode())
        h.update(json.dumps(template.params, sort_keys=True, default=str).encode())
        h.update(json.dumps(list(grid or []), sort_keys=True, default=str).encode())
    return h.hexdigest()


def group_key(candidate_index: int, static_items, points, fold: Optional[int] = None
              ) -> str:
    """Stable identity of one executable search unit."""
    payload = {"ci": candidate_index,
               "static": sorted((k, str(v)) for k, v in static_items),
               "points": points}
    if fold is not None:
        payload["fold"] = fold
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True, default=str).encode()).hexdigest()


class SearchCheckpoint(JsonlCheckpoint):
    """Append-only JSONL: one header record + one record per completed group.
    File protocol (fingerprint header, fsync'd appends, torn-tail truncation)
    is the shared utils.jsonl_checkpoint.JsonlCheckpoint."""

    RECORD_KIND = "group"
    PAYLOAD_FIELD = "results"

    def get(self, key: str) -> Optional[list[dict]]:
        return self._records.get(key)
