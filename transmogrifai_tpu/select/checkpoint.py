"""Mid-search checkpoint/resume for the ModelSelector (SURVEY §5.4).

The reference has no mid-train checkpointing (only model-level save); this closes the
gap the TPU build was asked to close: every completed (family, grid-group[, fold])
unit of the search appends its validation results to a JSONL file as soon as it
finishes, fsync'd, so a killed search resumes by skipping completed groups and
produces a bit-identical summary (fold assignment, balancing, and fit programs are
all seed-deterministic — the only state worth persisting is the completed results,
guarded by a fingerprint of everything that determines them).
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

import numpy as np


def search_fingerprint(X, y, weights, val_masks, keep, problem_type: str,
                       metric: str, candidates) -> str:
    """Digest of everything that determines the search results: the prepared data,
    fold layout, metric, and candidate descriptors. A checkpoint whose fingerprint
    differs is stale (different data/config) and is discarded."""
    h = hashlib.sha256()
    for arr in (X, y, weights, val_masks, keep):
        a = np.ascontiguousarray(np.asarray(arr, np.float32))
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    h.update(problem_type.encode())
    h.update(metric.encode())
    for template, grid in candidates:
        h.update(type(template).__name__.encode())
        h.update(json.dumps(template.params, sort_keys=True, default=str).encode())
        h.update(json.dumps(list(grid or []), sort_keys=True, default=str).encode())
    return h.hexdigest()


def group_key(candidate_index: int, static_items, points, fold: Optional[int] = None
              ) -> str:
    """Stable identity of one executable search unit."""
    payload = {"ci": candidate_index,
               "static": sorted((k, str(v)) for k, v in static_items),
               "points": points}
    if fold is not None:
        payload["fold"] = fold
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True, default=str).encode()).hexdigest()


class SearchCheckpoint:
    """Append-only JSONL: one header record + one record per completed group."""

    def __init__(self, path: str, fingerprint: str):
        self.path = path
        self.fingerprint = fingerprint
        self._groups: dict[str, list[dict]] = {}
        self._load_or_init()

    def _load_or_init(self) -> None:
        if os.path.exists(self.path):
            lines = []
            try:
                with open(self.path) as fh:
                    for ln in fh:
                        if not ln.strip():
                            continue
                        try:
                            lines.append(json.loads(ln))
                        except json.JSONDecodeError:
                            break  # torn final line from a crash: keep what parsed
            except OSError:
                lines = []
            if lines and lines[0].get("kind") == "header" \
                    and lines[0].get("fingerprint") == self.fingerprint:
                for rec in lines[1:]:
                    if rec.get("kind") == "group":
                        self._groups[rec["key"]] = rec["results"]
                return
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        with open(self.path, "w") as fh:
            fh.write(json.dumps({"kind": "header",
                                 "fingerprint": self.fingerprint}) + "\n")

    def get(self, key: str) -> Optional[list[dict]]:
        return self._groups.get(key)

    def put(self, key: str, results: list[dict]) -> None:
        self._groups[key] = results
        with open(self.path, "a") as fh:
            fh.write(json.dumps({"kind": "group", "key": key,
                                 "results": results}) + "\n")
            fh.flush()
            os.fsync(fh.fileno())

    def complete(self) -> None:
        """The search finished: remove the file so the next train starts fresh."""
        try:
            os.remove(self.path)
        except FileNotFoundError:
            pass
