"""Data splitters: train/holdout reservation, class balancing, rare-label cutting.

Analogs of the reference tuning splitters (core/.../impl/tuning/Splitter.scala:47,
DataSplitter.scala:62, DataBalancer.scala:73-238, DataCutter.scala:76) with one
deliberate TPU-first change: the balancer does NOT materialize a resampled dataset
(Spark `sample()` produces a new RDD with a different row count). Resampling changes
array shapes, which would force recompilation per fold; instead balancing is expressed
as per-row *sample weights* that every trainer threads through its loss (ops/linear.py
`sample_weight`). Expected class contributions match the reference's up/down-sample
fractions exactly, and shapes stay static so folds x grid ride vmap axes.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

# reference defaults: Splitter.scala:141-145
RESERVE_TEST_FRACTION_DEFAULT = 0.1
SAMPLE_FRACTION_DEFAULT = 0.1
MAX_TRAINING_SAMPLE_DEFAULT = int(1e6)
MAX_LABEL_CATEGORIES_DEFAULT = 100
MIN_LABEL_FRACTION_DEFAULT = 0.0


@dataclass
class SplitterSummary:
    """What the splitter decided (recorded into ModelSelectorSummary, the analog of
    the reference's SplitterSummary metadata)."""

    splitter: str = "DataSplitter"
    reserve_test_fraction: float = RESERVE_TEST_FRACTION_DEFAULT
    #: balancer: multiplier applied to the majority class weight (<= 1 means down-weight)
    down_sample_fraction: Optional[float] = None
    #: balancer: multiplier applied to the minority class weight (>= 1 means up-weight)
    up_sample_fraction: Optional[float] = None
    positive_fraction: Optional[float] = None
    #: cutter: label values kept / dropped
    labels_kept: list = field(default_factory=list)
    labels_dropped: list = field(default_factory=list)

    def to_json(self) -> dict:
        return {k: v for k, v in self.__dict__.items() if v is not None}


class DataSplitter:
    """Random train/holdout reservation (analog of DataSplitter.scala:62)."""

    def __init__(self, reserve_test_fraction: float = RESERVE_TEST_FRACTION_DEFAULT,
                 max_training_sample: int = MAX_TRAINING_SAMPLE_DEFAULT,
                 seed: int = 42):
        if not 0.0 <= reserve_test_fraction < 1.0:
            raise ValueError("reserve_test_fraction must be in [0, 1)")
        self.reserve_test_fraction = reserve_test_fraction
        self.max_training_sample = max_training_sample
        self.seed = seed

    def split_indices(self, y: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """-> (train_idx, holdout_idx), seeded permutation."""
        n = len(y)
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(n)
        n_test = int(round(n * self.reserve_test_fraction))
        test, train = perm[:n_test], perm[n_test:]
        if len(train) > self.max_training_sample:
            train = train[: self.max_training_sample]
        return np.sort(train), np.sort(test)

    def prepare(self, y_train: np.ndarray) -> tuple[np.ndarray, Optional[dict],
                                                    SplitterSummary]:
        """Per-row training weights + optional label remap (identity here).
        Subclasses (balancer/cutter) override. -> (weights [N], label_map, summary)."""
        return (np.ones(len(y_train), np.float32), None,
                SplitterSummary(splitter=type(self).__name__,
                                reserve_test_fraction=self.reserve_test_fraction))


class DataBalancer(DataSplitter):
    """Binary-imbalance correction (analog of DataBalancer.scala:73-238).

    Reference semantics (DataBalancer.scala:88-113): if the minority fraction is below
    `sample_fraction`, down-sample the majority and/or up-sample the minority so the
    post-balance minority fraction equals `sample_fraction`. Here both become class
    weight multipliers with identical expected contributions."""

    def __init__(self, sample_fraction: float = SAMPLE_FRACTION_DEFAULT,
                 max_training_sample: int = MAX_TRAINING_SAMPLE_DEFAULT,
                 reserve_test_fraction: float = RESERVE_TEST_FRACTION_DEFAULT,
                 seed: int = 42):
        super().__init__(reserve_test_fraction, max_training_sample, seed)
        if not 0.0 < sample_fraction < 0.5:
            raise ValueError("sample_fraction must be in (0, 0.5)")
        self.sample_fraction = sample_fraction

    def prepare(self, y_train: np.ndarray):
        y = np.asarray(y_train, np.float32)
        n = len(y)
        pos = float((y == 1.0).sum())
        neg = n - pos
        small, big = (pos, neg) if pos <= neg else (neg, pos)
        small_is_pos = pos <= neg
        summary = SplitterSummary(
            splitter="DataBalancer",
            reserve_test_fraction=self.reserve_test_fraction,
            positive_fraction=pos / max(n, 1),
        )
        w = np.ones(n, np.float32)
        sf = self.sample_fraction
        if small == 0 or big == 0 or small / n >= sf:
            # already balanced enough (DataBalancer keeps data as-is)
            summary.down_sample_fraction = 1.0
            summary.up_sample_fraction = 1.0
            return w, None, summary
        # weight the majority down so minority carries `sf` of total weight:
        # small / (small + down * big) = sf  =>  down = small (1 - sf) / (sf * big)
        down = small * (1.0 - sf) / (sf * big)
        summary.down_sample_fraction = down
        summary.up_sample_fraction = 1.0
        big_mask = (y == 1.0) if not small_is_pos else (y != 1.0)
        w[big_mask] = down
        return w, None, summary


class DataCutter(DataSplitter):
    """Multiclass rare-label dropping (analog of DataCutter.scala:76): keep at most
    `max_label_categories` most frequent labels and only labels with frequency >=
    `min_label_fraction`; dropped rows get weight 0 and kept labels are re-indexed
    to contiguous class ids (the label_map) so trainers see a dense class axis."""

    def __init__(self, max_label_categories: int = MAX_LABEL_CATEGORIES_DEFAULT,
                 min_label_fraction: float = MIN_LABEL_FRACTION_DEFAULT,
                 reserve_test_fraction: float = RESERVE_TEST_FRACTION_DEFAULT,
                 seed: int = 42):
        super().__init__(reserve_test_fraction, seed=seed)
        if not 0.0 <= min_label_fraction < 0.5:
            raise ValueError("min_label_fraction must be in [0, 0.5)")
        self.max_label_categories = max_label_categories
        self.min_label_fraction = min_label_fraction

    def prepare(self, y_train: np.ndarray):
        y = np.asarray(y_train)
        labels, counts = np.unique(y, return_counts=True)
        frac = counts / max(len(y), 1)
        order = np.argsort(-counts)
        kept = []
        for i in order:
            if frac[i] >= self.min_label_fraction and len(kept) < self.max_label_categories:
                kept.append(labels[i])
        kept_sorted = sorted(float(k) for k in kept)
        label_map = {old: new for new, old in enumerate(kept_sorted)}
        dropped = [float(l) for l in labels if float(l) not in label_map]
        w = np.array([1.0 if float(v) in label_map else 0.0 for v in y], np.float32)
        summary = SplitterSummary(
            splitter="DataCutter",
            reserve_test_fraction=self.reserve_test_fraction,
            labels_kept=kept_sorted,
            labels_dropped=sorted(dropped),
        )
        return w, label_map, summary
