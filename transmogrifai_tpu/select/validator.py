"""Cross-validation / train-validation-split over a batched device axis.

Analog of OpValidator/OpCrossValidation/OpTrainValidationSplit (core/.../impl/tuning/
OpValidator.scala:129-256, OpCrossValidation.scala:41-118) with the central TPU-first
re-design (SURVEY §2.11c): the reference runs k-folds x grid-points as JVM Futures over
Spark jobs; here a fold is a {0,1} row-weight vector, so every (fold, grid-point) fit
has identical static shapes and the whole search is TWO nested vmaps of one compiled
fit+eval program — folds x grid becomes a batched axis that pjit can shard across the
mesh's model axis, with row-sharded matmuls psum'ing over the data axis.

Leakage control matches the reference: balancer weights apply to TRAINING rows only
(validationPrepare, OpValidator.scala:250-253); cutter keep-masks apply to both.
"""
from __future__ import annotations

import os
import threading

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .tuning_metrics import make_metric_fn


@dataclass
class EvaluatedGridPoint:
    """One (model family, grid point) validation result."""

    model_name: str
    grid_point: dict
    metric_name: str
    metric_values: list = field(default_factory=list)  # per fold
    #: index into the candidates list (families can repeat with different static
    #: params, so class name alone does not identify the template)
    candidate_index: int = 0

    @property
    def metric_mean(self) -> float:
        return float(np.mean(self.metric_values))

    def to_json(self) -> dict:
        return {
            "model_name": self.model_name,
            "grid_point": self.grid_point,
            "metric_name": self.metric_name,
            "metric_values": [float(v) for v in self.metric_values],
            "metric_mean": self.metric_mean,
        }


class ValidatorBase:
    validation_type = "base"

    def __init__(self, seed: int = 42, stratify: bool = True):
        self.seed = seed
        self.stratify = stratify

    def fold_masks(self, y: np.ndarray, keep: np.ndarray) -> np.ndarray:
        """-> val_masks [K, N] in {0,1}: row i validates in fold k iff val_masks[k,i].
        Rows with keep==0 (cutter-dropped) belong to no fold."""
        raise NotImplementedError

    def _assign_folds(self, y: np.ndarray, keep: np.ndarray, k: int) -> np.ndarray:
        """Fold id per row (stratified round-robin per class when stratify=True,
        mirroring prepareStratification, OpValidator.scala:203-226)."""
        n = len(y)
        rng = np.random.default_rng(self.seed)
        fold_of = np.full(n, -1, np.int64)
        idx = np.nonzero(keep > 0)[0]
        if self.stratify:
            classes = np.unique(y[idx])
            for c in classes:
                rows = idx[y[idx] == c]
                rows = rng.permutation(rows)
                fold_of[rows] = np.arange(len(rows)) % k
        else:
            rows = rng.permutation(idx)
            fold_of[rows] = np.arange(len(rows)) % k
        return fold_of


class CrossValidation(ValidatorBase):
    """k-fold CV (OpCrossValidation.scala:41-118); folds stratified by class for
    classification problems."""

    validation_type = "CrossValidation"

    def __init__(self, num_folds: int = 3, seed: int = 42, stratify: bool = True):
        super().__init__(seed=seed, stratify=stratify)
        if num_folds < 2:
            raise ValueError("num_folds must be >= 2")
        self.num_folds = num_folds

    def fold_masks(self, y, keep):
        fold_of = self._assign_folds(y, keep, self.num_folds)
        return np.stack([(fold_of == k).astype(np.float32)
                         for k in range(self.num_folds)])


class TrainValidationSplit(ValidatorBase):
    """Single stratified split (OpTrainValidationSplit.scala:34)."""

    validation_type = "TrainValidationSplit"

    def __init__(self, train_ratio: float = 0.75, seed: int = 42, stratify: bool = True):
        super().__init__(seed=seed, stratify=stratify)
        if not 0.0 < train_ratio < 1.0:
            raise ValueError("train_ratio must be in (0, 1)")
        self.train_ratio = train_ratio

    def fold_masks(self, y, keep):
        n = len(y)
        rng = np.random.default_rng(self.seed)
        mask = np.zeros(n, np.float32)
        idx = np.nonzero(keep > 0)[0]
        val_frac = 1.0 - self.train_ratio
        if self.stratify:
            for c in np.unique(y[idx]):
                rows = rng.permutation(idx[y[idx] == c])
                mask[rows[: int(round(len(rows) * val_frac))]] = 1.0
        else:
            rows = rng.permutation(idx)
            mask[rows[: int(round(len(rows) * val_frac))]] = 1.0
        return mask[None, :]


def _group_grid(template, grid: Sequence[dict]):
    """Split a grid by its static (non-vmappable) part. -> list of
    (static_params dict, vmap_stacks dict[name, np.ndarray [G]], points list[dict])."""
    vmappable = set(template.vmap_params)
    groups: dict[tuple, dict] = {}
    for point in grid or [{}]:
        static = {k: v for k, v in point.items() if k not in vmappable}
        key = tuple(sorted(static.items()))
        g = groups.setdefault(key, {"static": static, "vmap": [], "points": []})
        g["vmap"].append({k: v for k, v in point.items() if k in vmappable})
        g["points"].append(point)
    out = []
    for g in groups.values():
        names = sorted({k for d in g["vmap"] for k in d})
        stacks = {
            name: np.asarray(
                [d.get(name, template.params.get(name, 0.0)) for d in g["vmap"]],
                np.float32,
            )
            for name in names
        }
        out.append((g["static"], stacks, g["points"]))
    return out


#: jitted folds x grid search programs, keyed by (family, static params, metric).
#: Without this cache every selector fit would rebuild the vmap closures and re-trace,
#: paying tracing + dispatch on each AutoML search; with it, repeat searches on the
#: same shapes are pure device compute (the bench.py steady state).
_SEARCH_PROGRAM_CACHE: dict = {}
_SEARCH_PROGRAM_LOCK = threading.Lock()


@jax.jit
def _fold_weights(tw, vm, keepd):
    """(train_weights [N], val_masks [K,N], keep [N]) -> per-fold train/val
    weight grids, as one program."""
    return tw[None, :] * (1.0 - vm), keepd[None, :] * vm


@jax.jit
def _concat_flat(arrays):
    """Flatten+concatenate unit results in ONE program (the fused-fetch path);
    eager ravel/concat would dispatch per array."""
    return jnp.concatenate([jnp.ravel(a) for a in arrays])


def _hashable(v):
    """Canonicalize a static param value for the cache key (lists -> tuples, e.g.
    MLP hidden-layer sizes)."""
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


def _search_program(template, static_items: tuple, vmap_names: tuple,
                    problem_type: str, metric: str, num_classes: int,
                    per_fold_X: bool = False):
    key = (type(template), tuple((k, _hashable(v)) for k, v in static_items),
           vmap_names, problem_type, metric, num_classes, per_fold_X)
    fn = _SEARCH_PROGRAM_CACHE.get(key)
    if fn is not None:
        return fn
    with _SEARCH_PROGRAM_LOCK:  # parallel-compile threads share one fn per key
        fn = _SEARCH_PROGRAM_CACHE.get(key)
        if fn is not None:
            return fn
        return _build_search_program(key, template, static_items,
                                     problem_type, metric, num_classes,
                                     vmap_names, per_fold_X)


def _build_search_program(key, template, static_items, problem_type, metric,
                          num_classes, vmap_names, per_fold_X):
    static_kwargs = dict(static_items)
    metric_fn, _ = make_metric_fn(problem_type, metric, num_classes=num_classes)

    def fit_eval(X, y, train_w, val_w, hyper):
        params = template.fit_fn(X, y, sample_weight=train_w, **static_kwargs, **hyper)
        pred, raw, prob = template.predict_fn(params, X)
        return metric_fn(pred, raw, prob, y, val_w)

    # per_fold_X: workflow-level CV recomputes the matrix per fold, so X carries a
    # leading fold axis and rides the SAME fold vmap as the weights — all folds'
    # fits stay one batched program instead of K serial dispatches
    x_axis = 0 if per_fold_X else None
    if vmap_names:  # vmap over the stacked grid axis, then over folds
        inner = jax.vmap(fit_eval, in_axes=(None, None, None, None, 0))
        fn = jax.jit(jax.vmap(inner, in_axes=(x_axis, None, 0, 0, None)))
    else:
        fn = jax.jit(jax.vmap(
            lambda X, y, twk, vwk: fit_eval(X, y, twk, vwk, {}),
            in_axes=(x_axis, None, 0, 0),
        ))
    # exported-program cache: a warm process skips the ~5-20s python trace of
    # each search program, not just its XLA compile (utils/export_cache.py;
    # single-device runs only — mesh/test envs fall through to the jit)
    from ..utils.export_cache import ExportCachingProgram

    fn = ExportCachingProgram(fn, key_material=repr(key),
                              label=f"search:{type(template).__name__}",
                              lane="search")
    # threadlint: ok OP605 - _SEARCH_PROGRAM_LOCK is held by the only
    # caller (_search_program's double-checked miss path calls here with
    # the lock still held)
    _SEARCH_PROGRAM_CACHE[key] = fn
    return fn


def _host_unit_scores(u, X, y, train_weights, val_masks, keep,
                      problem_type, metric, num_classes, per_fold_X):
    """Fold x grid-point scores [K, G] for a host-lane template (host_fit=True):
    each point fits an external estimator on the fold's weighted train rows and
    scores validation rows with the SAME metric function as the device lane."""
    metric_fn, _ = make_metric_fn(problem_type, metric,
                                  num_classes=max(num_classes, 2))
    template = u["template"]
    Xh, yh = np.asarray(X, np.float32), np.asarray(y, np.float32)
    tw, vm = np.asarray(train_weights), np.asarray(val_masks)
    ftw = tw[None, :] * (1.0 - vm)              # [K, N] fold train weights
    fvw = np.asarray(keep)[None, :] * vm        # [K, N] fold val weights
    K = vm.shape[0]
    scores = np.zeros((K, u["n_points"]), np.float32)
    yd = jnp.asarray(yh)
    for gi, point in enumerate(u["points"]):
        for k in range(K):
            Xk = Xh[k] if per_fold_X else Xh
            pred, raw, prob = template.host_score(Xk, yh, ftw[k], **point)
            scores[k, gi] = float(metric_fn(
                jnp.asarray(pred), jnp.asarray(raw), jnp.asarray(prob),
                yd, jnp.asarray(fvw[k])))
    return scores


def evaluate_candidates(
    candidates,
    X,
    y,
    train_weights: np.ndarray,
    val_masks: np.ndarray,
    keep: np.ndarray,
    problem_type: str,
    metric: str,
    num_classes: int = 0,
    mesh=None,
    checkpoint=None,
    checkpoint_fold: Optional[int] = None,
) -> list[EvaluatedGridPoint]:
    """Validate every (family, grid-point) over every fold.

    candidates: list of (PredictorEstimator template, grid list[dict]).
    train_weights [N]: balancer/cutter weights applied when FITTING.
    val_masks [K, N]: fold validation indicators. keep [N]: cutter keep-mask applied
    when SCORING validation rows.
    mesh: optional jax.sharding.Mesh (data x model axes). Grid points shard over the
    model axis — each chip fits its slice of the hyperparameter grid (the Spark
    thread-pool model-parallelism, SURVEY §2.12, as a sharded device axis); rows
    shard over the data axis when they divide it evenly (fits' matmuls then psum
    partial products over ICI).
    checkpoint: optional SearchCheckpoint — each (family, grid-group) appends its
    results on completion and already-completed groups are skipped on resume
    (SURVEY §5.4 resumable selector loops); checkpoint_fold scopes group keys when
    the caller runs one fold at a time (workflow-level CV).
    """
    per_fold_X = np.ndim(X) == 3  # [K, N, D]: per-fold matrices (workflow-level CV)
    Xd = jnp.asarray(X, jnp.float32)
    yd = jnp.asarray(y, jnp.float32)
    tw = jnp.asarray(train_weights, jnp.float32)
    vm = jnp.asarray(val_masks, jnp.float32)
    keepd = jnp.asarray(keep, jnp.float32)
    # ONE dispatch for both [K, N] weight grids (eager broadcasts would be
    # 3-4 separate tiny programs — each a round trip on a tunneled device)
    fold_train_w, fold_val_w = _fold_weights(tw, vm, keepd)

    n_model = 1
    wide = False
    if mesh is not None:
        from ..mesh import DATA_AXIS, MODEL_AXIS, replicate, shard_batch, shard_wide
        from ..ops.linear import WIDE_D_THRESHOLD

        n_model = mesh.shape[MODEL_AXIS]
        n_data = mesh.shape[DATA_AXIS]
        row_dim = 1 if per_fold_X else 0
        rows_ok = Xd.shape[row_dim] % n_data == 0
        # wide matrices claim the model axis for the FEATURE dimension instead of
        # the grid: partial dot-products psum over it (SURVEY §5.7); the grid then
        # rides replicated vmap (compute is matmul-dominated in this regime)
        wide = (not per_fold_X and n_model > 1
                and Xd.shape[1] >= WIDE_D_THRESHOLD
                and Xd.shape[1] % n_model == 0)
        # rows shard over the data axis ONLY when the grid axis is not also
        # sharded: combining MODEL_AXIS grid sharding with DATA_AXIS row
        # sharding in the folds x grid program miscompiles under the XLA SPMD
        # partitioner at some shape coincidences (observed: 4x2 mesh, 2 folds,
        # sort-based AuROC/AuPR return large negative garbage while 2x4 and
        # 4 folds are exact — jax 0.4.37 CPU). Data-parallel meshes (the
        # auto-mesh default, n_model == 1) keep full row sharding; dual-axis
        # meshes buy grid parallelism and replicate rows. Regression test:
        # tests/test_multichip.py::test_dual_axis_search_parity.
        row_shard = rows_ok and (wide or n_model == 1)
        if wide:
            Xd = shard_wide(mesh, Xd) if rows_ok else jax.device_put(
                Xd, jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec(None, MODEL_AXIS)))
            n_model = 1  # grid axis no longer sharded
        elif row_shard:
            Xd = shard_batch(mesh, Xd, batch_dim=row_dim)
        else:
            Xd = replicate(mesh, Xd)
        if row_shard:
            yd = shard_batch(mesh, yd)
            fold_train_w = shard_batch(mesh, fold_train_w, batch_dim=1)
            fold_val_w = shard_batch(mesh, fold_val_w, batch_dim=1)
        else:
            yd = replicate(mesh, yd)
            fold_train_w = replicate(mesh, fold_train_w)
            fold_val_w = replicate(mesh, fold_val_w)

    # collect one work unit per (family, grid-group); checkpoint-complete groups
    # replay their stored results instead of running
    units: list[dict] = []
    for ci, (template, grid) in enumerate(candidates):
        name = type(template).__name__
        for static, stacks, points in _group_grid(template, grid):
            static_kwargs = {**template.fit_kwargs(), **static}
            for k in stacks:
                static_kwargs.pop(k, None)
            ck_key = None
            if checkpoint is not None:
                from .checkpoint import group_key

                ck_key = group_key(ci, static_kwargs.items(), points,
                                   fold=checkpoint_fold)
                done = checkpoint.get(ck_key)
                if done is not None:
                    units.append({"cached": done})
                    continue
            hyper = None
            n_points = len(points)
            if stacks:
                hyper = {k: np.asarray(v, np.float32) for k, v in stacks.items()}
                if mesh is not None and wide:
                    from ..mesh import replicate

                    hyper = {k: replicate(mesh, v) for k, v in hyper.items()}
                elif mesh is not None:
                    from ..mesh import shard_grid

                    pad = (-n_points) % n_model  # even shards: repeat the last point
                    hyper = {
                        k: shard_grid(mesh, np.concatenate([v, np.repeat(v[-1:], pad)]))
                        for k, v in hyper.items()
                    }
                else:
                    hyper = {k: jnp.asarray(v) for k, v in hyper.items()}
            units.append({"ci": ci, "name": name, "points": points,
                          "template": template,
                          "static_items": tuple(sorted(static_kwargs.items())),
                          "vmap_names": tuple(sorted(stacks)),
                          "hyper": hyper, "ck_key": ck_key, "n_points": n_points})

    def run_unit(u):
        """Dispatch one group's program; returns the DEVICE [K, G_padded] array.
        No host fetch here: over a tunneled device each fetch is a ~90ms round
        trip, so all units' results are fetched in ONE transfer afterwards."""
        if getattr(u["template"], "host_fit", False):
            # host lane: wrapped external estimators (stages/model/wrapper.py)
            # fit on the host, fold by fold — the reference runs its wrapped
            # Spark estimators on the JVM next to its own stages the same way
            return jnp.asarray(_host_unit_scores(
                u, X, y, train_weights, val_masks, keep,
                problem_type, metric, num_classes, per_fold_X))
        program = _search_program(
            u["template"], u["static_items"], u["vmap_names"],
            problem_type, metric, num_classes, per_fold_X=per_fold_X,
        )
        if mesh is not None:
            from ..mesh import record_sharded_dispatch

            record_sharded_dispatch()
        if u["hyper"] is not None:
            return program(Xd, yd, fold_train_w, fold_val_w, u["hyper"])
        return program(Xd, yd, fold_train_w, fold_val_w)[:, None]

    def trim(u, scores_padded: np.ndarray) -> np.ndarray:
        return scores_padded[:, :u["n_points"]] if u["hyper"] is not None \
            else scores_padded

    def finish(u, scores) -> None:
        """Record one completed group (and checkpoint it IMMEDIATELY — a kill while
        other groups still run must not lose this one)."""
        group_results = [
            EvaluatedGridPoint(
                model_name=u["name"],
                grid_point=dict(point),
                metric_name=metric,
                metric_values=[float(s) for s in scores[:, gi]],
                candidate_index=u["ci"],
            )
            for gi, point in enumerate(u["points"])
        ]
        if checkpoint is not None:
            checkpoint.put(u["ck_key"], [
                {**r.to_json(), "candidate_index": r.candidate_index}
                for r in group_results
            ])
        u["group_results"] = group_results

    live = [u for u in units if "cached" not in u]
    # distinct groups have DISTINCT compiled programs; running their first calls on
    # threads overlaps the XLA compilations (compile releases the GIL; device
    # execution serializes on the runtime regardless). Measured ~1.7x on two cold
    # tree programs. TT_PARALLEL_COMPILE=0 forces the serial path.
    use_threads = (len(live) > 1
                   and os.environ.get("TT_PARALLEL_COMPILE", "1") != "0")
    if checkpoint is None:
        # latency path: dispatch every unit's program (async), then ONE fused
        # host fetch for all results — each per-unit np.asarray would pay a
        # ~90ms tunnel round trip, and searches have 3-8 units
        if use_threads:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(min(4, len(live))) as ex:
                devs = list(ex.map(run_unit, live))
        else:
            devs = [run_unit(u) for u in live]
        if devs:
            shapes = [d.shape for d in devs]
            flat = np.asarray(_concat_flat(devs))
            off = 0
            for u, shp in zip(live, shapes):
                size = int(np.prod(shp))
                finish(u, trim(u, flat[off:off + size].reshape(shp)))
                off += size
    elif use_threads:
        from concurrent.futures import ThreadPoolExecutor, as_completed

        errors: list[BaseException] = []
        with ThreadPoolExecutor(min(4, len(live))) as ex:
            by_future = {ex.submit(run_unit, u): u for u in live}
            # completion order: each group checkpoints the moment it finishes,
            # regardless of how long earlier-submitted groups still compile;
            # drain EVERYTHING so completed groups survive any failure — including
            # an interrupt raised while WAITING in as_completed (not just inside
            # fut.result()): checkpoint whatever already finished before re-raising
            try:
                for fut in as_completed(by_future):
                    try:
                        u = by_future[fut]
                        finish(u, trim(u, np.asarray(fut.result())))
                    except BaseException as e:  # noqa: BLE001
                        errors.append(e)
            except BaseException as e:  # noqa: BLE001
                for fut in by_future:  # queued-not-started units exit immediately
                    fut.cancel()
                errors.append(e)
        if errors:
            # shutdown already waited for in-flight units; checkpoint any that
            # completed during the wait (their compute is paid — a resume must
            # not re-run them)
            for fut, u in by_future.items():
                if fut.done() and not fut.cancelled() and "group_results" not in u:
                    try:
                        finish(u, trim(u, np.asarray(fut.result())))
                    except (KeyboardInterrupt, SystemExit) as ie:
                        errors.append(ie)  # an interrupt during drain still outranks
                    except BaseException:  # noqa: BLE001
                        pass  # this unit already failed; its error is in `errors`
        if errors:
            # interrupts outrank model errors: never swallow a Ctrl-C behind one
            for e in errors:
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    raise e
            raise errors[0]
    else:
        for u in live:
            finish(u, trim(u, np.asarray(run_unit(u))))

    results: list[EvaluatedGridPoint] = []
    for u in units:  # original order: results are deterministic either way
        if "cached" in u:
            for rec in u["cached"]:
                results.append(EvaluatedGridPoint(
                    model_name=rec["model_name"],
                    grid_point=rec["grid_point"],
                    metric_name=rec["metric_name"],
                    metric_values=list(rec["metric_values"]),
                    candidate_index=rec["candidate_index"],
                ))
            continue
        results.extend(u["group_results"])
    return results
