"""ModelSelector: automatic model selection as an estimator stage.

Analog of ModelSelector/ModelSelectorFactory and the three problem-type factories
(core/.../impl/selector/ModelSelector.scala:73-135, BinaryClassificationModelSelector.
scala:52-128, MultiClassificationModelSelector.scala:59-61, RegressionModelSelector.
scala:59-61). `fit` = reserve holdout -> prepare train (balance/cut) -> validate every
(family, grid-point) over folds via the vmapped validator -> refit the winner on the
full prepared train split -> report train + holdout metrics with the exact host
evaluators. The search itself is device-batched (see validator.py).
"""
from __future__ import annotations

import json
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ..evaluators.evaluators import Evaluators
from ..stages.base import register_stage
from ..stages.model.base import PredictorEstimator
from .grids import ParamGridBuilder
from .splitters import DataBalancer, DataCutter, DataSplitter, SplitterSummary
from .validator import (
    CrossValidation,
    TrainValidationSplit,
    ValidatorBase,
    evaluate_candidates,
)

#: reference default regularization grid (DefaultSelectorParams.scala: Regularization)
REGULARIZATION_GRID = [0.001, 0.01, 0.1, 0.2]

_VALIDATOR_CLASSES = {c.__name__: c for c in (CrossValidation, TrainValidationSplit)}
_SPLITTER_CLASSES = {c.__name__: c for c in (DataSplitter, DataBalancer, DataCutter)}


def _ctor_args(obj) -> dict:
    """JSON args reconstructing `obj` via type(obj)(**args): the instance attributes
    named by the ctor's keyword parameters (validators/splitters store every ctor
    arg under its own name). A ctor parameter with NO same-named attribute raises —
    silently substituting the ctor default would reload a different search."""
    import inspect

    from ..stages.base import _jsonify

    sig = inspect.signature(type(obj).__init__)
    out = {}
    for name in sig.parameters:
        if name == "self":
            continue
        if not hasattr(obj, name):
            raise TypeError(
                f"{type(obj).__name__} stores ctor arg {name!r} under a different "
                "attribute name — it cannot be serialized faithfully; store it "
                f"as self.{name}")
        out[name] = _jsonify(getattr(obj, name))
    return out


def _restore_by_ctor(classes: dict, spec: dict):
    if spec["class"] not in classes:
        raise ValueError(f"unknown class {spec['class']!r}; expected one of "
                         f"{sorted(classes)}")
    return classes[spec["class"]](**spec["args"])


@dataclass
class ModelSelectorSummary:
    """What the selector saw and decided (analog of ModelSelectorSummary.scala)."""

    validation_type: str
    problem_type: str
    metric_name: str
    larger_is_better: bool
    best_model_name: str = ""
    best_params: dict = field(default_factory=dict)
    validation_results: list = field(default_factory=list)  # [EvaluatedGridPoint]
    splitter_summary: Optional[SplitterSummary] = None
    train_metrics: Optional[object] = None
    holdout_metrics: Optional[object] = None
    n_train: int = 0
    n_holdout: int = 0
    models_evaluated: int = 0  # grid points x folds (the bench.py throughput unit)

    def to_json(self) -> dict:
        return {
            "validation_type": self.validation_type,
            "problem_type": self.problem_type,
            "metric_name": self.metric_name,
            "larger_is_better": self.larger_is_better,
            "best_model_name": self.best_model_name,
            "best_params": self.best_params,
            "validation_results": [r.to_json() for r in self.validation_results],
            "splitter_summary": (self.splitter_summary.to_json()
                                 if self.splitter_summary else None),
            "train_metrics": (self.train_metrics.to_json()
                              if self.train_metrics is not None else None),
            "holdout_metrics": (self.holdout_metrics.to_json()
                                if self.holdout_metrics is not None else None),
            "n_train": self.n_train,
            "n_holdout": self.n_holdout,
            "models_evaluated": self.models_evaluated,
        }

    def pretty(self) -> str:
        from ..utils.table import pretty_table

        lines = [f"Selected model: {self.best_model_name} {self.best_params}"]
        ranked = sorted(self.validation_results, key=lambda r: r.metric_mean,
                        reverse=self.larger_is_better)
        lines.append(pretty_table(
            [[r.model_name, str(r.grid_point), r.metric_mean,
              " ".join(f"{v:.4f}" for v in r.metric_values)]
             for r in ranked[:10]],
            headers=["model", "grid point", f"mean {self.metric_name}", "folds"],
            title=f"Validation ({self.validation_type}, metric={self.metric_name}):",
        ))
        if self.holdout_metrics is not None:
            hj = self.holdout_metrics.to_json()
            scalar = [(k, v) for k, v in hj.items() if isinstance(v, (int, float))]
            other = [k for k, v in hj.items()
                     if not isinstance(v, (int, float)) and v]
            lines.append(pretty_table(
                [[k, v] for k, v in scalar], headers=["holdout metric", "value"]))
            if other:
                lines.append(f"(non-scalar holdout metrics in to_json(): "
                             f"{', '.join(other)})")
        return "\n".join(lines)


@register_stage
class ModelSelector(PredictorEstimator):
    """Estimator stage `(response, OPVector) -> Prediction` that picks and fits the
    best model family x hyperparameters (ModelSelector.scala:73-135)."""

    operation_name = "modelSelector"

    def __init__(self, problem_type: str = "binary", metric: Optional[str] = None,
                 models: Optional[Sequence] = None,
                 validator: Optional[ValidatorBase] = None,
                 splitter: Optional[DataSplitter] = None, seed: int = 42,
                 mesh=None):
        super().__init__(problem_type=problem_type, seed=seed)
        if problem_type not in ("binary", "multiclass", "regression"):
            raise ValueError(f"unknown problem_type {problem_type!r}")
        self.problem_type = problem_type
        self.metric = metric or {"binary": "AuPR", "multiclass": "F1",
                                 "regression": "RootMeanSquaredError"}[problem_type]
        self.models = list(models) if models is not None else default_models(problem_type)
        self.validator = validator or CrossValidation(num_folds=3, seed=seed,
                                                      stratify=problem_type != "regression")
        self.splitter = splitter or default_splitter(problem_type, seed)
        self.seed = seed
        #: optional device mesh: grid points shard over its model axis, rows over
        #: its data axis (set directly or via ctor; never serialized)
        self.mesh = mesh
        #: optional search-checkpoint path (SURVEY §5.4): completed grid groups are
        #: persisted during fit and skipped on resume after a crash/kill
        self.checkpoint_path: Optional[str] = None
        self.summary_: Optional[ModelSelectorSummary] = None

    def with_checkpoint(self, path: str) -> "ModelSelector":
        """Enable mid-search checkpoint/resume: fit() appends each completed
        (family, grid-group) result to `path` and, on a later fit over the same
        data/config, skips those groups. The file is removed when fit completes."""
        self.checkpoint_path = path
        return self

    def with_warm_start(self, source) -> "ModelSelector":
        """Warm-start the WINNER REFIT from `source` (a fitted
        PredictionModel — e.g. the current champion's prediction stage — or
        a raw params payload): when the search's winning family supports
        warm starts AND matches the source's family/shape, the refit's
        optimizer starts from those parameters instead of cold (the
        autopilot's drift-retrain contract). The SEARCH itself is untouched
        — vmapped fold x grid programs stay cold and replicated, so
        validation scores never depend on the previous champion. Mismatches
        silently cold-fit. Runtime wiring: never serialized."""
        self._warm_source = source
        return self

    def config_fingerprint(self):
        """The selector's search configuration lives in attributes, not ctor params;
        warm-start reuse must see all of it (models/grids/metric/validator/splitter)."""
        from ..stages.base import _jsonify

        return {
            **_jsonify(self.params),
            "metric": self.metric,
            "models": [[type(t).__name__, _jsonify(t.params), _jsonify(list(grid))]
                       for t, grid in self.models],
            "validator": [type(self.validator).__name__,
                          _jsonify(vars(self.validator))],
            "splitter": [type(self.splitter).__name__, _jsonify(vars(self.splitter))],
        }

    # --- unfitted serialization (FeatureJsonHelper-grade graph round trip) ----------
    def to_json(self) -> dict:
        """Ctor params + the search configuration (metric/models/validator/splitter),
        so an UNFITTED selector survives graph_to_json -> graph_from_json with its
        full search intact (graph/json_helper.py). The mesh and checkpoint_path are
        runtime wiring and are deliberately not serialized."""
        from ..stages.base import _jsonify

        data = super().to_json()
        data["search"] = {
            "metric": self.metric,
            "models": [
                {"class": type(t).__name__, "params": _jsonify(t.params),
                 "grid": _jsonify(list(grid))}
                for t, grid in self.models
            ],
            "validator": {"class": type(self.validator).__name__,
                          "args": _ctor_args(self.validator)},
            "splitter": {"class": type(self.splitter).__name__,
                         "args": _ctor_args(self.splitter)},
        }
        return data

    @classmethod
    def from_json(cls, data: dict) -> "ModelSelector":
        from ..stages.base import STAGE_REGISTRY

        kwargs = dict(data["params"])
        search = data.get("search")
        if search:
            kwargs["metric"] = search["metric"]
            for m in search["models"]:
                if m["class"] not in STAGE_REGISTRY:
                    raise ValueError(
                        f"unknown model class {m['class']!r}; not in the stage "
                        "registry of this build")
            kwargs["models"] = [
                (STAGE_REGISTRY[m["class"]](**m["params"]), list(m["grid"]))
                for m in search["models"]
            ]
            kwargs["validator"] = _restore_by_ctor(
                _VALIDATOR_CLASSES, search["validator"])
            kwargs["splitter"] = _restore_by_ctor(
                _SPLITTER_CLASSES, search["splitter"])
        stage = cls(**kwargs)
        stage.uid = data["uid"]
        return stage

    def resource_profile(self, *, width, n_rows, mesh_shape) -> dict:
        """`op explain` hook (key contract in analyze/shard_model.py): the
        search vmaps each family's grid over the model axis — grid-padded
        with CLONE points to divide it — and vmapped fits run REPLICATED
        (resolve_shard_optimizer's batched check), so the per-point state
        multiplies by the padded point count and no collective traffic is
        modeled. The winner refit is a solo fit priced like the standalone
        stage; the search phase reported here dominates."""
        n_data, n_model = int(mesh_shape[0]), int(mesh_shape[1])
        peak = {"params": 0, "opt": 0, "aux": 0, "points": 1, "pad": 0,
                "name": None}
        notes = []
        for template, grid in self.models:
            points = max(1, len(grid) if grid else 1)
            pad = (-points) % n_model if n_model > 1 else 0
            prof = {}
            hook = getattr(template, "resource_profile", None)
            if callable(hook):
                try:
                    # (1, 1): vmapped fits cannot shard_map — replicated
                    prof = hook(width=width, n_rows=n_rows,
                                mesh_shape=(1, 1)) or {}
                except (TypeError, ValueError, KeyError):
                    prof = {}
            elif width:
                # linear families: f32 weights + bias per point
                prof = {"params_bytes": 4 * (int(width) + 1)}
            per = (int(prof.get("params_bytes", 0) or 0)
                   + int(prof.get("opt_state_bytes", 0) or 0))
            total = (points + pad) * per + int(prof.get("aux_bytes", 0) or 0)
            if total >= (peak["points"] + peak["pad"]) * (
                    peak["params"] + peak["opt"]) + peak["aux"]:
                peak = {"params": int(prof.get("params_bytes", 0) or 0),
                        "opt": int(prof.get("opt_state_bytes", 0) or 0),
                        "aux": int(prof.get("aux_bytes", 0) or 0),
                        "points": points, "pad": pad,
                        "name": type(template).__name__}
            if pad:
                notes.append(f"{type(template).__name__}: {points} grid "
                             f"points pad +{pad} clones to divide "
                             f"model={n_model}")
        points_all = peak["points"] + peak["pad"]
        if peak["name"]:
            notes.append(f"peak family {peak['name']}: x{points_all} vmapped "
                         "grid points, state replicated per point")
        return {
            "params_bytes": points_all * peak["params"],
            "opt_state_bytes": points_all * peak["opt"],
            "aux_bytes": peak["aux"],
            "activation_bytes": (int(n_rows) * int(width) * 4
                                 if (n_rows and width) else 0),
            "rows_per_device": int(n_rows) if n_rows else None,
            "rows_sharded": False,
            "grid_points": peak["points"],
            "grid_pad": peak["pad"],
            "notes": notes,
        }

    # the selector's own fit is the whole search; fit_fn/predict_fn are the winner's
    def fit_columns(self, cols):
        import jax
        import jax.numpy as jnp

        y_full, X_full = self.label_and_matrix(cols)
        y_np = np.asarray(y_full, np.float32)  # split/fold logic is host numpy

        train_idx, holdout_idx = self.splitter.split_indices(y_np)
        # the matrix stays DEVICE-resident end to end (search -> refit ->
        # metrics): row slices are device gathers, and the host copy is fetched
        # only where actually needed (checkpoint fingerprint, per-fold CV path)
        X_tr = jnp.take(X_full, jnp.asarray(train_idx), axis=0)
        y_tr = y_np[train_idx]
        weights, label_map, split_summary = self.splitter.prepare(y_tr)

        num_classes = 0
        y_used = y_tr
        models = list(self.models)
        if self.problem_type == "multiclass":
            if label_map is None:
                label_map = {float(c): i for i, c in enumerate(np.unique(y_tr))}
            num_classes = len(label_map)
            y_used = np.asarray([label_map.get(float(v), 0) for v in y_tr], np.float32)
            models = [(t.with_params(num_classes=num_classes)
                       if "num_classes" in t.params else t, g) for t, g in models]

        keep = (weights > 0).astype(np.float32)
        val_masks = self.validator.fold_masks(y_used, keep)
        from .. import obs

        fold_matrix_fn = getattr(self, "_in_fold_matrix_fn", None)
        ckpt = None
        if self.checkpoint_path:
            from .checkpoint import SearchCheckpoint, search_fingerprint

            fp = search_fingerprint(np.asarray(X_tr, np.float32), y_used,
                                    weights, val_masks, keep,
                                    self.problem_type, self.metric, models)
            ckpt = SearchCheckpoint(self.checkpoint_path, fp)
        with obs.span("selector:search"):
            if fold_matrix_fn is None:
                results = evaluate_candidates(
                    models, X_tr, y_used, weights, val_masks, keep,
                    self.problem_type, self.metric, num_classes=num_classes,
                    mesh=self.mesh, checkpoint=ckpt,
                )
            else:
                # workflow-level CV (cutDAG): label-touching upstream estimators are
                # refit per fold on that fold's training rows, the matrix recomputed,
                # and candidates validated against THAT fold only — leakage-safe.
                # The K per-fold matrices stack into one [K, N, D] batch so the
                # whole search stays ONE vmapped program over (folds x grid) rather
                # than K serial dispatches; refits themselves replay only the
                # label-tainted cone (unaffected columns are reused from the main
                # pass), so the CV path costs the refit cone, not K full plans.
                fold_mats = []
                for k in range(val_masks.shape[0]):
                    fit_local = (val_masks[k] == 0) & (keep > 0)
                    global_rows = train_idx[np.nonzero(fit_local)[0]]
                    col = fold_matrix_fn(np.asarray(global_rows))
                    fold_mats.append(np.asarray(col.values, np.float32)[train_idx])
                widths = {m.shape[1] for m in fold_mats}
                if len(widths) == 1:  # width-stable (pad-to-bucket): batched path
                    results = evaluate_candidates(
                        models, np.stack(fold_mats), y_used, weights, val_masks,
                        keep, self.problem_type, self.metric,
                        num_classes=num_classes, mesh=self.mesh, checkpoint=ckpt,
                    )
                else:  # per-fold widths diverged (bucketing off): serial fallback
                    results = None
                    for k, X_k in enumerate(fold_mats):
                        fold_results = evaluate_candidates(
                            models, X_k, y_used, weights, val_masks[k:k + 1], keep,
                            self.problem_type, self.metric,
                            num_classes=num_classes, mesh=self.mesh,
                            checkpoint=ckpt, checkpoint_fold=k,
                        )
                        if results is None:
                            results = fold_results
                        else:
                            for agg, r in zip(results, fold_results):
                                agg.metric_values.extend(r.metric_values)
        from .tuning_metrics import make_metric_fn

        _, larger = make_metric_fn(self.problem_type, self.metric,
                                   num_classes=max(num_classes, 2))
        best = (max if larger else min)(results, key=lambda r: r.metric_mean)
        template = models[best.candidate_index][0]
        best_est = template.with_params(**best.grid_point)
        # the refit instance carries the selector's mesh so mesh-capable
        # families (MeshAwareFit: sharded-optimizer MLP, model-axis tree
        # histograms) refit SHARDED via their fit_kwargs threading — the
        # search templates stay mesh-free (replicated vmapped programs)
        best_est.mesh = self.mesh

        # warm-start kwargs resolve against the WINNER: if the autopilot's
        # champion is an LR model and the fresh search picks a forest, the
        # mismatch silently cold-fits (warm_fit_kwargs -> {})
        warm_source = getattr(self, "_warm_source", None)
        warm_kw = {}
        if warm_source is not None:
            best_est._warm_source = warm_source
            warm_kw = best_est.warm_fit_kwargs(int(X_tr.shape[1]))

        host_lane = getattr(best_est, "host_fit", False)
        with obs.span("selector:refit"):
            if host_lane:
                # wrapped external estimator (stages/model/wrapper.py): fit on
                # host; `params` is the fitted external object
                params = best_est.host_fit_full(
                    np.asarray(X_tr, np.float32), np.asarray(y_used, np.float32),
                    np.asarray(weights))
            else:
                X_fit, y_fit = X_tr, jnp.asarray(y_used)
                w_fit = jnp.asarray(weights)
                if self.mesh is not None:
                    # winner refit over the mesh: rows over the data axis when
                    # they divide it (the fit's matmuls psum partial products
                    # over ICI), features over the model axis when wide —
                    # same placement policy as the search itself
                    from ..mesh import (
                        DATA_AXIS,
                        record_sharded_dispatch,
                        replicate,
                        shard_batch,
                        shard_for_training,
                    )

                    X_fit, y_fit = shard_for_training(self.mesh, X_fit, y_fit)
                    if X_fit.shape[0] % self.mesh.shape[DATA_AXIS] == 0:
                        w_fit = shard_batch(self.mesh, w_fit)
                    else:
                        w_fit = replicate(self.mesh, w_fit)
                    record_sharded_dispatch()
                # no block_until_ready: the refit output flows straight into the
                # fused predict+metrics programs — forcing it here would add one
                # ~90ms tunnel round trip purely for phase attribution
                if self.mesh is None:
                    # single-device refit rides the shared training AOT store:
                    # static fit hyperparams fold into the blob key, warm-start
                    # arrays ride as operands; any ineligible kwarg or store
                    # failure falls back to the plain fit_fn call
                    from ..stages.base import _jsonify
                    from ..utils.export_cache import exec_cached_call

                    try:
                        pcfg = json.dumps(_jsonify(best_est.params),
                                          sort_keys=True)
                    except TypeError:
                        pcfg = repr(sorted(best_est.params.items(),
                                           key=lambda kv: kv[0]))
                    params = exec_cached_call(
                        best_est.fit_fn,
                        f"refit|{best_est.__class__.__name__}|{pcfg}",
                        args=(X_fit, y_fit),
                        kwargs={"sample_weight": w_fit,
                                **best_est.fit_kwargs(), **warm_kw},
                        label=f"refit:{best_est.__class__.__name__}",
                        lane="refit")
                else:
                    params = best_est.fit_fn(X_fit, y_fit,
                                             sample_weight=w_fit,
                                             **best_est.fit_kwargs(),
                                             **warm_kw)

        summary = ModelSelectorSummary(
            validation_type=self.validator.validation_type,
            problem_type=self.problem_type,
            metric_name=self.metric,
            larger_is_better=larger,
            best_model_name=best.model_name,
            best_params=dict(best.grid_point),
            validation_results=results,
            splitter_summary=split_summary,
            n_train=len(train_idx),
            n_holdout=len(holdout_idx),
            models_evaluated=len(results) * val_masks.shape[0],
        )
        # metrics run as ONE fused predict+metrics program per pass (one
        # dispatch + one fetch each — each extra device call costs a ~90ms
        # round trip on a tunneled device); the metrics objects are then
        # assembled on host by the exact evaluators
        ev = _metrics_evaluator(self.problem_type, num_classes)
        if host_lane:
            def prog(p, Xs, ys, _ev=ev):
                pred, raw, prob = best_est.host_predict(p, np.asarray(Xs))
                args = [jnp.asarray(pred), jnp.asarray(raw), jnp.asarray(prob),
                        jnp.asarray(ys, jnp.float32)]
                if self.problem_type == "multiclass":
                    args.append(num_classes)
                return _ev.device_metrics(*args)
        else:
            prog = _metrics_program(best_est, ev, self.problem_type, num_classes)
        # train metrics over kept rows only — cutter-dropped rows carry weight 0 and
        # were remapped to class 0, so including them would corrupt the report.
        # BOTH metric programs dispatch async and their results come back with
        # the fitted params in ONE device_get: the former three serial fetches
        # (train, holdout, make_model's host_params) each paid a ~90ms round
        # trip on a tunneled device — ~0.3s of every small-problem train.
        with obs.span("selector:train_metrics"):
            kept_rows = weights > 0
            if kept_rows.all():
                Xk, yk = X_tr, y_used
            else:
                ki = jnp.asarray(np.nonzero(kept_rows)[0])
                Xk, yk = jnp.take(X_tr, ki, axis=0), y_used[kept_rows]
            train_dev = prog(params, Xk, jnp.asarray(yk, jnp.float32))
        hold_dev = None
        if len(holdout_idx):
            with obs.span("selector:holdout_metrics"):
                y_h = y_np[holdout_idx]
                h_idx = np.asarray(holdout_idx)
                if label_map is not None:
                    keep_h = np.asarray([float(v) in label_map for v in y_h])
                    h_idx = h_idx[keep_h]
                    y_h = np.asarray([label_map.get(float(v), 0)
                                      for v in y_h[keep_h]], np.float32)
                X_h = jnp.take(X_full, jnp.asarray(h_idx), axis=0)
                hold_dev = prog(params, X_h, jnp.asarray(y_h, jnp.float32))
        with obs.span("selector:metrics_fetch"):
            train_host, hold_host, params_host = jax.device_get(
                (train_dev, hold_dev, params))
        summary.train_metrics = ev.assemble(train_host)
        if hold_host is not None:
            summary.holdout_metrics = ev.assemble(hold_host)
        # built from the ALREADY-FETCHED params pytree (numpy leaves):
        # make_model's host_params device_get passes host arrays through free
        model = best_est.make_model(params_host)
        if ckpt is not None and not getattr(self, "_defer_checkpoint_complete", False):
            # fit finished: next fit starts a fresh search. A checkpointed
            # Workflow.train defers this removal to TRAIN end — a kill during a
            # LATER phase must still be able to resume without redoing the search
            ckpt.complete()
        self.summary_ = summary
        model.selector_summary = summary
        return model


#: fused predict+metrics jit programs, keyed by (model family, ctor params,
#: problem type, num_classes) — see _metrics_program. Default-config evaluators
#: only (the selector builds its own); custom-threshold evaluators go through
#: evaluate_all on a scored table instead. LRU-bounded like _FUSED_RUN_CACHE:
#: each entry pins a compiled executable, and a long-lived service whose
#: searches win ever-different grid points must evict (ADVICE r03).
_METRICS_PROGRAM_CACHE: OrderedDict = OrderedDict()
_METRICS_PROGRAM_CACHE_MAX = 64
_METRICS_PROGRAM_LOCK = threading.Lock()
_EVALUATOR_CACHE: dict = {}


def _metrics_evaluator(problem_type: str, num_classes: int):
    key = (problem_type, num_classes)
    ev = _EVALUATOR_CACHE.get(key)
    if ev is None:
        with _METRICS_PROGRAM_LOCK:
            ev = _EVALUATOR_CACHE.get(key)
            if ev is None:
                ev = _EVALUATOR_CACHE[key] = {
                    "binary": lambda: Evaluators.binary_classification(
                        "label", "pred"),
                    "multiclass": lambda: Evaluators.multi_classification(
                        "label", "pred", num_classes=num_classes),
                    "regression": lambda: Evaluators.regression(
                        "label", "pred"),
                }[problem_type]()
    return ev


def _metrics_program(template, evaluator, problem_type: str, num_classes: int):
    """ONE jitted program: winner's predict_fn -> evaluator.device_metrics.
    Params ride as ARGUMENTS (not baked constants), so the program caches
    across trains of the same family/shapes; the caller pays one dispatch and
    one fetch per metrics pass. The key includes the template's STATIC ctor
    params: predict_fn can be instance-BOUND and branch on them (NaiveBayes
    model_type, GLM family), so two configs of one class must not share a
    traced program. vmap_params are excluded: the search already runs every
    grid point of a static group through ONE vmapped program, so they cannot
    change program structure by contract — and keying on them made the winner
    miss this cache whenever it was not the grid point op_warmup solo-fitted
    (points[0] per group), re-paying the fused-metrics compiles on the first
    real train (the BENCH_r05 boston 3.8x first-train slip)."""
    from ..stages.base import _jsonify

    dynamic = set(getattr(template, "vmap_params", ()))
    static_params = {k: v for k, v in template.params.items()
                     if k not in dynamic}
    try:
        cfg = json.dumps(_jsonify(static_params), sort_keys=True)
    except TypeError:
        cfg = repr(sorted(static_params.items(), key=lambda kv: kv[0]))
    key = (template.__class__, cfg, problem_type, num_classes)
    # lock: warmup runs solo fits on threads (workflow/warmup.py), and the
    # LRU's move_to_end/popitem pair is not safe under concurrent mutation
    with _METRICS_PROGRAM_LOCK:
        fn = _METRICS_PROGRAM_CACHE.get(key)
        if fn is not None:
            _METRICS_PROGRAM_CACHE.move_to_end(key)
    if fn is None:
        import jax

        if problem_type == "multiclass":
            def prog(params, X, y):
                pred, raw, prob = template.predict_fn(params, X)
                return evaluator.device_metrics(pred, raw, prob, y, num_classes)
        else:
            def prog(params, X, y):
                pred, raw, prob = template.predict_fn(params, X)
                return evaluator.device_metrics(pred, raw, prob, y)
        fn = jax.jit(prog)
        # metrics programs ride the shared training AOT store too: a warm
        # process hydrates the fused predict+metrics executable instead of
        # tracing + compiling it (utils/export_cache.py; inert under mesh)
        from ..utils.export_cache import ExportCachingProgram

        fn = ExportCachingProgram(
            fn,
            key_material=f"metrics|{template.__class__.__name__}|{cfg}|"
                         f"{problem_type}|{num_classes}",
            label=f"metrics:{template.__class__.__name__}",
            lane="metrics")
        with _METRICS_PROGRAM_LOCK:
            fn = _METRICS_PROGRAM_CACHE.setdefault(key, fn)
            while len(_METRICS_PROGRAM_CACHE) > _METRICS_PROGRAM_CACHE_MAX:
                _METRICS_PROGRAM_CACHE.popitem(last=False)
    return fn


def default_splitter(problem_type: str, seed: int = 42) -> DataSplitter:
    """Reference default splitters per problem type: balancer for binary, cutter for
    multiclass, plain splitter for regression."""
    if problem_type == "binary":
        return DataBalancer(seed=seed)
    if problem_type == "multiclass":
        return DataCutter(seed=seed)
    return DataSplitter(seed=seed)


def default_models(problem_type: str):
    """Default model families + grids per problem type, mirroring the reference
    defaults (BinaryClassificationModelSelector.scala:52-128: LR/RF/GBT/SVC grids;
    multiclass LR/RF; regression LinReg/RF/GBT/GLM) over the families implemented."""
    from ..stages.model.linear import (
        LinearRegression,
        LinearSVC,
        LogisticRegression,
        MultinomialLogisticRegression,
    )

    reg_grid = ParamGridBuilder().add("l2", REGULARIZATION_GRID).build()
    if problem_type == "binary":
        models = [
            (LogisticRegression(max_iter=25), reg_grid),
            (LinearSVC(), ParamGridBuilder().add("reg", REGULARIZATION_GRID).build()),
        ]
        models.extend(_tree_models("binary"))
        return models
    if problem_type == "multiclass":
        models = [(MultinomialLogisticRegression(), reg_grid)]
        models.extend(_tree_models("multiclass"))
        return models
    models = [(LinearRegression(), reg_grid)]
    models.extend(_tree_models("regression"))
    return models


def _tree_models(problem_type: str):
    """Tree families once available (RandomForest/GBT; DefaultSelectorParams.scala
    MaxDepth/MinInstancesPerNode grids). Empty until the tree ops module lands."""
    try:
        from ..stages.model.trees import default_tree_candidates
    except ImportError:
        return []
    return default_tree_candidates(problem_type)


class BinaryClassificationModelSelector:
    """Factory surface mirroring BinaryClassificationModelSelector.scala."""

    @staticmethod
    def with_cross_validation(num_folds: int = 3, validation_metric: str = "AuPR",
                              splitter: Optional[DataSplitter] = None,
                              models: Optional[Sequence] = None, seed: int = 42,
                              stratify: bool = True) -> ModelSelector:
        return ModelSelector(
            "binary", metric=validation_metric, models=models,
            validator=CrossValidation(num_folds=num_folds, seed=seed, stratify=stratify),
            splitter=splitter or DataBalancer(seed=seed), seed=seed)

    @staticmethod
    def with_train_validation_split(train_ratio: float = 0.75,
                                    validation_metric: str = "AuPR",
                                    splitter: Optional[DataSplitter] = None,
                                    models: Optional[Sequence] = None,
                                    seed: int = 42) -> ModelSelector:
        return ModelSelector(
            "binary", metric=validation_metric, models=models,
            validator=TrainValidationSplit(train_ratio=train_ratio, seed=seed),
            splitter=splitter or DataBalancer(seed=seed), seed=seed)


class MultiClassificationModelSelector:
    @staticmethod
    def with_cross_validation(num_folds: int = 3, validation_metric: str = "F1",
                              splitter: Optional[DataSplitter] = None,
                              models: Optional[Sequence] = None,
                              seed: int = 42) -> ModelSelector:
        return ModelSelector(
            "multiclass", metric=validation_metric, models=models,
            validator=CrossValidation(num_folds=num_folds, seed=seed),
            splitter=splitter or DataCutter(seed=seed), seed=seed)

    @staticmethod
    def with_train_validation_split(train_ratio: float = 0.75,
                                    validation_metric: str = "F1",
                                    splitter: Optional[DataSplitter] = None,
                                    models: Optional[Sequence] = None,
                                    seed: int = 42) -> ModelSelector:
        return ModelSelector(
            "multiclass", metric=validation_metric, models=models,
            validator=TrainValidationSplit(train_ratio=train_ratio, seed=seed),
            splitter=splitter or DataCutter(seed=seed), seed=seed)


class RegressionModelSelector:
    @staticmethod
    def with_cross_validation(num_folds: int = 3,
                              validation_metric: str = "RootMeanSquaredError",
                              splitter: Optional[DataSplitter] = None,
                              models: Optional[Sequence] = None,
                              seed: int = 42) -> ModelSelector:
        return ModelSelector(
            "regression", metric=validation_metric, models=models,
            validator=CrossValidation(num_folds=num_folds, seed=seed, stratify=False),
            splitter=splitter or DataSplitter(seed=seed), seed=seed)

    @staticmethod
    def with_train_validation_split(train_ratio: float = 0.75,
                                    validation_metric: str = "RootMeanSquaredError",
                                    splitter: Optional[DataSplitter] = None,
                                    models: Optional[Sequence] = None,
                                    seed: int = 42) -> ModelSelector:
        return ModelSelector(
            "regression", metric=validation_metric, models=models,
            validator=TrainValidationSplit(train_ratio=train_ratio, seed=seed,
                                           stratify=False),
            splitter=splitter or DataSplitter(seed=seed), seed=seed)
