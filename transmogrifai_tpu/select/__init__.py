"""Model selection & tuning: splitters, grids, batched CV, ModelSelector.

TPU-native re-design of the reference's selector/tuning packages (SURVEY §2.11c):
folds x grid-points ride vmap axes of one compiled program instead of a JVM thread
pool over Spark jobs."""
from .grids import ParamGridBuilder, RandomParamBuilder, pin_grid
from .selector import (
    BinaryClassificationModelSelector,
    ModelSelector,
    ModelSelectorSummary,
    MultiClassificationModelSelector,
    RegressionModelSelector,
    default_models,
)
from .splitters import DataBalancer, DataCutter, DataSplitter, SplitterSummary
from .validator import (
    CrossValidation,
    EvaluatedGridPoint,
    TrainValidationSplit,
    evaluate_candidates,
)

__all__ = [
    "ParamGridBuilder", "RandomParamBuilder", "pin_grid",
    "BinaryClassificationModelSelector", "ModelSelector", "ModelSelectorSummary",
    "MultiClassificationModelSelector", "RegressionModelSelector", "default_models",
    "DataBalancer", "DataCutter", "DataSplitter", "SplitterSummary",
    "CrossValidation", "EvaluatedGridPoint", "TrainValidationSplit",
    "evaluate_candidates",
]
