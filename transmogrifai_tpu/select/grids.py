"""Hyperparameter grid construction: cartesian grids and random search.

Analog of Spark's ParamGridBuilder usage in the selector factories plus the
reference's RandomParamBuilder (core/.../selector/RandomParamBuilder.scala:52).
A grid is just a list of dicts {param_name: value}; the validator later splits each
grid by the family's vmap_params so continuous axes ride one compiled program.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class ParamGridBuilder:
    """Cartesian product grid: `ParamGridBuilder().add("l2", [0.01, 0.1]).build()`."""

    def __init__(self):
        self._axes: list[tuple[str, list]] = []

    def add(self, name: str, values: Sequence) -> "ParamGridBuilder":
        self._axes.append((name, list(values)))
        return self

    def build(self) -> list[dict]:
        grid = [{}]
        for name, values in self._axes:
            grid = [{**g, name: v} for g in grid for v in values]
        return grid


def pin_grid(grid: Sequence[dict], **pins) -> list[dict]:
    """Pin params across an existing grid: every point gets `pins` applied
    on top, and points that differed only on a pinned axis collapse to one
    (first occurrence wins — deterministic in grid order). This is how
    `op autotune` hands a searched knob (n_bins, shard_optimizer) to a
    selector: the CV search stops spending grid points on an axis the
    tuner already fixed, instead of silently overriding the tuned value
    with its own axis."""
    out: list[dict] = []
    seen: set = set()
    for point in grid:
        p = {**point, **pins}
        key = tuple(sorted((k, repr(v)) for k, v in p.items()))
        if key not in seen:
            seen.add(key)
            out.append(p)
    return out


class RandomParamBuilder:
    """Random-search grid (analog of RandomParamBuilder.scala:52): draw each param
    from a uniform / log-uniform ("exponential") / choice distribution."""

    def __init__(self, seed: int = 42):
        self._draws: list[tuple[str, str, tuple]] = []
        self.seed = seed

    def uniform(self, name: str, lo: float, hi: float) -> "RandomParamBuilder":
        self._draws.append((name, "uniform", (lo, hi)))
        return self

    def exponential(self, name: str, lo: float, hi: float) -> "RandomParamBuilder":
        if lo <= 0 or hi <= 0:
            raise ValueError("exponential bounds must be positive")
        self._draws.append((name, "exponential", (lo, hi)))
        return self

    def choice(self, name: str, options: Sequence) -> "RandomParamBuilder":
        self._draws.append((name, "choice", (list(options),)))
        return self

    def build(self, n: int, seed: Optional[int] = None) -> list[dict]:
        rng = np.random.default_rng(self.seed if seed is None else seed)
        out = []
        for _ in range(n):
            point = {}
            for name, kind, args in self._draws:
                if kind == "uniform":
                    lo, hi = args
                    point[name] = float(rng.uniform(lo, hi))
                elif kind == "exponential":
                    lo, hi = args
                    point[name] = float(np.exp(rng.uniform(np.log(lo), np.log(hi))))
                else:
                    (options,) = args
                    point[name] = options[int(rng.integers(len(options)))]
            out.append(point)
        return out
