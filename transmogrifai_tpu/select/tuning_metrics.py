"""Weighted, vmappable metric kernels for the tuning inner loop.

The reference evaluates each CV fold with full Spark evaluators
(OpCrossValidation.scala:102-118). Here the inner loop stays on device: every metric
is a pure-jnp function of (pred, raw, prob, y, w) where `w` is the validation-fold
row weight — so metrics for all folds x grid-points are computed by the same vmapped
program that fit them. Weighted AUCs use sort+cumsum (one device sort per fold/grid
cell); tie handling matches the step-curve convention, and the *final* train/holdout
numbers reported in ModelSelectorSummary come from the exact host evaluators
(evaluators/evaluators.py), so selection and reporting agree with the reference.
"""
from __future__ import annotations

import jax.numpy as jnp


def _binary_scores(prob):
    return prob[:, 1] if prob.shape[-1] > 1 else prob[:, 0]


def _weighted_curve(scores, y, w):
    """-> (tps, fps, P, N) cumulative weighted counts, scores descending."""
    order = jnp.argsort(-scores)
    ys = y[order]
    ws = w[order]
    tps = jnp.cumsum(ws * ys)
    fps = jnp.cumsum(ws * (1.0 - ys))
    return tps, fps, tps[-1], fps[-1]


def weighted_auroc(scores, y, w):
    tps, fps, P, N = _weighted_curve(scores, y, w)
    tpr = tps / jnp.maximum(P, 1e-12)
    fpr = fps / jnp.maximum(N, 1e-12)
    tpr = jnp.concatenate([jnp.zeros(1), tpr])
    fpr = jnp.concatenate([jnp.zeros(1), fpr])
    return jnp.sum((fpr[1:] - fpr[:-1]) * 0.5 * (tpr[1:] + tpr[:-1]))


def weighted_aupr(scores, y, w):
    tps, fps, P, _ = _weighted_curve(scores, y, w)
    precision = tps / jnp.maximum(tps + fps, 1e-12)
    recall = tps / jnp.maximum(P, 1e-12)
    recall = jnp.concatenate([jnp.zeros(1), recall])
    # step interpolation (right-continuous), the average-precision convention
    return jnp.sum((recall[1:] - recall[:-1]) * precision)


def _weighted_confusion_binary(pred, y, w):
    tp = jnp.sum(w * pred * y)
    fp = jnp.sum(w * pred * (1.0 - y))
    fn = jnp.sum(w * (1.0 - pred) * y)
    tn = jnp.sum(w * (1.0 - pred) * (1.0 - y))
    return tp, fp, fn, tn


def weighted_f1(pred, y, w):
    tp, fp, fn, _ = _weighted_confusion_binary(pred, y, w)
    p = tp / jnp.maximum(tp + fp, 1e-12)
    r = tp / jnp.maximum(tp + fn, 1e-12)
    return 2 * p * r / jnp.maximum(p + r, 1e-12)


def weighted_precision(pred, y, w):
    tp, fp, _, _ = _weighted_confusion_binary(pred, y, w)
    return tp / jnp.maximum(tp + fp, 1e-12)


def weighted_recall(pred, y, w):
    tp, _, fn, _ = _weighted_confusion_binary(pred, y, w)
    return tp / jnp.maximum(tp + fn, 1e-12)


def weighted_error(pred, y, w):
    wrong = jnp.sum(w * (pred != y))
    return wrong / jnp.maximum(jnp.sum(w), 1e-12)


def weighted_multiclass_f1(pred, y, w, num_classes: int):
    """Class-frequency-weighted F1 from a weighted confusion built by one-hot matmul."""
    P = jnp.eye(num_classes)[pred.astype(jnp.int32)]  # [N, C]
    Y = jnp.eye(num_classes)[y.astype(jnp.int32)]
    conf = (Y * w[:, None]).T @ P  # [C true, C pred]
    tp = jnp.diag(conf)
    support = conf.sum(axis=1)
    predicted = conf.sum(axis=0)
    prec = tp / jnp.maximum(predicted, 1e-12)
    rec = tp / jnp.maximum(support, 1e-12)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-12)
    return jnp.sum(f1 * support) / jnp.maximum(support.sum(), 1e-12)


def weighted_rmse(pred, y, w):
    return jnp.sqrt(jnp.sum(w * (pred - y) ** 2) / jnp.maximum(jnp.sum(w), 1e-12))


def weighted_mae(pred, y, w):
    return jnp.sum(w * jnp.abs(pred - y)) / jnp.maximum(jnp.sum(w), 1e-12)


def weighted_r2(pred, y, w):
    wsum = jnp.maximum(jnp.sum(w), 1e-12)
    ybar = jnp.sum(w * y) / wsum
    ss_res = jnp.sum(w * (pred - y) ** 2)
    ss_tot = jnp.maximum(jnp.sum(w * (y - ybar) ** 2), 1e-12)
    return 1.0 - ss_res / ss_tot


def make_metric_fn(problem_type: str, metric: str, num_classes: int = 0):
    """-> (fn(pred, raw, prob, y, w) -> scalar, larger_is_better)."""
    binary = {
        "AuROC": (lambda p, r, pr, y, w: weighted_auroc(_binary_scores(pr), y, w), True),
        "AuPR": (lambda p, r, pr, y, w: weighted_aupr(_binary_scores(pr), y, w), True),
        "F1": (lambda p, r, pr, y, w: weighted_f1(p, y, w), True),
        "Precision": (lambda p, r, pr, y, w: weighted_precision(p, y, w), True),
        "Recall": (lambda p, r, pr, y, w: weighted_recall(p, y, w), True),
        "Error": (lambda p, r, pr, y, w: weighted_error(p, y, w), False),
    }
    multi = {
        "F1": (lambda p, r, pr, y, w: weighted_multiclass_f1(p, y, w, num_classes), True),
        "Error": (lambda p, r, pr, y, w: weighted_error(p, y, w), False),
    }
    regression = {
        "RootMeanSquaredError": (lambda p, r, pr, y, w: weighted_rmse(p, y, w), False),
        "MeanAbsoluteError": (lambda p, r, pr, y, w: weighted_mae(p, y, w), False),
        "R2": (lambda p, r, pr, y, w: weighted_r2(p, y, w), True),
    }
    table = {"binary": binary, "multiclass": multi, "regression": regression}[problem_type]
    if metric not in table:
        raise ValueError(f"unknown {problem_type} tuning metric {metric!r}; "
                         f"known: {sorted(table)}")
    return table[metric]
