"""Profiling/tracing: per-phase wall clock, device cost estimates, XLA trace capture.

Analog of the reference's OpSparkListener metrics bus (utils/src/main/scala/com/
salesforce/op/utils/spark/OpSparkListener.scala:56-146, wired via logStageMetrics/
collectStageMetrics in OpParams.scala:94-95): Spark's per-stage task metrics become
(a) per-phase wall clock collected by a context-manager profiler, (b) XLA cost-model
FLOP/byte estimates of the jitted programs (the GC-time/shuffle-bytes analog), and
(c) optional on-disk device traces via jax.profiler for TensorBoard.

Usage:
    with profile(trace_dir=None) as prof:
        ... train/score ...
    prof.report()  # {"phases": [...], "device_cost": {...}}

Workflow.train/transform and WorkflowRunner call `phase(...)` internally; with no
active profiler those are zero-overhead no-ops.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class PhaseTiming:
    name: str
    wall_s: float = 0.0
    count: int = 0


@dataclass
class Profiler:
    phases: dict[str, PhaseTiming] = field(default_factory=dict)
    #: program-name -> XLA cost analysis ({"flops": ..., "bytes accessed": ...})
    device_cost: dict[str, dict[str, float]] = field(default_factory=dict)
    trace_dir: Optional[str] = None
    _order: list[str] = field(default_factory=list)
    _lock: "threading.Lock" = field(default_factory=lambda: threading.Lock())

    def add_phase(self, name: str, wall_s: float) -> None:
        # lock: phases report from worker threads too (warmup's parallel solo
        # fits, the selector's overlapped unit compiles) — the check-then-create
        # and the += pair would lose updates unprotected
        with self._lock:
            t = self.phases.get(name)
            if t is None:
                t = self.phases[name] = PhaseTiming(name)
                self._order.append(name)
            t.wall_s += wall_s
            t.count += 1

    def add_cost(self, name: str, cost: dict[str, float]) -> None:
        self.device_cost[name] = dict(cost)

    def report(self) -> dict:
        out: dict[str, Any] = {
            "phases": [
                {"name": n, "wall_s": round(self.phases[n].wall_s, 6),
                 "count": self.phases[n].count}
                for n in self._order
            ],
        }
        if self.device_cost:
            total_flops = sum(c.get("flops", 0.0) for c in self.device_cost.values())
            out["device_cost"] = {
                "programs": self.device_cost,
                "total_estimated_flops": total_flops,
            }
        if self.trace_dir:
            out["trace_dir"] = self.trace_dir
        return out


_ACTIVE: list[Profiler] = []


def current() -> Optional[Profiler]:
    return _ACTIVE[-1] if _ACTIVE else None


@contextmanager
def profile(trace_dir: Optional[str] = None):
    """Activate a profiler for the dynamic extent; optionally capture an on-disk
    jax.profiler trace viewable in TensorBoard/XProf."""
    prof = Profiler(trace_dir=trace_dir)
    _ACTIVE.append(prof)
    started_trace = False
    if trace_dir is not None:
        import jax

        jax.profiler.start_trace(trace_dir)
        started_trace = True
    try:
        yield prof
    finally:
        if started_trace:
            import jax

            jax.profiler.stop_trace()
        _ACTIVE.pop()


@contextmanager
def phase(name: str):
    """Time a named phase into the active profiler; no-op without one."""
    prof = current()
    if prof is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        prof.add_phase(name, time.perf_counter() - t0)


def record_cost(name: str, jitted_fn, *args, **kwargs) -> None:
    """Attach the XLA cost-model estimate of a jitted program to the active profiler
    (flops / bytes accessed — the compiler's own numbers, not wall-clock measurement)."""
    prof = current()
    if prof is None:
        return
    try:
        compiled = jitted_fn.lower(*args, **kwargs).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        prof.add_cost(name, {
            k: float(v) for k, v in dict(analysis).items()
            if isinstance(v, (int, float)) and k in
            ("flops", "bytes accessed", "utilization operand 0 {}")
        })
    except Exception:
        # cost analysis is best-effort: some backends/fns don't expose it
        pass


#: per-chip peak dense bf16 matmul throughput (FLOP/s) by device kind — the MFU
#: denominator. Public figures for the TPU generations jax reports; anything
#: unknown (e.g. host CPU in tests) yields None and MFU is omitted.
_PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def device_peak_flops(device=None) -> Optional[float]:
    """Peak bf16 FLOP/s of one device, or None when unknown."""
    import jax

    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for name, peak in _PEAK_BF16_FLOPS.items():
        if kind.startswith(name):
            return peak
    return None


def mfu(total_flops: float, wall_s: float, n_devices: int = 1,
        device=None) -> Optional[float]:
    """Model FLOPs Utilization: achieved / peak over the wall-clock interval."""
    peak = device_peak_flops(device)
    if peak is None or wall_s <= 0:
        return None
    return total_flops / (wall_s * peak * n_devices)


def compiled_flops(jitted_fn, *args, **kwargs) -> Optional[float]:
    """FLOPs of one invocation per XLA's own cost model (not wall-clock)."""
    try:
        compiled = jitted_fn.lower(*args, **kwargs).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        return float(dict(analysis).get("flops", 0.0))
    except Exception:
        return None
