"""Profiling facade — thin back-compat layer over `transmogrifai_tpu.obs`.

The flat phase timer that used to live here grew into the hierarchical span
tracer + compile watchdog in `obs/` (spans, XLA compile attribution,
Chrome-trace export, retrace budgets — see docs/observability.md). This module
keeps the original surface working unchanged:

    with profile(trace_dir=None) as prof:
        ... train/score ...
    prof.report()  # superset of the old {"phases": [...], "device_cost": ...}

`profile()` now yields an `obs.Tracer` (exposing the old Profiler attributes:
`phases`, `add_phase`, `add_cost`, `device_cost`, `report()`), `phase(...)`
opens an `obs.span(...)`, and `record_cost`/`compiled_flops` route through the
tracer's cached lowering so cost capture no longer pays a second backend
compile per program. MFU helpers (device peak FLOPs tables) stay here.
"""
from __future__ import annotations

from typing import Optional

from . import obs
from .obs import PhaseTiming, Tracer  # noqa: F401  (back-compat re-exports)
from .obs import compiled_flops, record_cost  # noqa: F401
from .obs.tracer import Tracer as Profiler  # noqa: F401  (legacy name)

current = obs.current
profile = obs.trace
phase = obs.span


#: per-chip peak dense bf16 matmul throughput (FLOP/s) by device kind — the MFU
#: denominator. Public figures for the TPU generations jax reports; anything
#: unknown (e.g. host CPU in tests) yields None and MFU is omitted.
_PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def device_peak_flops(device=None) -> Optional[float]:
    """Peak bf16 FLOP/s of one device, or None when unknown."""
    import jax

    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for name, peak in _PEAK_BF16_FLOPS.items():
        if kind.startswith(name):
            return peak
    return None


def mfu(total_flops: float, wall_s: float, n_devices: int = 1,
        device=None) -> Optional[float]:
    """Model FLOPs Utilization: achieved / peak over the wall-clock interval."""
    peak = device_peak_flops(device)
    if peak is None or wall_s <= 0:
        return None
    return total_flops / (wall_s * peak * n_devices)
