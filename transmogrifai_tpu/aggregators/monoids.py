"""Monoid aggregators per feature kind + event-time cutoff semantics.

Reference parity:
  - MonoidAggregator ~ algebird MonoidAggregator (prepare/plus/present),
    features/.../aggregators/MonoidAggregatorDefaults.scala:59-111 defaults table.
  - Event ~ aggregators/Event.scala (timestamped value).
  - CutOffTime ~ aggregators/CutOffTime.scala:42-69 (UnixEpoch/DaysAgo/WeeksAgo/
    DDMMYYYY/NoCutoff).
  - FeatureAggregator.extract ~ aggregators/FeatureAggregator.scala:61-103 with the
    filterByDateWithCutoff rule (:110-124): predictors take events strictly BEFORE the
    cutoff (optionally within `predictor_window` before it), responses take events AT or
    AFTER the cutoff (optionally within `response_window` after it).
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..types import FeatureKind

_MS_PER_DAY = 24 * 3600 * 1000


# --------------------------------------------------------------------------------------
# Event + CutOffTime
# --------------------------------------------------------------------------------------
@dataclass(frozen=True)
class Event:
    """A timestamped raw value (reference Event.scala)."""

    time: int
    value: Any
    is_response: bool = False


@dataclass(frozen=True)
class CutOffTime:
    """Aggregation cutoff (reference CutOffTime.scala). `time_ms=None` = no cutoff."""

    ctype: str
    time_ms: Optional[int]

    @staticmethod
    def unix_epoch(since_epoch_ms: int) -> "CutOffTime":
        return CutOffTime("UnixEpoch", int(since_epoch_ms))

    @staticmethod
    def days_ago(days: int, now_ms: Optional[int] = None) -> "CutOffTime":
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        return CutOffTime("DaysAgo", now_ms - days * _MS_PER_DAY)

    @staticmethod
    def weeks_ago(weeks: int, now_ms: Optional[int] = None) -> "CutOffTime":
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        return CutOffTime("WeeksAgo", now_ms - weeks * 7 * _MS_PER_DAY)

    @staticmethod
    def ddmmyyyy(s: str) -> "CutOffTime":
        day, month, year = int(s[0:2]), int(s[2:4]), int(s[4:8])
        import datetime

        dt = datetime.datetime(year, month, day, tzinfo=datetime.timezone.utc)
        return CutOffTime("DDMMYYYY", int(dt.timestamp() * 1000))

    @staticmethod
    def no_cutoff() -> "CutOffTime":
        return CutOffTime("NoCutoff", None)


# --------------------------------------------------------------------------------------
# MonoidAggregator
# --------------------------------------------------------------------------------------
@dataclass(frozen=True)
class MonoidAggregator:
    """(zero, prepare, combine, present) — the aggregation algebra for one feature kind.

    `zero` is a factory so mutable accumulators are never shared. `segment_op` names the
    device segment-reduce this monoid lowers to for bulk numeric aggregation
    ("sum" | "max" | "min" | "or" | None for host-only monoids) — see ops/segment.py.
    """

    name: str
    zero: Callable[[], Any]
    prepare: Callable[[Any], Any]
    combine: Callable[[Any, Any], Any]
    present: Callable[[Any], Any]
    segment_op: Optional[str] = None

    def fold(self, values) -> Any:
        acc = self.zero()
        for v in values:
            acc = self.combine(acc, self.prepare(v))
        return self.present(acc)


def CustomMonoidAggregator(
    zero: Any, combine: Callable[[Any, Any], Any], name: str = "custom"
) -> MonoidAggregator:
    """User-defined monoid over raw (non-None) values, None-lifted the way the
    reference's CustomMonoidAggregator.scala:45 lifts into the Option monoid."""

    def _combine(a, b):
        if a is None:
            return b
        if b is None:
            return a
        return combine(a, b)

    return MonoidAggregator(
        name, zero=lambda: None, prepare=lambda v: v,
        combine=_combine, present=lambda a: zero if a is None else a,
    )


# --- option-lifted numeric helpers -----------------------------------------------------
def _opt(binop):
    def _combine(a, b):
        if a is None:
            return b
        if b is None:
            return a
        return binop(a, b)

    return _combine


def _sum_agg(name, cast=float, segment_op="sum") -> MonoidAggregator:
    return MonoidAggregator(
        name,
        zero=lambda: None,
        prepare=lambda v: None if v is None else cast(v),
        combine=_opt(lambda a, b: a + b),
        present=lambda a: a,
        segment_op=segment_op,
    )


def _extreme_agg(name, fn, segment_op) -> MonoidAggregator:
    return MonoidAggregator(
        name,
        zero=lambda: None,
        prepare=lambda v: v,
        combine=_opt(fn),
        present=lambda a: a,
        segment_op=segment_op,
    )


def _mode(counter: dict) -> Optional[Any]:
    """Most frequent value; ties broken by lexicographic order (deterministic, matching
    the reference ModePickList which takes the min of the maximal group)."""
    if not counter:
        return None
    best = max(counter.items(), key=lambda kv: (kv[1], ))
    top = best[1]
    return min(str(k) for k, v in counter.items() if v == top)


def _mode_agg(name) -> MonoidAggregator:
    def prep(v):
        return {} if v is None else {str(v): 1}

    def comb(a, b):
        out = dict(a)
        for k, v in b.items():
            out[k] = out.get(k, 0) + v
        return out

    return MonoidAggregator(name, zero=dict, prepare=prep, combine=comb, present=_mode)


def _concat_text_agg(name) -> MonoidAggregator:
    return MonoidAggregator(
        name,
        zero=lambda: None,
        prepare=lambda v: None if v is None else str(v),
        combine=_opt(lambda a, b: a + b),
        present=lambda a: a,
    )


def _concat_list_agg(name) -> MonoidAggregator:
    return MonoidAggregator(
        name,
        zero=list,
        prepare=lambda v: [] if v is None else list(v),
        combine=lambda a, b: a + b,
        present=lambda a: a,
    )


def _union_set_agg(name) -> MonoidAggregator:
    return MonoidAggregator(
        name,
        zero=frozenset,
        prepare=lambda v: frozenset() if v is None else frozenset(v),
        combine=lambda a, b: a | b,
        present=lambda a: a,
    )


# --- geolocation midpoint --------------------------------------------------------------
def _geo_prepare(v):
    """(lat, lon, accuracy) -> unit-vector accumulator (x, y, z, acc_sum, count).
    Midpoint of points on the sphere, matching Geolocation.scala:44-117's midpoint
    aggregation (unit-vector mean, average accuracy)."""
    if v is None or len(v) == 0:
        return (0.0, 0.0, 0.0, 0.0, 0)
    lat, lon, acc = float(v[0]), float(v[1]), float(v[2]) if len(v) > 2 else 0.0
    la, lo = math.radians(lat), math.radians(lon)
    return (
        math.cos(la) * math.cos(lo),
        math.cos(la) * math.sin(lo),
        math.sin(la),
        acc,
        1,
    )


def _geo_present(acc):
    x, y, z, acc_sum, n = acc
    if n == 0:
        return None
    x, y, z = x / n, y / n, z / n
    hyp = math.hypot(x, y)
    lat = math.degrees(math.atan2(z, hyp))
    lon = math.degrees(math.atan2(y, x))
    return (lat, lon, acc_sum / n)


_GEO_AGG = MonoidAggregator(
    "GeolocationMidpoint",
    zero=lambda: (0.0, 0.0, 0.0, 0.0, 0),
    prepare=_geo_prepare,
    combine=lambda a, b: tuple(ai + bi for ai, bi in zip(a, b)),
    present=_geo_present,
)


# --- map monoids ------------------------------------------------------------------------
def _union_map_agg(name, value_combine) -> MonoidAggregator:
    def comb(a, b):
        out = dict(a)
        for k, v in b.items():
            out[k] = value_combine(out[k], v) if k in out else v
        return out

    return MonoidAggregator(
        name,
        zero=dict,
        prepare=lambda v: {} if v is None else dict(v),
        combine=comb,
        present=lambda a: a,
    )


def _vector_sum_agg() -> MonoidAggregator:
    import numpy as np

    return MonoidAggregator(
        "SumVector",
        zero=lambda: None,
        prepare=lambda v: None if v is None else np.asarray(v, dtype=float),
        combine=_opt(lambda a, b: a + b),
        present=lambda a: a,
        segment_op="sum",
    )


# --------------------------------------------------------------------------------------
# Defaults registry — mirrors MonoidAggregatorDefaults.scala:59-111
# --------------------------------------------------------------------------------------
def _build_defaults() -> dict[str, MonoidAggregator]:
    d: dict[str, MonoidAggregator] = {}
    # numerics
    for k in ("Real", "RealNN", "Currency", "Percent"):
        d[k] = _sum_agg(f"Sum{k}")
    d["Integral"] = _sum_agg("SumIntegral", cast=int)
    d["Binary"] = MonoidAggregator(
        "LogicalOr",
        zero=lambda: None,
        prepare=lambda v: v,
        combine=_opt(lambda a, b: bool(a) or bool(b)),
        present=lambda a: a,
        segment_op="or",
    )
    d["Date"] = _extreme_agg("MaxDate", max, "max")
    d["DateTime"] = _extreme_agg("MaxDateTime", max, "max")
    # text: free text concatenates, categorical-ish takes the mode
    for k in ("Text", "TextArea", "Base64"):
        d[k] = _concat_text_agg(f"Concat{k}")
    for k in ("PickList", "ComboBox", "ID", "Email", "Phone", "URL",
              "Country", "State", "City", "PostalCode", "Street"):
        d[k] = _mode_agg(f"Mode{k}")
    # collections
    d["TextList"] = _concat_list_agg("ConcatTextList")
    d["DateList"] = _concat_list_agg("ConcatDateList")
    d["DateTimeList"] = _concat_list_agg("ConcatDateTimeList")
    d["MultiPickList"] = _union_set_agg("UnionMultiPickList")
    d["Geolocation"] = _GEO_AGG
    d["OPVector"] = _vector_sum_agg()
    # maps: union with per-kind value combination (UnionRealMap / UnionConcatTextMap /
    # UnionMultiPickListMap ... MonoidAggregatorDefaults.scala:66-87)
    num_add = lambda a, b: a + b
    d["RealMap"] = _union_map_agg("UnionRealMap", num_add)
    d["CurrencyMap"] = _union_map_agg("UnionCurrencyMap", num_add)
    d["PercentMap"] = _union_map_agg("UnionPercentMap", num_add)
    d["IntegralMap"] = _union_map_agg("UnionIntegralMap", num_add)
    d["BinaryMap"] = _union_map_agg("UnionBinaryMap", lambda a, b: bool(a) or bool(b))
    d["DateMap"] = _union_map_agg("UnionMaxDateMap", max)
    d["DateTimeMap"] = _union_map_agg("UnionMaxDateTimeMap", max)
    d["MultiPickListMap"] = _union_map_agg(
        "UnionMultiPickListMap", lambda a, b: frozenset(a) | frozenset(b)
    )
    for k in ("TextMap", "TextAreaMap", "PickListMap", "ComboBoxMap", "IDMap",
              "EmailMap", "PhoneMap", "URLMap", "CountryMap", "StateMap", "CityMap",
              "PostalCodeMap", "StreetMap", "NameMap", "Base64Map"):
        d[k] = _union_map_agg(f"UnionConcat{k}", lambda a, b: str(a) + str(b))
    # GeolocationMap: accumulate per-key unit-vector sums and only convert to a
    # midpoint in present(), so the combine stays associative (combining presented
    # midpoints would weight later events more)
    def _geomap_prepare(v):
        return {} if v is None else {k: _geo_prepare(p) for k, p in dict(v).items()}

    def _geomap_combine(a, b):
        out = dict(a)
        for k, acc in b.items():
            out[k] = (
                tuple(x + y for x, y in zip(out[k], acc)) if k in out else acc
            )
        return out

    d["GeolocationMap"] = MonoidAggregator(
        "UnionGeolocationMidpointMap",
        zero=dict,
        prepare=_geomap_prepare,
        combine=_geomap_combine,
        present=lambda a: {k: _geo_present(acc) for k, acc in a.items()},
    )
    return d


MONOID_DEFAULTS: dict[str, MonoidAggregator] = _build_defaults()


def default_aggregator(kind: FeatureKind | str) -> MonoidAggregator:
    """Default monoid for a feature kind (MonoidAggregatorDefaults.aggregatorOf)."""
    name = kind if isinstance(kind, str) else kind.name
    agg = MONOID_DEFAULTS.get(name)
    if agg is None:
        raise KeyError(f"no default aggregator for kind {name!r}")
    return agg


# --------------------------------------------------------------------------------------
# FeatureAggregator — event filtering + fold
# --------------------------------------------------------------------------------------
@dataclass
class FeatureAggregator:
    """Aggregates one feature's events for one entity, honoring the cutoff rule
    (reference FeatureAggregator.scala:61-124).

    Predictors: event.time < cutoff (and >= cutoff - window if a window is set).
    Responses:  event.time >= cutoff (and <= cutoff + window if a window is set).
    """

    extract_fn: Callable[[Any], Any]
    aggregator: MonoidAggregator
    is_response: bool = False
    special_window_ms: Optional[int] = None  # per-feature override of the reader window

    def event_in_window(
        self,
        event_time: int,
        cutoff: CutOffTime,
        window_ms: Optional[int],
    ) -> bool:
        if cutoff.time_ms is None:
            return True
        c = cutoff.time_ms
        w = self.special_window_ms if self.special_window_ms is not None else window_ms
        if self.is_response:
            return event_time >= c and (w is None or event_time <= c + w)
        return event_time < c and (w is None or event_time >= c - w)

    def extract(
        self,
        records,
        timestamp_fn: Optional[Callable[[Any], int]],
        cutoff: CutOffTime,
        response_window_ms: Optional[int] = None,
        predictor_window_ms: Optional[int] = None,
    ) -> Any:
        agg = self.aggregator
        window = response_window_ms if self.is_response else predictor_window_ms
        acc = agg.zero()
        for record in records:
            t = timestamp_fn(record) if timestamp_fn is not None else 0
            if self.event_in_window(int(t), cutoff, window):
                acc = agg.combine(acc, agg.prepare(self.extract_fn(record)))
        return agg.present(acc)
