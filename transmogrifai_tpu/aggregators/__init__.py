"""Monoid aggregators: rolling multi-row entities up to one training row.

TPU-native analog of the reference's algebird aggregator layer
(features/src/main/scala/com/salesforce/op/aggregators/): `MonoidAggregator` =
(zero, prepare, combine, present) dataclass; per-kind defaults registry mirrors
MonoidAggregatorDefaults.scala; `Event`/`CutOffTime` carry the leakage-control time
semantics of Event.scala / CutOffTime.scala; `FeatureAggregator` applies the
predictor-before-cutoff / response-after-cutoff filter of FeatureAggregator.scala:100.

Bulk numeric aggregation lowers to device segment reductions (ops/segment.py) instead of
Spark's reduceByKey shuffle (reference DataReader.scala:206-279).
"""
from .monoids import (
    CutOffTime,
    CustomMonoidAggregator,
    Event,
    FeatureAggregator,
    MonoidAggregator,
    default_aggregator,
    MONOID_DEFAULTS,
)

__all__ = [
    "CutOffTime",
    "CustomMonoidAggregator",
    "Event",
    "FeatureAggregator",
    "MonoidAggregator",
    "default_aggregator",
    "MONOID_DEFAULTS",
]
