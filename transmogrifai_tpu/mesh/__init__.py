from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    shard_batch,
    shard_grid,
    replicate,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "shard_batch",
    "shard_grid",
    "replicate",
]
