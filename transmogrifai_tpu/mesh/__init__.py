from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    global_batch_from_process_shards,
    make_mesh,
    make_multislice_mesh,
    process_local_batch,
    shard_batch,
    shard_grid,
    shard_wide,
    shard_for_training,
    pad_to_multiple,
    replicate,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "global_batch_from_process_shards",
    "make_mesh",
    "make_multislice_mesh",
    "process_local_batch",
    "shard_batch",
    "shard_grid",
    "shard_wide",
    "shard_for_training",
    "pad_to_multiple",
    "replicate",
]
