"""Device mesh: the framework's entire distributed substrate.

The reference outsources distribution to Spark (SURVEY.md §2.12): row parallelism =
RDD maps, aggregation = treeAggregate, tuning parallelism = a thread pool. Here the
substrate is a `jax.sharding.Mesh` with two named axes:

  - DATA_AXIS ("data"): rows of the training matrix are sharded across chips; every
    monoid aggregation (moments, correlations, gradients, histogram stats) becomes an
    XLA reduction that lowers to psum over ICI — no hand-written collectives.
  - MODEL_AXIS ("model"): the tuning axis — CV folds x hyperparameter grid points are
    laid out here (vmapped fits with per-point params sharded over MODEL_AXIS), the
    role Spark's thread-pool model-parallelism plays in OpCrossValidation.scala:102-118.

On a single host this still works (mesh of 1..8 local devices); on multi-host TPU the
same code spans slices via jax's global mesh — DCN collectives ride the same psum calls.
Wide-feature sharding (this domain's "sequence parallelism", SURVEY §5.7) lays the
feature axis of X over MODEL_AXIS when D is large: partial dot-products psum across it.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    n_data: Optional[int] = None,
    n_model: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data x model) mesh over the available devices. An explicit
    `n_data` requests exactly n_data*n_model devices (extras intentionally
    unused); with n_data inferred, n_model must divide the device count —
    silently training on fewer devices than visible is never the default."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        if len(devices) % n_model != 0:
            raise ValueError(
                f"n_model={n_model} must divide the {len(devices)} devices "
                "(or pass n_data explicitly to use a subset)"
            )
        n_data = max(1, len(devices) // n_model)
    use = devices[: n_data * n_model]
    if len(use) < n_data * n_model:
        raise ValueError(
            f"mesh {n_data}x{n_model} needs {n_data * n_model} devices, "
            f"have {len(devices)}"
        )
    arr = np.array(use).reshape(n_data, n_model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def make_multislice_mesh(
    n_model: int = 1,
    devices: Optional[Sequence] = None,
    slice_assignments: Optional[Sequence[int]] = None,
) -> Mesh:
    """(data x model) mesh spanning multiple slices/hosts (the pod-scale form of
    SURVEY §5.8): each slice's devices are laid CONTIGUOUSLY along the data axis,
    and the model/tuning axis pairs devices within one slice. Reductions over
    DATA_AXIS (gradient psums, moment/histogram combines) are associative, so
    XLA's hierarchical collectives do the heavy segment over ICI inside each
    slice and only the tiny per-slice partials cross DCN — the layout, not
    hand-written comms, is the whole multi-host story. Cross-slice traffic on
    MODEL_AXIS never occurs with this layout.

    Slice membership comes from each device's `slice_index` (TPU multi-slice) or
    `process_index` (multi-host CPU/GPU); `slice_assignments` overrides it (one
    slice id per device — how tests fake a 2-slice topology on 8 CPU devices).
    Falls back to `make_mesh` when only one slice is visible."""
    devices = list(devices if devices is not None else jax.devices())
    if slice_assignments is None:
        def _slice_of(d):
            si = getattr(d, "slice_index", None)  # slice 0 is falsy but VALID
            return si if si is not None else d.process_index

        slice_assignments = [_slice_of(d) for d in devices]
    if len(slice_assignments) != len(devices):
        raise ValueError(
            f"{len(slice_assignments)} slice assignments for {len(devices)} devices"
        )
    groups: dict = {}
    for d, sl in zip(devices, slice_assignments):
        groups.setdefault(sl, []).append(d)
    if len(groups) <= 1:
        return make_mesh(n_model=n_model, devices=devices)  # raises if non-dividing
    sizes = {sl: len(g) for sl, g in groups.items()}
    if len(set(sizes.values())) != 1:
        # a mesh must be rectangular; silently trimming the bigger slice would
        # train on less hardware than provisioned
        raise ValueError(f"slices are uneven ({sizes}); pass explicit devices")
    per = next(iter(sizes.values()))
    if per % n_model != 0:
        raise ValueError(
            f"n_model={n_model} must divide the {per} devices of each slice, or "
            "the tuning axis would pair devices across DCN"
        )
    ordered = [
        d for sl in sorted(groups) for d in sorted(groups[sl], key=lambda x: x.id)
    ]
    arr = np.array(ordered).reshape(-1, n_model)  # slice-contiguous data axis
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def shard_batch(mesh: Mesh, arr, batch_dim: int = 0):
    """Place an array with its batch dim sharded over DATA_AXIS (rows across chips)."""
    spec = [None] * np.ndim(arr)
    spec[batch_dim] = DATA_AXIS
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


def shard_grid(mesh: Mesh, arr, grid_dim: int = 0):
    """Place a hyperparameter-grid axis over MODEL_AXIS."""
    spec = [None] * np.ndim(arr)
    spec[grid_dim] = MODEL_AXIS
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


def replicate(mesh: Mesh, arr):
    return jax.device_put(arr, NamedSharding(mesh, P()))


def shard_wide(mesh: Mesh, arr):
    """Place an [N, D] design matrix with rows over DATA_AXIS AND columns over
    MODEL_AXIS — the wide-feature sharding of SURVEY §5.7 (this domain's sequence
    parallelism). Downstream X@w / X^T r matmuls under jit then psum their partial
    dot-products over the model axis and their row-partials over the data axis;
    XLA inserts the collectives from the sharding alone."""
    return jax.device_put(arr, NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS)))


def shard_for_training(mesh: Mesh, X, y, wide_threshold: Optional[int] = None):
    """Default trainer-layer placement for a fit: rows over the data axis whenever
    they divide it; the feature axis additionally over the model axis when the
    matrix is wide (>= wide_threshold columns, defaulting to the SAME threshold
    that flips LogisticRegression to its D-linear solver — the two decisions must
    agree or a feature-sharded matrix would still run the DxD-Hessian path).
    Falls back to replication for non-dividing axes (XLA requires even shards)."""
    if wide_threshold is None:
        from ..ops.linear import WIDE_D_THRESHOLD

        wide_threshold = WIDE_D_THRESHOLD
    n, d = X.shape
    n_data = mesh.shape[DATA_AXIS]
    n_model = mesh.shape[MODEL_AXIS]
    row_ok = n % n_data == 0
    col_ok = d >= wide_threshold and d % n_model == 0 and n_model > 1
    spec = P(DATA_AXIS if row_ok else None, MODEL_AXIS if col_ok else None)
    Xs = jax.device_put(X, NamedSharding(mesh, spec))
    ys = jax.device_put(y, NamedSharding(mesh, P(DATA_AXIS if row_ok else None)))
    return Xs, ys


def process_local_batch(mesh: Mesh, local_rows, batch_dim: int = 0):
    """Multi-host ingestion (SURVEY §2.7's TPU column): each PROCESS passes only
    the rows its own reader loaded, and jax assembles the global DATA_AXIS-
    sharded array without any host ever holding the full matrix
    (jax.make_array_from_process_local_data). Single-process meshes degenerate
    to a plain sharded device_put — same call site either way."""
    spec = [None] * np.ndim(local_rows)
    spec[batch_dim] = DATA_AXIS
    sharding = NamedSharding(mesh, P(*spec))
    local = np.asarray(local_rows)
    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(sharding, local)


def global_batch_from_process_shards(mesh: Mesh, local_parts: Sequence,
                                     batch_dim: int = 0):
    """Assemble a DATA_AXIS-sharded global array from PER-PROCESS local row
    blocks on a single controller — the dryrun/test twin of
    `process_local_batch` (which takes only this process's block): each block
    lands on its contiguous share of the data axis via
    jax.make_array_from_single_device_arrays, so the construction exercises the
    same per-shard placement a real pod performs, without N hosts."""
    parts = [np.asarray(p) for p in local_parts]
    n_total = sum(p.shape[batch_dim] for p in parts)
    n_data = mesh.shape[DATA_AXIS]
    if n_total % n_data != 0:
        raise ValueError(f"{n_total} rows do not divide the data axis ({n_data})")
    flat = np.concatenate(parts, axis=batch_dim)  # single-controller only
    shape = flat.shape
    spec = [None] * flat.ndim
    spec[batch_dim] = DATA_AXIS
    sharding = NamedSharding(mesh, P(*spec))
    arrays = [
        jax.device_put(flat[idx], device)
        for device, idx in sharding.addressable_devices_indices_map(shape).items()
    ]
    return jax.make_array_from_single_device_arrays(shape, sharding, arrays)


def pad_to_multiple(arr, multiple: int, axis: int = 0, fill=0):
    """Pad a batch axis so it divides the mesh (XLA needs even shards); returns
    (padded, original_length)."""
    n = arr.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return arr, n
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, rem)
    return np.pad(np.asarray(arr), widths, constant_values=fill), n
