"""Device mesh: the framework's entire distributed substrate.

The reference outsources distribution to Spark (SURVEY.md §2.12): row parallelism =
RDD maps, aggregation = treeAggregate, tuning parallelism = a thread pool. Here the
substrate is a `jax.sharding.Mesh` with two named axes:

  - DATA_AXIS ("data"): rows of the training matrix are sharded across chips; every
    monoid aggregation (moments, correlations, gradients, histogram stats) becomes an
    XLA reduction that lowers to psum over ICI — no hand-written collectives.
  - MODEL_AXIS ("model"): the tuning axis — CV folds x hyperparameter grid points are
    laid out here (vmapped fits with per-point params sharded over MODEL_AXIS), the
    role Spark's thread-pool model-parallelism plays in OpCrossValidation.scala:102-118.

On a single host this still works (mesh of 1..8 local devices); on multi-host TPU the
same code spans slices via jax's global mesh — DCN collectives ride the same psum calls.
Wide-feature sharding (this domain's "sequence parallelism", SURVEY §5.7) lays the
feature axis of X over MODEL_AXIS when D is large: partial dot-products psum across it.
"""
from __future__ import annotations

import threading
from typing import Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


# --- mesh observability ---------------------------------------------------------------
# Process-wide counters of mesh-placement work: every sharded/replicated
# device_put issued through the helpers below (count + bytes) and every
# dispatch of a program whose reductions psum over the mesh (recorded by the
# sharded callers: validator search units, sanity/stats passes, sharded
# scoring batches). The counters LIVE in the unified metrics registry
# (obs/metrics.py — `mesh_*` series in `op monitor --prom` and AppMetrics'
# `metrics` section); mesh_stats()/reset_mesh_stats() keep the historical
# per-run-delta surface the runner's `mesh` section is built from.
from ..obs import metrics as _obs_metrics

_MESH_COUNTERS = {
    "transfers": ("mesh_transfers_total",
                  "sharded/replicated device_put placements issued by mesh "
                  "helpers"),
    "transfer_bytes": ("mesh_transfer_bytes_total",
                       "bytes moved by mesh placement device_puts"),
    "sharded_dispatches": ("mesh_sharded_dispatches_total",
                           "dispatches of programs over sharded operands "
                           "(psum over ICI)"),
    "collective_bytes": ("mesh_collective_bytes_total",
                         "modeled ICI collective payload bytes recorded by "
                         "sharded fits (psum/all_gather/psum_scatter "
                         "tensors, Alpa-style byte counting)"),
}


def _counter(key: str) -> "_obs_metrics.Counter":
    # fetched per call (one lock + dict hit, trivial next to a device_put):
    # module-cached instruments would detach from the registry when tests
    # reset it
    name, help_text = _MESH_COUNTERS[key]
    return _obs_metrics.default_registry().counter(name, help=help_text)


_MESH_STATS_LOCK = threading.Lock()
#: reset_mesh_stats() baseline: registry counters are monotone by contract,
#: so "reset" subtracts a remembered floor instead of rewinding them
_MESH_STATS_BASE = {"transfers": 0.0, "transfer_bytes": 0.0,
                    "sharded_dispatches": 0.0, "collective_bytes": 0.0}


def record_transfer(arr) -> None:
    _counter("transfers").inc()
    _counter("transfer_bytes").inc(int(getattr(arr, "nbytes", 0) or 0))


def record_sharded_dispatch(n: int = 1) -> None:
    """Count a dispatch of a program running over sharded operands (its
    cross-device reductions lower to psum over ICI)."""
    _counter("sharded_dispatches").inc(int(n))


def record_collective(nbytes: int) -> None:
    """Record the modeled ICI payload of a sharded fit's collectives
    (logical tensor bytes per psum/all_gather/psum_scatter, summed over the
    fit). Recorded host-side by the sharded trainers from their RUNTIME
    shapes, so the static resource model (analyze/shard_model.py) can be
    held to predicted-vs-measured parity in tests."""
    if nbytes > 0:
        _counter("collective_bytes").inc(int(nbytes))


def mesh_stats() -> dict:
    totals = {k: _counter(k).value for k in _MESH_COUNTERS}
    with _MESH_STATS_LOCK:
        return {k: int(v - min(_MESH_STATS_BASE[k], v))
                for k, v in totals.items()}


def reset_mesh_stats() -> None:
    with _MESH_STATS_LOCK:
        for k in _MESH_COUNTERS:
            _MESH_STATS_BASE[k] = _counter(k).value


def mesh_section(mesh: Optional[Mesh],
                 base: Optional[dict] = None) -> Optional[dict]:
    """The AppMetrics `mesh` report: axis sizes + placement counters. With
    `base` (an earlier mesh_stats() snapshot) the counters are per-run
    deltas — how the runner scopes the process-wide totals to one run."""
    if mesh is None:
        return None
    stats = mesh_stats()
    if base is not None:
        stats = {k: v - base.get(k, 0) for k, v in stats.items()}
    return {
        "shape": {DATA_AXIS: int(mesh.shape[DATA_AXIS]),
                  MODEL_AXIS: int(mesh.shape[MODEL_AXIS])},
        "n_devices": int(mesh.size),
        **stats,
    }


# --- auto-mesh ------------------------------------------------------------------------
def parse_mesh_shape(spec: Union[None, str, Sequence[int]]):
    """'4,2' / 'data,model' counts / (4, 2) -> (n_data, n_model);
    None or 'auto' -> None (let auto_mesh lay all devices on the data axis)."""
    if spec is None or spec == "auto":
        return None
    if isinstance(spec, str):
        parts = [p.strip() for p in spec.split(",") if p.strip()]
    else:
        parts = list(spec)
    if len(parts) != 2:
        raise ValueError(
            f"mesh shape must be 'n_data,n_model' (e.g. '4,2') or 'auto', "
            f"got {spec!r}")
    n_data, n_model = int(parts[0]), int(parts[1])
    if n_data < 1 or n_model < 1:
        raise ValueError(f"mesh axes must be >= 1, got {n_data}x{n_model}")
    return n_data, n_model


def auto_mesh(mesh_shape: Union[None, str, Sequence[int]] = None,
              devices: Optional[Sequence] = None) -> Optional[Mesh]:
    """The default multi-device wiring (Workflow.train / WorkflowRunner /
    `op run`): build a (data x model) mesh over every visible device. With no
    explicit shape, all devices lay on the DATA axis — per the cross-replica
    data-parallel touchstone (PAPERS.md), the layout carries the scaling and
    row-parallel reductions psum over ICI, while the tuning grid stays
    unsharded (grid sharding needs padding; opt in via an explicit shape).

    Returns None when exactly ONE device is visible and no shape was
    requested: single-chip execution degenerates to the unmeshed path exactly
    (same programs, same caches, zero behavior change)."""
    shape = parse_mesh_shape(mesh_shape)
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        if len(devices) <= 1:
            return None
        return make_mesh(n_data=len(devices), n_model=1, devices=devices)
    n_data, n_model = shape
    return make_mesh(n_data=n_data, n_model=n_model, devices=devices)


def data_axis_size(mesh: Optional[Mesh]) -> int:
    """Data-axis extent of a possibly-absent mesh (1 = unmeshed) — the gate
    every data-sharded code path keys on (trees' sharded split finding, the
    OP406 lint)."""
    return 1 if mesh is None else int(mesh.shape[DATA_AXIS])


def mesh_shard_map(body, mesh: Mesh, in_specs, out_specs):
    """Version-portable `shard_map` over this mesh with replication checking
    OFF — the tree lane's sharded split program carries a pallas_call, for
    which shard_map has no replication rule (check_rep=True raises
    NotImplementedError); correctness of the replicated outputs is carried by
    the psum that precedes them. Newer jax renames the flag (check_vma) and
    promotes shard_map out of jax.experimental — both spellings are tried so
    the call sites never version-switch."""
    try:
        from jax.experimental.shard_map import shard_map as _sm
    except ImportError:  # jax >= 0.8: promoted to the top-level namespace
        _sm = jax.shard_map
    try:
        return _sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)
    except TypeError:
        return _sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)


def use_mesh(mesh: Mesh):
    """Version-portable ambient-mesh context: `jax.set_mesh` where it exists
    (jax >= 0.6), falling back to the classic `Mesh` context manager. Only
    needed by code relying on ambient-mesh name resolution — NamedSharding-
    placed inputs partition under plain jit without it."""
    set_mesh = getattr(jax, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    return mesh  # Mesh is itself a context manager


def default_mesh(mesh_shape: Union[None, str, Sequence[int]] = None) -> Optional[Mesh]:
    """The shared auto-mesh resolution of Workflow.train / WorkflowRunner /
    `op warmup`: auto_mesh over the visible devices, honoring the
    TT_AUTO_MESH=0 kill switch (which disables only the IMPLICIT mesh — an
    explicit mesh_shape still builds one)."""
    import os

    if mesh_shape is None and os.environ.get("TT_AUTO_MESH", "1") == "0":
        return None
    return auto_mesh(mesh_shape)


def make_mesh(
    n_data: Optional[int] = None,
    n_model: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data x model) mesh over the available devices. An explicit
    `n_data` requests exactly n_data*n_model devices (extras intentionally
    unused); with n_data inferred, n_model must divide the device count —
    silently training on fewer devices than visible is never the default."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        if len(devices) % n_model != 0:
            raise ValueError(
                f"n_model={n_model} must divide the {len(devices)} devices "
                "(or pass n_data explicitly to use a subset)"
            )
        n_data = max(1, len(devices) // n_model)
    use = devices[: n_data * n_model]
    if len(use) < n_data * n_model:
        raise ValueError(
            f"mesh {n_data}x{n_model} needs {n_data * n_model} devices, "
            f"have {len(devices)}"
        )
    arr = np.array(use).reshape(n_data, n_model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def make_multislice_mesh(
    n_model: int = 1,
    devices: Optional[Sequence] = None,
    slice_assignments: Optional[Sequence[int]] = None,
) -> Mesh:
    """(data x model) mesh spanning multiple slices/hosts (the pod-scale form of
    SURVEY §5.8): each slice's devices are laid CONTIGUOUSLY along the data axis,
    and the model/tuning axis pairs devices within one slice. Reductions over
    DATA_AXIS (gradient psums, moment/histogram combines) are associative, so
    XLA's hierarchical collectives do the heavy segment over ICI inside each
    slice and only the tiny per-slice partials cross DCN — the layout, not
    hand-written comms, is the whole multi-host story. Cross-slice traffic on
    MODEL_AXIS never occurs with this layout.

    Slice membership comes from each device's `slice_index` (TPU multi-slice) or
    `process_index` (multi-host CPU/GPU); `slice_assignments` overrides it (one
    slice id per device — how tests fake a 2-slice topology on 8 CPU devices).
    Falls back to `make_mesh` when only one slice is visible."""
    devices = list(devices if devices is not None else jax.devices())
    if slice_assignments is None:
        def _slice_of(d):
            si = getattr(d, "slice_index", None)  # slice 0 is falsy but VALID
            return si if si is not None else d.process_index

        slice_assignments = [_slice_of(d) for d in devices]
    if len(slice_assignments) != len(devices):
        raise ValueError(
            f"{len(slice_assignments)} slice assignments for {len(devices)} devices"
        )
    groups: dict = {}
    for d, sl in zip(devices, slice_assignments):
        groups.setdefault(sl, []).append(d)
    if len(groups) <= 1:
        return make_mesh(n_model=n_model, devices=devices)  # raises if non-dividing
    sizes = {sl: len(g) for sl, g in groups.items()}
    if len(set(sizes.values())) != 1:
        # a mesh must be rectangular; silently trimming the bigger slice would
        # train on less hardware than provisioned
        raise ValueError(f"slices are uneven ({sizes}); pass explicit devices")
    per = next(iter(sizes.values()))
    if per % n_model != 0:
        raise ValueError(
            f"n_model={n_model} must divide the {per} devices of each slice, or "
            "the tuning axis would pair devices across DCN"
        )
    ordered = [
        d for sl in sorted(groups) for d in sorted(groups[sl], key=lambda x: x.id)
    ]
    arr = np.array(ordered).reshape(-1, n_model)  # slice-contiguous data axis
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def shard_batch(mesh: Mesh, arr, batch_dim: int = 0):
    """Place an array with its batch dim sharded over DATA_AXIS (rows across chips)."""
    spec = [None] * np.ndim(arr)
    spec[batch_dim] = DATA_AXIS
    record_transfer(arr)
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


def shard_grid(mesh: Mesh, arr, grid_dim: int = 0):
    """Place a hyperparameter-grid axis over MODEL_AXIS."""
    spec = [None] * np.ndim(arr)
    spec[grid_dim] = MODEL_AXIS
    record_transfer(arr)
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


def replicate(mesh: Mesh, arr):
    record_transfer(arr)
    return jax.device_put(arr, NamedSharding(mesh, P()))


def shard_rows_padded(mesh: Mesh, X, y=None, w=None):
    """Rows over DATA_AXIS for arbitrary row counts: pad to a multiple of the
    data axis by REPEATING ROW 0 with WEIGHT 0, so every weighted reduction
    (moments, correlations, contingency matmuls) is exact and min/max see only
    values already present. Returns (Xs, ys, ws, n_rows) — consumers MUST
    thread `ws` through their reductions; unweighted statistics (ranks,
    unweighted quantiles) are NOT pad-safe and must use even shards instead.

    This is the HOST-side form (numpy pad, one H2D per array) for ingest-time
    call sites and benches. SanityChecker.fit_columns applies the same
    repeat-row-0/weight-0 policy DEVICE-side (jnp.concatenate + reshard) so an
    already-device-resident design matrix never round-trips to the host —
    keep the two in sync."""
    X = np.asarray(X)
    n = X.shape[0]
    n_data = mesh.shape[DATA_AXIS]
    pad = (-n) % n_data
    w_full = np.ones(n, np.float32) if w is None else np.asarray(w, np.float32)
    if pad:
        X = np.concatenate([X, np.repeat(X[:1], pad, axis=0)])
        w_full = np.concatenate([w_full, np.zeros(pad, np.float32)])
        if y is not None:
            y = np.asarray(y)
            y = np.concatenate([y, np.repeat(y[:1], pad, axis=0)])
    Xs = shard_batch(mesh, X)
    ys = None if y is None else shard_batch(mesh, y)
    ws = shard_batch(mesh, w_full)
    return Xs, ys, ws, n


def shard_wide(mesh: Mesh, arr):
    """Place an [N, D] design matrix with rows over DATA_AXIS AND columns over
    MODEL_AXIS — the wide-feature sharding of SURVEY §5.7 (this domain's sequence
    parallelism). Downstream X@w / X^T r matmuls under jit then psum their partial
    dot-products over the model axis and their row-partials over the data axis;
    XLA inserts the collectives from the sharding alone."""
    record_transfer(arr)
    return jax.device_put(arr, NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS)))


def shard_for_training(mesh: Mesh, X, y, wide_threshold: Optional[int] = None):
    """Default trainer-layer placement for a fit: rows over the data axis whenever
    they divide it; the feature axis additionally over the model axis when the
    matrix is wide (>= wide_threshold columns, defaulting to the SAME threshold
    that flips LogisticRegression to its D-linear solver — the two decisions must
    agree or a feature-sharded matrix would still run the DxD-Hessian path).
    Falls back to replication for non-dividing axes (XLA requires even shards)."""
    if wide_threshold is None:
        from ..ops.linear import WIDE_D_THRESHOLD

        wide_threshold = WIDE_D_THRESHOLD
    n, d = X.shape
    n_data = mesh.shape[DATA_AXIS]
    n_model = mesh.shape[MODEL_AXIS]
    row_ok = n % n_data == 0
    col_ok = d >= wide_threshold and d % n_model == 0 and n_model > 1
    spec = P(DATA_AXIS if row_ok else None, MODEL_AXIS if col_ok else None)
    record_transfer(X)
    record_transfer(y)
    Xs = jax.device_put(X, NamedSharding(mesh, spec))
    ys = jax.device_put(y, NamedSharding(mesh, P(DATA_AXIS if row_ok else None)))
    return Xs, ys


def process_local_batch(mesh: Mesh, local_rows, batch_dim: int = 0):
    """Multi-host ingestion (SURVEY §2.7's TPU column): each PROCESS passes only
    the rows its own reader loaded, and jax assembles the global DATA_AXIS-
    sharded array without any host ever holding the full matrix
    (jax.make_array_from_process_local_data). Single-process meshes degenerate
    to a plain sharded device_put — same call site either way."""
    spec = [None] * np.ndim(local_rows)
    spec[batch_dim] = DATA_AXIS
    sharding = NamedSharding(mesh, P(*spec))
    local = np.asarray(local_rows)
    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(sharding, local)


def global_batch_from_process_shards(mesh: Mesh, local_parts: Sequence,
                                     batch_dim: int = 0):
    """Assemble a DATA_AXIS-sharded global array from PER-PROCESS local row
    blocks on a single controller — the dryrun/test twin of
    `process_local_batch` (which takes only this process's block): each block
    lands on its contiguous share of the data axis via
    jax.make_array_from_single_device_arrays, so the construction exercises the
    same per-shard placement a real pod performs, without N hosts."""
    parts = [np.asarray(p) for p in local_parts]
    n_total = sum(p.shape[batch_dim] for p in parts)
    n_data = mesh.shape[DATA_AXIS]
    if n_total % n_data != 0:
        raise ValueError(f"{n_total} rows do not divide the data axis ({n_data})")
    flat = np.concatenate(parts, axis=batch_dim)  # single-controller only
    shape = flat.shape
    spec = [None] * flat.ndim
    spec[batch_dim] = DATA_AXIS
    sharding = NamedSharding(mesh, P(*spec))
    arrays = [
        jax.device_put(flat[idx], device)
        for device, idx in sharding.addressable_devices_indices_map(shape).items()
    ]
    return jax.make_array_from_single_device_arrays(shape, sharding, arrays)


def pad_to_multiple(arr, multiple: int, axis: int = 0, fill=0):
    """Pad a batch axis so it divides the mesh (XLA needs even shards); returns
    (padded, original_length)."""
    n = arr.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return arr, n
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, rem)
    return np.pad(np.asarray(arr), widths, constant_values=fill), n
