from .raw_feature_filter import (
    FeatureDistribution,
    RawFeatureFilter,
    RawFeatureFilterResults,
)

__all__ = ["RawFeatureFilter", "FeatureDistribution", "RawFeatureFilterResults"]
