"""RawFeatureFilter: pre-modeling raw-data QA and automatic feature exclusion.

TPU-native analog of the reference RawFeatureFilter (core/src/main/scala/com/salesforce/
op/filters/RawFeatureFilter.scala:90-135 ctor+thresholds, :482 generateFilteredRaw;
FeatureDistribution.scala:58; results RawFeatureFilterResults.scala:50-135; workflow
wiring OpWorkflow.scala:524-563). It inspects RAW feature columns — before any
vectorization — on the training set and (optionally) a scoring set, and blacklists
features whose distributions say they will hurt the model:

  - fill rate below `min_fill_rate`                           (mostly-missing)
  - |train fill - scoring fill| above `max_fill_difference`   (serving skew)
  - fill ratio above `max_fill_ratio_diff`                    (serving skew)
  - train/scoring Jensen-Shannon divergence above
    `max_js_divergence` (log2: bounded [0, 1])                (distribution drift)
  - |corr(null-indicator, label)| above `max_correlation`     (missingness leaks label)

The reference computes per-partition FeatureDistribution monoids and reduces them over
the RDD; here histograms are jnp bincount/histogram passes (device reduction — psum'd
when rows are sharded) and the decision logic is host-side. Text-like features are
summarized by hashing values into a fixed bucket space (the text-hash distribution of
FeatureDistribution.scala), numerics by fixed-edge histograms from the training range.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ..types import Column, Storage
from ..types.table import Table

_EPS = 1e-12


def _stable_hash(s: str) -> int:
    """Process-independent string hash: persisted FeatureDistribution buckets must be
    comparable across runs (python hash() is salted per process; the reference uses
    MurmurHash3 for the same reason)."""
    import zlib

    return zlib.crc32(s.encode("utf-8"))


def _js_divergence(p: np.ndarray, q: np.ndarray) -> float:
    """Jensen-Shannon divergence (log base 2 -> [0, 1]) between two count vectors.

    Degenerate inputs are guarded to 0.0: empty vectors, mismatched lengths,
    and all-zero or non-finite-sum counts (a feature 100% missing in one of
    the two tables yields an all-zero histogram; NaN counts would otherwise
    propagate a NaN total). Missingness itself is the fill-rate checks' job —
    "no mass observed" carries no distribution-shape evidence."""
    p = np.asarray(p, np.float64)
    q = np.asarray(q, np.float64)
    if p.size == 0 or p.shape != q.shape:
        return 0.0
    ps, qs = float(p.sum()), float(q.sum())
    if not (np.isfinite(ps) and np.isfinite(qs)) or ps <= 0 or qs <= 0:
        return 0.0
    p = p / ps
    q = q / qs
    m = 0.5 * (p + q)

    def kl(a, b):
        mask = a > _EPS
        return float((a[mask] * np.log2(a[mask] / np.maximum(b[mask], _EPS))).sum())

    return 0.5 * kl(p, m) + 0.5 * kl(q, m)


@dataclass
class FeatureDistribution:
    """Summary of one raw feature's values (FeatureDistribution.scala:58): presence
    counts plus a histogram — numeric bins over the training range, or hashed-value
    buckets for text-like features."""

    name: str
    kind: str
    count: int
    null_count: int
    histogram: np.ndarray
    #: numeric features: bin edges shared between train/scoring so JS is comparable
    bin_edges: Optional[np.ndarray] = None

    @property
    def fill_rate(self) -> float:
        return 1.0 - self.null_count / max(self.count, 1)

    def js_divergence(self, other: "FeatureDistribution") -> float:
        if len(self.histogram) != len(other.histogram) or self.histogram.sum() == 0 \
                or other.histogram.sum() == 0:
            return 0.0
        return _js_divergence(np.asarray(self.histogram, np.float64),
                              np.asarray(other.histogram, np.float64))

    def to_json(self) -> dict:
        return {
            "name": self.name, "kind": self.kind, "count": self.count,
            "null_count": self.null_count, "fill_rate": self.fill_rate,
            "histogram": np.asarray(self.histogram).tolist(),
        }


@dataclass
class RawFeatureFilterResults:
    """What was computed and decided (RawFeatureFilterResults.scala:50-135)."""

    train_distributions: dict = field(default_factory=dict)
    scoring_distributions: dict = field(default_factory=dict)
    excluded: list = field(default_factory=list)  # {"name", "reason"}
    config: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "train_distributions": {k: d.to_json() for k, d in self.train_distributions.items()},
            "scoring_distributions": {k: d.to_json() for k, d in self.scoring_distributions.items()},
            "excluded": list(self.excluded),
            "config": dict(self.config),
        }

    def pretty(self) -> str:
        lines = [f"RawFeatureFilter: {len(self.excluded)} raw features excluded"]
        for e in self.excluded:
            lines.append(f"  - {e['name']}: {e['reason']}")
        return "\n".join(lines)


class RawFeatureFilter:
    """Configure thresholds, attach with `workflow.with_raw_feature_filter(rff)`
    (defaults mirror OpWorkflow.scala:527-538)."""

    def __init__(self, scoring_reader=None, bins: int = 100,
                 min_fill_rate: float = 0.001, max_fill_difference: float = 0.90,
                 max_fill_ratio_diff: float = 20.0, max_js_divergence: float = 0.90,
                 max_correlation: float = 0.95,
                 protected_features: Sequence[str] = ()):
        self.scoring_reader = scoring_reader
        self.bins = int(bins)
        self.min_fill_rate = float(min_fill_rate)
        self.max_fill_difference = float(max_fill_difference)
        self.max_fill_ratio_diff = float(max_fill_ratio_diff)
        self.max_js_divergence = float(max_js_divergence)
        self.max_correlation = float(max_correlation)
        self.protected_features = frozenset(protected_features)
        self.results_: Optional[RawFeatureFilterResults] = None

    # --- distribution computation ---------------------------------------------------
    def _distribution(self, feature, col: Column,
                      train_dist: Optional[FeatureDistribution] = None) -> FeatureDistribution:
        n = len(col)
        mask = np.asarray(col.effective_mask())
        null_count = int((~mask).sum())
        st = col.kind.storage
        hist = np.zeros(self.bins, np.float64)
        edges = None
        if st in (Storage.REAL, Storage.INTEGRAL, Storage.DATE, Storage.BINARY):
            vals = np.asarray(col.values, np.float64)[mask]
            if train_dist is not None and train_dist.bin_edges is not None:
                edges = train_dist.bin_edges  # scoring reuses training edges
            elif vals.size:
                lo, hi = float(vals.min()), float(vals.max())
                hi = hi if hi > lo else lo + 1.0
                edges = np.linspace(lo, hi, self.bins + 1)
            if edges is not None and vals.size:
                hist, _ = np.histogram(np.clip(vals, edges[0], edges[-1]), bins=edges)
                hist = hist.astype(np.float64)
        elif st in (Storage.TEXT, Storage.TEXT_LIST, Storage.TEXT_SET, Storage.MAP):
            # hashed-value buckets (text hash distribution of the reference)
            idx = []
            for v, m in zip(col.values, mask):
                if not m:
                    continue
                if st is Storage.TEXT:
                    idx.append(_stable_hash(v) % self.bins)
                elif st is Storage.MAP:
                    idx.extend(_stable_hash(k) % self.bins for k in v)
                else:
                    idx.extend(_stable_hash(t) % self.bins for t in v)
            if idx:
                hist = np.bincount(np.asarray(idx), minlength=self.bins).astype(np.float64)
        # other storages (vector/geolocation/prediction): fill rate only
        return FeatureDistribution(
            name=feature.name, kind=col.kind.name, count=n, null_count=null_count,
            histogram=hist, bin_edges=edges,
        )

    def compute_distributions(self, features, table: Table,
                              train: Optional[dict] = None) -> dict:
        out = {}
        for f in features:
            if f.is_response:
                continue
            ref = None if train is None else train.get(f.name)
            out[f.name] = self._distribution(f, table[f.name], ref)
        return out

    # --- decision + workflow hook -----------------------------------------------------
    def filter_raw(self, raw_features, train_table: Table):
        """-> (train_table, blacklisted features). Called by Workflow.train()
        (generateFilteredRaw, RawFeatureFilter.scala:482)."""
        train_dists = self.compute_distributions(raw_features, train_table)
        scoring_dists: dict = {}
        if self.scoring_reader is not None:
            predictors = [f for f in raw_features if not f.is_response]
            scoring_table = self.scoring_reader.generate_table(list(predictors))
            scoring_dists = self.compute_distributions(predictors, scoring_table,
                                                       train=train_dists)

        label = next((f for f in raw_features if f.is_response), None)
        y = None
        if label is not None and label.name in train_table.columns:
            lcol = train_table[label.name]
            if lcol.kind.on_device:
                y = np.asarray(lcol.filled(0.0), np.float32)

        reasons: dict[str, str] = {}
        for f in raw_features:
            if f.is_response or f.name in self.protected_features:
                continue
            d = train_dists[f.name]
            if d.fill_rate < self.min_fill_rate:
                reasons[f.name] = (f"fill rate {d.fill_rate:.4f} < min_fill_rate "
                                   f"{self.min_fill_rate}")
                continue
            if y is not None:
                null_ind = 1.0 - np.asarray(train_table[f.name].effective_mask(), np.float32)
                if null_ind.std() > 0 and y.std() > 0:
                    corr = float(np.corrcoef(null_ind, y)[0, 1])
                    if abs(corr) > self.max_correlation:
                        reasons[f.name] = (
                            f"null-indicator/label correlation {abs(corr):.3f} > "
                            f"max_correlation {self.max_correlation}")
                        continue
            if f.name in scoring_dists:
                s = scoring_dists[f.name]
                fill_diff = abs(d.fill_rate - s.fill_rate)
                if fill_diff > self.max_fill_difference:
                    reasons[f.name] = (f"train/scoring fill difference {fill_diff:.3f} > "
                                       f"max_fill_difference {self.max_fill_difference}")
                    continue
                ratio = (max(d.fill_rate, s.fill_rate)
                         / max(min(d.fill_rate, s.fill_rate), _EPS))
                if ratio > self.max_fill_ratio_diff:
                    reasons[f.name] = (f"train/scoring fill ratio {ratio:.1f} > "
                                       f"max_fill_ratio_diff {self.max_fill_ratio_diff}")
                    continue
                js = d.js_divergence(s)
                if js > self.max_js_divergence:
                    reasons[f.name] = (f"train/scoring JS divergence {js:.3f} > "
                                       f"max_js_divergence {self.max_js_divergence}")

        # attach the computed distributions to the Feature objects themselves so
        # downstream insights can read them off the lineage (the reference's
        # FeatureLike.distributions, FeatureLike.scala:48-103)
        for f in raw_features:
            dists = []
            if f.name in train_dists:
                dists.append(("train", train_dists[f.name]))
            if f.name in scoring_dists:
                dists.append(("scoring", scoring_dists[f.name]))
            f.distributions = tuple(dists)

        self.results_ = RawFeatureFilterResults(
            train_distributions=train_dists,
            scoring_distributions=scoring_dists,
            excluded=[{"name": n, "reason": r} for n, r in reasons.items()],
            config={
                "bins": self.bins, "min_fill_rate": self.min_fill_rate,
                "max_fill_difference": self.max_fill_difference,
                "max_fill_ratio_diff": self.max_fill_ratio_diff,
                "max_js_divergence": self.max_js_divergence,
                "max_correlation": self.max_correlation,
            },
        )
        blacklisted = tuple(f for f in raw_features if f.name in reasons)
        return train_table, blacklisted
