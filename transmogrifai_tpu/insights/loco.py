"""RecordInsightsLOCO: per-row leave-one-column-out explanations.

TPU-native analog of RecordInsightsLOCO (reference core/src/main/scala/com/salesforce/
op/stages/impl/insights/RecordInsightsLOCO.scala:62-112): for each slot of the feature
vector, re-score the row with that slot zeroed and report the score delta. The
reference walks slots in a Scala loop with top-K heaps per row; here ALL slot
perturbations are ONE vmapped re-scoring batch — a [D, N, D] masked sweep the compiler
tiles onto the MXU (SURVEY §2.11f: "batch the perturbations — TPU-friendly") — and the
top-K selection is jax.lax.top_k over the slot axis.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..stages.base import Transformer, register_stage
from ..types import Column, kind_of


#: memory cap for the auto-derived slot chunk: the sweep materializes
#: [slot_batch, N, D] masked copies of X in f32
_LOCO_SWEEP_BYTES = 1 << 28  # 256 MB


def loco_deltas(predict_fn, X: jnp.ndarray, slot_batch: int = 0) -> jnp.ndarray:
    """Score deltas [N, D] for zeroing each slot: base_score - masked_score, taken on
    probability of the predicted class (binary: class 1; regression: the value).

    predict_fn: X -> (pred, raw, prob). slot_batch > 0 chunks the vmap over
    slots to bound memory at [slot_batch, N, D]; slot_batch == 0 (default)
    AUTO-derives the chunk from the vector width so a wide vector cannot OOM
    (the full [D, N, D] sweep is 256 GB at N=64k, D=1k — ADVICE r04): the
    largest slot chunk whose masked-copy tensor stays under ~256 MB."""
    X = jnp.asarray(X, jnp.float32)
    n, d = X.shape
    if not slot_batch:
        slot_batch = max(1, min(d, _LOCO_SWEEP_BYTES // max(n * d * 4, 1)))
        if slot_batch == d:
            slot_batch = 0  # whole sweep fits: single vmap, no chunk loop
    base_pred, _, base_prob = predict_fn(X)
    c = base_prob.shape[1]
    if c == 1:
        score_col = jnp.zeros(n, jnp.int32)  # regression: the value
    elif c == 2:
        score_col = jnp.ones(n, jnp.int32)  # binary: positive-class prob
    else:
        # multiclass: each row's delta is on ITS predicted class's probability
        score_col = jnp.asarray(base_pred, jnp.int32)
    rows = jnp.arange(n)

    def masked_score(slot):
        Xm = X * (1.0 - jax.nn.one_hot(slot, d)[None, :])
        _, _, prob = predict_fn(Xm)
        return prob[rows, score_col]

    slots = jnp.arange(d)
    if slot_batch and slot_batch < d:
        # pad the slot axis to a multiple of slot_batch so every chunk shares
        # ONE compiled shape (a ragged tail would re-trace/re-compile the whole
        # vmapped predict graph); pad slots mask a real column, their rows are
        # sliced off below
        pad = (-d) % slot_batch
        slots_p = jnp.concatenate([slots, jnp.zeros(pad, slots.dtype)])
        chunks = [
            jax.vmap(masked_score)(slots_p[i: i + slot_batch])
            for i in range(0, d + pad, slot_batch)
        ]
        masked = jnp.concatenate(chunks, axis=0)[:d]  # [D, N]
    else:
        masked = jax.vmap(masked_score)(slots)
    return base_prob[rows, score_col][:, None] - masked.T  # [N, D]


@register_stage
class RecordInsightsLOCO(Transformer):
    """Transformer `(features OPVector, prediction Prediction) -> Text` producing a
    JSON explanation per row: top-K (slot name, delta) by |delta|.

    Wired AFTER a fitted model stage; it re-uses the model's predict kernel, so the
    whole sweep stays on device. The output mirrors RecordInsightsParser's format."""

    operation_name = "loco"
    arity = (2, 2)

    def __init__(self, top_k: int = 20, slot_batch: int = 0):
        super().__init__(top_k=int(top_k), slot_batch=int(slot_batch))
        self.model = None  # fitted PredictionModel, injected via for_model

    @classmethod
    def for_model(cls, model, top_k: int = 20, slot_batch: int = 0) -> "RecordInsightsLOCO":
        stage = cls(top_k=top_k, slot_batch=slot_batch)
        stage.model = model
        return stage

    def out_kind(self, in_kinds):
        if in_kinds[0].name != "OPVector":
            raise TypeError("LOCO first input must be the feature vector")
        return kind_of("Text")

    def is_response_out(self) -> bool:
        return False

    def transform_columns(self, cols: Sequence[Column]) -> Column:
        import json

        if self.model is None:
            raise ValueError("RecordInsightsLOCO needs a fitted model: use for_model()")
        vec = cols[0]
        X = jnp.asarray(vec.values, jnp.float32)
        deltas = loco_deltas(self.model.predict, X, self.params["slot_batch"])
        k = min(self.params["top_k"], X.shape[1])
        # inert width-bucketing pad slots carry zero signal by construction —
        # they must never be NAMED in a per-row explanation (ranked below every
        # real slot and filtered from the emitted entries)
        pad = (np.array([s.is_padding for s in vec.schema], bool)
               if vec.schema is not None else np.zeros(X.shape[1], bool))
        ranked = jnp.where(jnp.asarray(pad)[None, :], -1.0, jnp.abs(deltas))
        top_vals, top_idx = jax.lax.top_k(ranked, k)
        # one fused fetch (two serial np.asarray calls = two tunnel round trips)
        top_idx, deltas_np = jax.device_get((top_idx, deltas))
        names = (
            vec.schema.column_names()
            if vec.schema is not None
            else [f"f{i}" for i in range(X.shape[1])]
        )
        out = np.empty(X.shape[0], dtype=object)
        for i in range(X.shape[0]):
            out[i] = json.dumps(
                [
                    {"name": names[j], "delta": round(float(deltas_np[i, j]), 6)}
                    for j in top_idx[i] if not pad[j]
                ]
            )
        return Column(kind_of("Text"), out, None)
