"""RecordInsightsCorr: correlation-based per-row explanations.

TPU-native analog of RecordInsightsCorr (reference core/src/main/scala/com/salesforce/
op/stages/impl/insights/RecordInsightsCorr.scala): fit learns each vector slot's
Pearson correlation with the prediction score in ONE X^T-style fused pass (a matmul —
no per-slot loops); each row's insight for a slot is then `slot_value_centered * corr`,
and the transform emits the same top-K JSON format as RecordInsightsLOCO so
RecordInsightsParser-style consumers handle both.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..stages.base import Estimator, Transformer, register_stage
from ..types import Column, kind_of


@jax.jit
def slot_score_correlations(X: jnp.ndarray, score: jnp.ndarray):
    """Per-slot Pearson corr with the score: one centered matmul pass -> ([D], [D])."""
    X = jnp.asarray(X, jnp.float32)
    s = jnp.asarray(score, jnp.float32)
    n = X.shape[0]
    xm = X.mean(axis=0)
    sm = s.mean()
    xc = X - xm[None, :]
    sc = s - sm
    cov = xc.T @ sc / jnp.maximum(n - 1, 1)                      # [D]
    xstd = jnp.sqrt(jnp.maximum((xc ** 2).sum(axis=0) / jnp.maximum(n - 1, 1), 1e-12))
    sstd = jnp.sqrt(jnp.maximum((sc ** 2).sum() / jnp.maximum(n - 1, 1), 1e-12))
    return cov / (xstd * sstd), xm


def _score_of(pred_col: Column) -> jnp.ndarray:
    prob = pred_col.prob
    if prob.shape[1] > 1:
        return prob[:, 1] if prob.shape[1] == 2 else prob.max(axis=1)
    return pred_col.pred


@register_stage
class RecordInsightsCorr(Estimator):
    """Estimator `(features OPVector, prediction Prediction) -> Text` JSON insights."""

    operation_name = "insightsCorr"
    arity = (2, 2)

    def __init__(self, top_k: int = 20):
        super().__init__(top_k=int(top_k))

    def out_kind(self, in_kinds):
        if in_kinds[0].name != "OPVector":
            raise TypeError("RecordInsightsCorr first input must be the feature vector")
        return kind_of("Text")

    def is_response_out(self) -> bool:
        return False

    def fit_columns(self, cols: Sequence[Column]):
        vec, pred = cols
        corr, means = slot_score_correlations(
            jnp.asarray(vec.values, jnp.float32), _score_of(pred)
        )
        names = (vec.schema.column_names() if vec.schema is not None
                 else [f"f{i}" for i in range(vec.values.shape[1])])
        return RecordInsightsCorrModel(
            correlations=np.asarray(corr).tolist(),
            means=np.asarray(means).tolist(),
            names=list(names),
            top_k=self.params["top_k"],
        )


@register_stage
class RecordInsightsCorrModel(Transformer):
    operation_name = "insightsCorr"
    arity = (2, 2)

    def out_kind(self, in_kinds):
        return kind_of("Text")

    def is_response_out(self) -> bool:
        return False

    def transform_columns(self, cols: Sequence[Column]) -> Column:
        import json

        p = self.params
        X = np.asarray(cols[0].values, np.float32)
        corr = np.nan_to_num(np.asarray(p["correlations"], np.float32))
        means = np.asarray(p["means"], np.float32)
        contrib = (X - means[None, :]) * corr[None, :]           # [N, D]
        k = min(p["top_k"], X.shape[1])
        top_idx = np.argsort(-np.abs(contrib), axis=1)[:, :k]
        out = np.empty(X.shape[0], dtype=object)
        for i in range(X.shape[0]):
            out[i] = json.dumps([
                {"name": p["names"][j], "corr": round(float(corr[j]), 6),
                 "contribution": round(float(contrib[i, j]), 6)}
                for j in top_idx[i]
            ])
        return Column(kind_of("Text"), out, None)
