from .corr import RecordInsightsCorr, slot_score_correlations
from .loco import RecordInsightsLOCO, loco_deltas
from .model_insights import FeatureInsight, ModelInsights, model_insights
from .parser import (
    RecordInsight,
    dump_record_insights,
    parse_insights_column,
    parse_record_insights,
)

__all__ = [
    "ModelInsights",
    "FeatureInsight",
    "model_insights",
    "RecordInsightsLOCO",
    "RecordInsightsCorr",
    "RecordInsight",
    "slot_score_correlations",
    "loco_deltas",
    "parse_record_insights",
    "parse_insights_column",
    "dump_record_insights",
]
