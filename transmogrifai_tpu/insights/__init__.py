from .corr import RecordInsightsCorr, slot_score_correlations
from .loco import RecordInsightsLOCO, loco_deltas
from .model_insights import FeatureInsight, ModelInsights, model_insights

__all__ = [
    "ModelInsights",
    "FeatureInsight",
    "model_insights",
    "RecordInsightsLOCO",
    "RecordInsightsCorr",
    "slot_score_correlations",
    "loco_deltas",
]
