"""ModelInsights: the full training report assembled from the fitted DAG.

TPU-native analog of reference ModelInsights (core/src/main/scala/com/salesforce/op/
ModelInsights.scala:72-391) and OpWorkflowModel.summaryPretty (OpWorkflowModel.scala:
195-217). The report is assembled by walking the fitted stages the same way the
reference walks DataFrame metadata: SanityChecker summaries supply per-slot statistics,
the ModelSelector summary supplies validation history and the winning model, and the
winner's parameters supply per-slot contributions.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    from ..graph.feature import Feature
    from ..workflow.workflow import WorkflowModel


@dataclass
class SlotInsight:
    """One vector slot's derived statistics (analog of the reference's Insights per
    derived feature)."""

    slot_name: str
    corr_with_label: Optional[float] = None
    variance: Optional[float] = None
    mean: Optional[float] = None
    cramers_v: Optional[float] = None
    #: PMI (bits) of this indicator with each label value (OpStatistics
    #: pointwiseMutualInfo row; label order = the checker group's labels list)
    pmi_with_label: Optional[list] = None
    contribution: Optional[float] = None
    dropped_reason: Optional[str] = None

    def to_json(self) -> dict:
        return {k: v for k, v in vars(self).items() if v is not None} | {
            "slot_name": self.slot_name
        }


@dataclass
class FeatureInsight:
    """All derived slots of one raw feature (ModelInsights.features entries)."""

    feature_name: str
    kind: str
    derived: list[SlotInsight] = field(default_factory=list)
    #: RawFeatureFilter distributions read off the feature lineage
    #: (FeatureLike.distributions analog): {"train": {...}, "scoring": {...}}
    distributions: dict = field(default_factory=dict)

    @property
    def max_contribution(self) -> Optional[float]:
        vals = [s.contribution for s in self.derived if s.contribution is not None]
        return max(vals) if vals else None

    def to_json(self) -> dict:
        return {
            "feature_name": self.feature_name,
            "kind": self.kind,
            "derived": [s.to_json() for s in self.derived],
            "distributions": self.distributions,
        }


@dataclass
class ModelInsights:
    label_name: str
    label_kind: str
    problem_type: Optional[str] = None
    features: list[FeatureInsight] = field(default_factory=list)
    selected_model: Optional[dict] = None       # ModelSelectorSummary.to_json()
    sanity_checker: Optional[dict] = None       # SanityCheckerSummary.to_json()
    blacklisted: list[str] = field(default_factory=list)
    stages: list[dict] = field(default_factory=list)  # uid/op per fitted stage

    def to_json(self) -> dict:
        return {
            "label": {"name": self.label_name, "kind": self.label_kind},
            "problem_type": self.problem_type,
            "features": [f.to_json() for f in self.features],
            "selected_model": self.selected_model,
            "sanity_checker": self.sanity_checker,
            "blacklisted": list(self.blacklisted),
            "stages": list(self.stages),
        }

    def write(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_json(), fh, indent=1)

    def pretty(self) -> str:
        """Human-readable report (analog of ModelInsights.prettyPrint /
        summaryPretty)."""
        lines = [f"Label: {self.label_name} ({self.label_kind})"]
        if self.selected_model:
            sm = self.selected_model
            lines.append(
                f"Selected model: {sm.get('best_model_name')} {sm.get('best_params')}"
            )
            lines.append(
                f"Validation: {sm.get('validation_type')} on {sm.get('metric_name')}, "
                f"{sm.get('models_evaluated')} models evaluated"
            )
            hm = sm.get("holdout_metrics")
            if hm:
                metrics = ", ".join(f"{k}={v:.4f}" for k, v in hm.items()
                                    if isinstance(v, (int, float)))
                lines.append(f"Holdout: {metrics}")
        if self.blacklisted:
            lines.append(f"Blacklisted raw features: {', '.join(self.blacklisted)}")
        if self.sanity_checker:
            dropped = self.sanity_checker.get("dropped", [])
            lines.append(f"SanityChecker dropped {len(dropped)} slots")
        ranked = sorted(
            (f for f in self.features if f.max_contribution is not None),
            key=lambda f: -(f.max_contribution or 0.0),
        )
        if ranked:
            from ..utils.table import pretty_table

            lines.append(pretty_table(
                [[f.feature_name, f.kind, f.max_contribution] for f in ranked[:20]],
                headers=["feature", "kind", "max contribution"],
                title="Top feature contributions:"))
        return "\n".join(lines)


def _slot_parent(slot_name: str, raw_names: list[str]) -> Optional[str]:
    """Longest raw-feature-name prefix match (slot names are built as
    '<parent>[_<indicator>]')."""
    best = None
    for rn in raw_names:
        if slot_name == rn or slot_name.startswith(rn + "_"):
            if best is None or len(rn) > len(best):
                best = rn
    return best


def _contributions(stage, n_slots: int) -> Optional[np.ndarray]:
    """Per-slot contribution from a fitted model's parameters: |w| for linear-family
    models (norm over classes for multiclass), gain-style importances for trees if
    the stage exposes them."""
    imp = getattr(stage, "feature_importances_", None)
    if imp is not None:
        arr = np.asarray(imp, np.float64).ravel()
        return _crop_padding(arr, n_slots)
    w = stage.params.get("w") if hasattr(stage, "params") else None
    if w is None:
        return None
    arr = np.abs(np.asarray(w, np.float64))
    if arr.ndim == 2:  # [C, D] multiclass (LinearParams layout) -> per-slot max
        arr = arr.max(axis=0)
    return _crop_padding(arr, n_slots)


def _crop_padding(arr: np.ndarray, n_slots: int) -> Optional[np.ndarray]:
    """Width bucketing appends inert pad columns at the END whose contribution is
    exactly zero — crop ONLY that case. Any other size mismatch (unknown weight
    layout, upstream slot bug) must yield None, not misattributed contributions."""
    if arr.size == n_slots:
        return arr
    if arr.size > n_slots and not np.any(arr[n_slots:]):
        return arr[:n_slots]
    return None


def model_insights(model: "WorkflowModel", feature: "Feature") -> ModelInsights:
    """Build the report for one result feature of a fitted WorkflowModel
    (analog of OpWorkflowModel.modelInsights, OpWorkflowModel.scala:163)."""
    label = next((f for f in model.raw_features if f.is_response), None)
    report = ModelInsights(
        label_name=label.name if label else "",
        label_kind=label.kind.name if label else "",
        blacklisted=[f.name for f in model.blacklisted],
        stages=[{"uid": s.uid, "operation": s.operation_name} for s in model.stages],
    )

    # lineage of the requested feature, restricted to fitted stages
    lineage_ids = {id(f) for f in feature.all_features()}
    in_lineage = [s for s in model.stages
                  if s._output is not None and id(s.get_output()) in lineage_ids]

    selector_summary = None
    predictor = None
    for s in in_lineage:
        summ = getattr(s, "selector_summary", None)
        if summ is not None:
            selector_summary = summ
            predictor = s
        elif hasattr(s, "predict") and predictor is None:
            predictor = s
    if selector_summary is not None:
        report.selected_model = selector_summary.to_json()
        report.problem_type = selector_summary.problem_type

    checker_summary = None
    for s in in_lineage:
        summ = getattr(s, "summary_", None)
        if summ is not None and hasattr(summ, "slot_stats"):
            checker_summary = summ
    if checker_summary is not None:
        report.sanity_checker = checker_summary.to_json()

    # per-slot insights: stats from the checker, contributions from the winner
    raw_names = [f.name for f in model.raw_features if not f.is_response]
    slots: dict[str, SlotInsight] = {}
    surviving: list[str] = []
    if checker_summary is not None:
        dropped = {d["name"]: d["reason"] for d in checker_summary.dropped}
        for st in checker_summary.slot_stats:
            slots[st.name] = SlotInsight(
                slot_name=st.name,
                corr_with_label=st.corr_with_label,
                variance=st.variance,
                mean=st.mean,
                cramers_v=st.cramers_v,
                pmi_with_label=getattr(st, "pmi_with_label", None),
                dropped_reason=dropped.get(st.name),
            )
            if st.name not in dropped:
                surviving.append(st.name)
    if predictor is not None and surviving:
        contrib = _contributions(predictor, len(surviving))
        if contrib is not None:
            for name, c in zip(surviving, contrib):
                slots[name].contribution = float(c)

    by_feature: dict[str, FeatureInsight] = {}
    kind_by_name = {f.name: f.kind.name for f in model.raw_features}
    for name, insight in slots.items():
        parent = _slot_parent(name, raw_names) or name
        fi = by_feature.setdefault(
            parent, FeatureInsight(parent, kind_by_name.get(parent, "?")))
        fi.derived.append(insight)
    # fold in RawFeatureFilter distributions attached to the raw features
    for f in model.raw_features:
        dists = getattr(f, "distributions", ())
        if not dists:
            continue
        fi = by_feature.setdefault(f.name, FeatureInsight(f.name, f.kind.name))
        fi.distributions = {split: d.to_json() for split, d in dists}
    report.features = list(by_feature.values())
    return report
