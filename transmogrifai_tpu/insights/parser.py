"""RecordInsightsParser: typed access to per-row LOCO insight payloads.

Analog of the reference RecordInsightsParser (core/src/main/scala/com/salesforce/
op/stages/impl/insights/RecordInsightsParser.scala), which parses the LOCO
output map back into `OpVectorColumnHistory -> strength` pairs for consumers.
Here the LOCO stage (insights/loco.py) emits one JSON string per row — a list
of {"name", "delta"} ordered by |delta| — and this module parses it back into
typed records, optionally resolving each slot name against a VectorSchema so
consumers get the full SlotInfo provenance (parent feature, indicator,
multi-hop history) instead of a display string.
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Iterable, Optional

from ..types.vector_schema import SlotInfo, VectorSchema


@dataclass(frozen=True)
class RecordInsight:
    """One slot's contribution to one scored row (parsed LOCO entry)."""

    slot_name: str
    delta: float
    #: resolved provenance when a schema was supplied to the parser
    slot: Optional[SlotInfo] = None

    def to_json(self) -> dict:
        return {"name": self.slot_name, "delta": self.delta}


def parse_record_insights(
    payload: str, schema: Optional[VectorSchema] = None
) -> list[RecordInsight]:
    """Parse one row's LOCO JSON payload -> typed records, ordered as emitted
    (descending |delta|). With a schema, slot names resolve to SlotInfo —
    unknown names (schema drift) resolve to None rather than erroring."""
    by_name: dict[str, SlotInfo] = {}
    if schema is not None:
        for s in schema:
            by_name[s.column_name()] = s
    entries = json.loads(payload)
    if not isinstance(entries, list):
        raise ValueError(f"record insight payload must be a JSON list, "
                         f"got {type(entries).__name__}")
    out = []
    for e in entries:
        out.append(RecordInsight(
            slot_name=str(e["name"]),
            delta=float(e["delta"]),
            slot=by_name.get(str(e["name"])),
        ))
    return out


def parse_insights_column(
    column, schema: Optional[VectorSchema] = None
) -> list[list[RecordInsight]]:
    """Parse a whole LOCO Text column (Column or iterable of JSON strings)."""
    values: Iterable = (column.to_list() if hasattr(column, "to_list")
                        else column)
    return [parse_record_insights(v, schema) if v is not None else []
            for v in values]


def dump_record_insights(insights: Iterable[RecordInsight]) -> str:
    """Inverse of parse_record_insights (round-trip serialization)."""
    return json.dumps([r.to_json() for r in insights])
