"""Unfitted feature-graph <-> JSON round trip (analog of FeatureJsonHelper,
reference features/src/main/scala/com/salesforce/op/features/FeatureJsonHelper.scala:48-110).

The fitted path (`WorkflowModel.save/load`) persists trained transformers; this module
persists the *pipeline definition* — raw features plus the topologically ordered, still
UNFITTED stage graph — so a graph can be authored once (by hand or by `op codegen`),
saved as JSON, and trained later or elsewhere:

    spec = graph_to_json([pred])          # before any train()
    ...
    pred2 = graph_from_json(spec)[-1]
    Workflow().set_result_features(pred2).train(table=...)

Stage identity rides the same registry serialization model save/load uses
(`Stage.to_json`/`from_json`), so every `@register_stage` class round-trips here for
free; stages carrying live callables (LambdaTransformer over a local closure) have no
faithful JSON identity and are refused loudly at save time, exactly like the
serializability sanitizer does for model save (`utils/sanitize.check_serializable`).
"""
from __future__ import annotations

import json
import os
from typing import Sequence

from ..stages.base import STAGE_REGISTRY
from .builder import FeatureBuilder
from .dag import compute_dag, dag_stages, validate_dag
from .feature import Feature

GRAPH_JSON_VERSION = 1


def stage_payload(s) -> dict:
    """One stage's manifest entry: registry JSON + its output wiring. Shared by
    the fitted model manifest (WorkflowModel.save) and the unfitted graph here."""
    return {**s.to_json(), "output": s.get_output().name,
            "output_kind": s.get_output().kind.name}


def replay_manifest(manifest: dict):
    """Rebuild (features_by_name, raw_features, stages) from a manifest's
    raw_features + stages sections — THE wiring replay loop, shared by
    WorkflowModel.load (fitted) and graph_from_json (unfitted) so corrupt-input
    handling and name semantics cannot diverge."""
    from ..stages.base import Stage

    features: dict[str, Feature] = {}
    raw = []
    for rf in manifest["raw_features"]:
        fb = FeatureBuilder(rf["name"], rf["kind"])
        if rf.get("window_ms") is not None:
            fb = fb.window(rf["window_ms"])
        f = fb.as_response() if rf["is_response"] else fb.as_predictor()
        features[f.name] = f
        raw.append(f)
    stages = []
    for sj in manifest["stages"]:
        stage = Stage.from_json(sj)
        if "origin" in sj:
            stage.origin_class = sj["origin"]["class"]
            stage.origin_params = sj["origin"]["params"]
        missing = [n for n in sj["inputs"] if n not in features]
        if missing:
            raise ValueError(
                f"stage {sj['uid']} inputs {missing} are not produced by any "
                "earlier stage or raw feature — corrupt or reordered graph json"
            )
        out = stage.set_input(*[features[n] for n in sj["inputs"]])
        out.name = sj["output"]
        features[out.name] = out
        stages.append(stage)
    return features, raw, stages


def _check_json_faithful(stage, payload: dict) -> None:
    """Refuse stages whose JSON form cannot reconstruct them (callables and other
    objects `_jsonify` collapses to a bare name). Rebuilds through the same
    `Stage.from_json` dispatch load uses, then compares the clone's re-serialized
    form — covering subclass sections (ModelSelector's `search`) too."""
    from ..stages.base import Stage

    if payload["class"] not in STAGE_REGISTRY:
        raise TypeError(f"{stage} is not @register_stage'd; unfitted graphs can "
                        "only carry registry stages")
    try:
        clone = Stage.from_json(payload)
    except Exception as e:  # noqa: BLE001
        raise TypeError(
            f"{stage} cannot be serialized unfitted: it does not reconstruct from "
            f"its own to_json ({type(e).__name__}: {e}). Stages built over live "
            "callables (local lambdas/closures) have no JSON identity — use a "
            "registered stage class instead."
        ) from e
    wiring = ("inputs", "output", "output_kind")
    reserialized = {k: v for k, v in clone.to_json().items() if k not in wiring}
    original = {k: v for k, v in payload.items() if k not in wiring}
    if reserialized != original:
        raise TypeError(
            f"{stage} does not survive the JSON round trip — it bakes state "
            "(callables, live objects) that JSON cannot carry."
        )


def _check_raw_serializable(r: Feature) -> None:
    """Raw features carrying live callables (custom `.extract(fn)` or a monoid
    `.aggregate(...)` object) cannot round-trip: replaying a bare FeatureBuilder
    would silently fall back to `record.get(name)` / no aggregation and train a
    DIFFERENT model. Refuse at save time, same contract as lambda stages."""
    gen = r.origin_stage
    if gen is None:
        return
    if getattr(gen, "extract_fn", None) is not None:
        raise TypeError(
            f"raw feature {r.name!r} has a custom extract function — live "
            "callables have no JSON identity; restructure the extraction as a "
            "stage, or re-attach .extract(fn) after graph_from_json"
        )
    if getattr(gen, "aggregator", None) is not None:
        raise TypeError(
            f"raw feature {r.name!r} has a custom aggregator — aggregator objects "
            "are not serialized; re-attach .aggregate(...) after graph_from_json"
        )


def graph_to_json(result_features: Sequence[Feature]) -> dict:
    """Serialize the UNFITTED graph reachable from `result_features`.

    Raises TypeError for stages that cannot round-trip (live callables)."""
    if isinstance(result_features, Feature):
        result_features = [result_features]
    dag = compute_dag(result_features)
    validate_dag(dag)
    from .feature import validate_distinct_names

    # name-keyed serialization: two distinct features sharing a name would be
    # silently collapsed into one on reload — refuse loudly instead (the same
    # check train() runs, applied at authoring time)
    validate_distinct_names([a for f in result_features for a in f.all_features()])
    raw = []
    seen_raw: set[str] = set()
    for f in result_features:
        for r in f.raw_features():
            if r.name not in seen_raw:
                seen_raw.add(r.name)
                _check_raw_serializable(r)
                raw.append(r)
    stage_payloads = []
    for s in dag_stages(dag):
        payload = stage_payload(s)
        _check_json_faithful(s, payload)
        stage_payloads.append(payload)
    return {
        "version": GRAPH_JSON_VERSION,
        "fitted": False,
        "raw_features": [
            {"name": f.name, "kind": f.kind.name, "is_response": f.is_response,
             **({"window_ms": f.origin_stage.params["window_ms"]}
                if f.origin_stage is not None
                and f.origin_stage.params.get("window_ms") is not None else {})}
            for f in raw
        ],
        "result_features": [f.name for f in result_features],
        "stages": stage_payloads,
    }


def graph_from_json(data: dict) -> list[Feature]:
    """Rebuild the unfitted graph; returns the result features (same order as saved).
    The rebuilt features wire fresh stage instances restored from the registry, so the
    graph is immediately trainable: `Workflow().set_result_features(*loaded)`."""
    if data.get("version") != GRAPH_JSON_VERSION:
        raise ValueError(f"unsupported graph json version {data.get('version')!r}")
    features, _, _ = replay_manifest(data)
    return [features[n] for n in data["result_features"]]


def save_graph(path: str, result_features: Sequence[Feature],
               overwrite: bool = False) -> None:
    """Write the unfitted graph to a JSON file."""
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(f"{path} exists; pass overwrite=True")
    spec = graph_to_json(result_features)
    with open(path, "w") as fh:
        json.dump(spec, fh, indent=1)


def load_graph(path: str) -> list[Feature]:
    with open(path) as fh:
        return graph_from_json(json.load(fh))
