from .builder import FeatureBuilder, features_from_schema, features_from_table
from .dag import compute_dag, dag_stages, split_layer_by_kind, validate_dag
from .feature import Feature, FeatureCycleError, validate_distinct_names

__all__ = [
    "Feature",
    "FeatureCycleError",
    "FeatureBuilder",
    "features_from_schema",
    "features_from_table",
    "compute_dag",
    "dag_stages",
    "split_layer_by_kind",
    "validate_dag",
    "validate_distinct_names",
]
