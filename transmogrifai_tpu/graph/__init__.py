from .builder import FeatureBuilder, features_from_schema, features_from_table
from .dag import compute_dag, dag_stages, split_layer_by_kind, validate_dag
from .feature import Feature, FeatureCycleError, validate_distinct_names
from .json_helper import graph_from_json, graph_to_json, load_graph, save_graph

__all__ = [
    "graph_from_json",
    "graph_to_json",
    "load_graph",
    "save_graph",
    "Feature",
    "FeatureCycleError",
    "FeatureBuilder",
    "features_from_schema",
    "features_from_table",
    "compute_dag",
    "dag_stages",
    "split_layer_by_kind",
    "validate_dag",
    "validate_distinct_names",
]
