"""Lineage graph -> layered execution DAG.

Port of the *algorithm* (not code) of FitStagesUtil.computeDAG (reference
core/src/main/scala/com/salesforce/op/utils/stages/FitStagesUtil.scala:173-198): back-trace
from result features collecting each origin stage's MAX distance-to-sink, then group
stages into layers by distance (descending) so every stage runs after all its inputs.
Within a layer, stages are independent — a layer of device transformers is traced into
one XLA program; estimator layers are fit points.
"""
from __future__ import annotations

from typing import Sequence

from ..stages.base import Estimator, FeatureGeneratorStage, Stage, Transformer
from .feature import Feature


def compute_dag(result_features: Sequence[Feature]) -> list[list[Stage]]:
    """Layered DAG: layers[0] runs first (raw generators excluded — readers own them).

    Stages appearing on multiple paths get their maximum distance (dedup to the earliest
    layer they are needed in is handled by max-distance layering, exactly as the
    reference does)."""
    distance: dict[int, int] = {}
    stages: dict[int, Stage] = {}
    for f in result_features:
        for stage, d in f.parent_stages().items():
            sid = id(stage)
            if sid not in distance or distance[sid] < d:
                distance[sid] = d
                stages[sid] = stage
    if not stages:
        return []
    layers: dict[int, list[Stage]] = {}
    for sid, d in distance.items():
        st = stages[sid]
        if isinstance(st, FeatureGeneratorStage):
            continue
        layers.setdefault(d, []).append(st)
    # larger distance = further from sink = runs earlier
    return [layers[d] for d in sorted(layers, reverse=True)]


def dag_stages(dag: list[list[Stage]]) -> list[Stage]:
    return [s for layer in dag for s in layer]


def validate_dag(dag: list[list[Stage]]) -> None:
    """Uniqueness checks (analog of OpWorkflow.validateStages, OpWorkflow.scala:265-323).

    The check itself lives in the static analyzer as rule OP001
    (analyze/rules.py) — this raising wrapper keeps the historical
    fail-fast contract for graph construction and manifest replay."""
    from ..analyze.rules import check_dag_uniqueness  # lazy: analyze imports graph

    for d in check_dag_uniqueness(dag):
        raise ValueError(f"[{d.code}] {d.message}")


def label_tainted_features(dag: list[list[Stage]], raw_features: Sequence[Feature]) -> set[int]:
    """ids of features whose value depends on a response feature (directly or through
    any ancestor stage). The taint set drives the workflow-level-CV cut: estimators
    with tainted inputs must refit inside every validation fold (the reference's
    cutDAG 'during' stages, FitStagesUtil.scala:305-358)."""
    tainted: set[int] = {id(f) for f in raw_features if f.is_response}
    for layer in dag:
        for stage in layer:
            if any(id(p) in tainted for p in stage.inputs):
                out = stage.get_output()
                tainted.add(id(out))
    return tainted


def value_tainted_features(dag: list[list[Stage]],
                           raw_features: Sequence[Feature]) -> set[int]:
    """ids of features whose transform-time VALUES depend pointwise on a
    response. Unlike label_tainted_features (any dependence, including through
    fitted params — the fold-refit cut), taint here does NOT flow through a
    stage's declared `fit_only_inputs` (label slots read only during fit, e.g.
    DecisionTreeNumericBucketizer's inputs[0]): those influence what is
    learned, not the rows the fitted transform emits. The analyzer's OP302
    rule uses this to reject plans where the raw response literally lands in
    a predictor's design matrix."""
    tainted: set[int] = {id(f) for f in raw_features if f.is_response}
    for layer in dag:
        for stage in layer:
            fit_only = set(getattr(stage, "fit_only_inputs", ()) or ())
            if any(id(p) in tainted for i, p in enumerate(stage.inputs)
                   if i not in fit_only):
                tainted.add(id(stage.get_output()))
    return tainted


def in_fold_estimators(dag: list[list[Stage]], raw_features: Sequence[Feature],
                       selector: Stage) -> set[int]:
    """ids of pre-selector ESTIMATOR stages that consume label-tainted features and
    therefore leak label signal into validation folds unless refit per fold
    (reference OpWorkflowCVTest semantics; DecisionTreeNumericBucketizer and
    SanityChecker are the canonical cases)."""
    tainted = label_tainted_features(dag, raw_features)
    # only estimators topologically UPSTREAM of the selector's inputs can leak into
    # its folds; a tainted estimator downstream (e.g. insights consuming the
    # Prediction) must not trigger the expensive per-fold recomputation path
    upstream: set[int] = set()
    for inp in selector.inputs:
        upstream |= {id(s) for s in inp.parent_stages()}
    out: set[int] = set()
    for layer in dag:
        for stage in layer:
            if stage is selector or not isinstance(stage, Estimator):
                continue
            if id(stage) not in upstream:
                continue
            if any(id(p) in tainted for p in stage.inputs):
                out.add(id(stage))
    return out


def split_layer_by_kind(layer: Sequence[Stage]) -> tuple[list[Estimator], list[Transformer], list[Transformer]]:
    """Partition a layer into (estimators, device transformers, host transformers) —
    the unit structure of fitAndTransformLayer (FitStagesUtil.scala:254-293)."""
    estimators: list[Estimator] = []
    device_tf: list[Transformer] = []
    host_tf: list[Transformer] = []
    for s in layer:
        if isinstance(s, Estimator):
            estimators.append(s)
        elif isinstance(s, Transformer):
            (device_tf if s.device_op else host_tf).append(s)
        else:
            raise TypeError(f"stage {s} is neither Transformer nor Estimator")
    return estimators, device_tf, host_tf
