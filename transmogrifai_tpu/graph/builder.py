"""FeatureBuilder: typed construction of raw features.

Analog of reference FeatureBuilder (features/src/main/scala/com/salesforce/op/features/
FeatureBuilder.scala:230-319): `FeatureBuilder.Real["row_type"]("age").extract(fn)
.asPredictor` becomes `FeatureBuilder.Real("age").extract(fn).as_predictor()`; the macro
codegen extract path becomes plain Python callables; `fromDataFrame` becomes
`from_schema`/`from_table` (schema sniffing lives in readers.schema_inference).
"""
from __future__ import annotations

from typing import Any, Callable, Mapping, Optional

from ..types import KINDS, FeatureKind, Table, kind_of
from .feature import Feature


class FeatureBuilder:
    """Builder for one raw feature. Use `FeatureBuilder.<Kind>(name)` or
    `FeatureBuilder.of(name, kind)`."""

    def __init__(self, name: str, kind: FeatureKind | str):
        self.name = name
        self.kind = kind_of(kind) if isinstance(kind, str) else kind
        self._extract: Optional[Callable[[Any], Any]] = None
        self._aggregator = None
        self._window_ms: Optional[int] = None

    @staticmethod
    def of(name: str, kind: FeatureKind | str) -> "FeatureBuilder":
        return FeatureBuilder(name, kind)

    def extract(self, fn: Callable[[Any], Any]) -> "FeatureBuilder":
        """Record->value extractor (compile-time macro codegen in the reference,
        FeatureBuilderMacros.scala, becomes a plain callable)."""
        self._extract = fn
        return self

    def aggregate(self, aggregator) -> "FeatureBuilder":
        """Monoid aggregator used by aggregate readers to roll up multi-row entities
        (reference FeatureBuilder.aggregate, MonoidAggregatorDefaults)."""
        self._aggregator = aggregator
        return self

    def window(self, window_ms: int) -> "FeatureBuilder":
        """Time-window for aggregation (reference FeatureBuilder.window)."""
        self._window_ms = window_ms
        return self

    def _build(self, is_response: bool) -> Feature:
        # imported here, not at module top: stages.base itself imports graph.feature,
        # so a module-level import would make `import transmogrifai_tpu.stages` fail
        from ..stages.base import FeatureGeneratorStage

        stage = FeatureGeneratorStage(self.name, self.kind.name)
        stage.extract_fn = self._extract
        stage.aggregator = self._aggregator
        stage.params["window_ms"] = self._window_ms
        feature = stage.set_input()
        feature.is_response = is_response
        return feature

    def as_predictor(self) -> Feature:
        return self._build(is_response=False)

    def as_response(self) -> Feature:
        return self._build(is_response=True)


# FeatureBuilder.Real("age"), FeatureBuilder.PickList("sex"), ... for every kind
for _kind_name in KINDS:
    setattr(
        FeatureBuilder,
        _kind_name,
        staticmethod((lambda kn: lambda name: FeatureBuilder(name, kn))(_kind_name)),
    )


def features_from_schema(
    schema: Mapping[str, FeatureKind | str],
    response: Optional[str] = None,
) -> dict[str, Feature]:
    """Create raw features for every (name, kind) entry; `response` marks one of them
    as the response (analog of FeatureBuilder.fromDataFrame, FeatureBuilder.scala:230)."""
    out: dict[str, Feature] = {}
    for name, kind in schema.items():
        fb = FeatureBuilder(name, kind)
        out[name] = fb.as_response() if name == response else fb.as_predictor()
    if response is not None and response not in out:
        raise ValueError(f"response {response!r} not in schema {sorted(schema)}")
    return out


def features_from_table(table: Table, response: Optional[str] = None) -> dict[str, Feature]:
    """Raw features matching an existing Table's columns."""
    return features_from_schema({n: c.kind for n, c in table.items()}, response)
