"""Feature: a typed, lineage-carrying pointer to a (future) column.

TPU-native analog of FeatureLike/Feature (reference features/src/main/scala/com/salesforce/
op/features/FeatureLike.scala:48-103, Feature.scala:52). A Feature never holds data — it is
a node in the expression graph: (name, kind, origin stage, parents, is_response). The graph
rooted at result features is the compile target that lowers to XLA computations.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Iterable, Optional

from ..types import FeatureKind, kind_of
from ..utils import uid as make_uid

if TYPE_CHECKING:  # pragma: no cover
    from ..stages.base import Stage


class FeatureCycleError(Exception):
    """Raised when feature lineage contains a cycle
    (analog of FeatureCycleException.scala)."""


class Feature:
    __slots__ = ("name", "kind", "is_response", "origin_stage", "parents", "uid",
                 "distributions", "consumers")

    def __init__(
        self,
        name: str,
        kind: FeatureKind | str,
        *,
        is_response: bool = False,
        origin_stage: Optional["Stage"] = None,
        parents: tuple["Feature", ...] = (),
    ):
        self.name = name
        self.kind = kind_of(kind) if isinstance(kind, str) else kind
        self.is_response = is_response
        self.origin_stage = origin_stage
        self.parents = tuple(parents)
        self.uid = make_uid("Feature")
        #: FeatureDistributions attached by the RawFeatureFilter during train
        #: (analog of FeatureLike.distributions, FeatureLike.scala:48-103):
        #: tuple of (split-name, FeatureDistribution) for "train"/"scoring"
        self.distributions: tuple = ()
        #: WEAK references to stages wired onto this feature via set_input
        #: (the forward edges the lineage graph lacks); the analyzer's
        #: dead-stage rule (OP401) walks them. Weakrefs + opportunistic
        #: pruning keep long-lived processes that build many plans over
        #: shared raw features from retaining every abandoned plan's stages.
        #: Fitted models adopt wiring without registering, so only user-wired
        #: stages appear.
        self.consumers: list = []

    # --- identity is object identity; uid for serialization ---------------------------
    def __repr__(self) -> str:
        return f"Feature({self.name}: {self.kind.name})"

    @property
    def is_raw(self) -> bool:
        return not self.parents

    # --- lineage walks (analog of FeatureLike.rawFeatures / parentStages) -------------
    def raw_features(self) -> list["Feature"]:
        """All raw (leaf) ancestors, de-duplicated, in first-visit order."""
        seen: set[int] = set()
        out: list[Feature] = []
        stack = [self]
        while stack:
            f = stack.pop()
            if id(f) in seen:
                continue
            seen.add(id(f))
            if f.is_raw:
                out.append(f)
            else:
                stack.extend(reversed(f.parents))
        return out

    def parent_stages(self) -> dict["Stage", int]:
        """Origin stages with MAX distance from this feature (longest path), used to
        layer the DAG (analog of FeatureLike.parentStages). Linear in V+E even on
        diamond-shaped lineage: one DFS for cycle check + post-order, then a
        longest-path DP over the reverse post-order."""
        WHITE, GREY, BLACK = 0, 1, 2
        color: dict[int, int] = {}
        postorder: list[Feature] = []
        stack: list[tuple[Feature, bool]] = [(self, False)]
        while stack:
            f, done = stack.pop()
            fid = id(f)
            if done:
                color[fid] = BLACK
                postorder.append(f)
                continue
            state = color.get(fid, WHITE)
            if state != WHITE:
                continue  # duplicate push from a sibling branch
            color[fid] = GREY
            stack.append((f, True))
            for p in f.parents:
                pstate = color.get(id(p), WHITE)
                if pstate == GREY:
                    # GREY = on the current DFS path -> back edge -> cycle
                    raise FeatureCycleError(f"cycle through feature {p.name!r}")
                if pstate == WHITE:
                    stack.append((p, False))
        # reverse post-order = topological order from self toward the leaves
        depth: dict[int, int] = {id(self): 0}
        stages: dict[int, tuple["Stage", int]] = {}
        for f in reversed(postorder):
            d = depth.get(id(f), 0)
            if f.origin_stage is not None:
                sid = id(f.origin_stage)
                if sid not in stages or stages[sid][1] < d:
                    stages[sid] = (f.origin_stage, d)
            for p in f.parents:
                pid = id(p)
                if depth.get(pid, -1) < d + 1:
                    depth[pid] = d + 1
        return {stage: d for stage, d in stages.values()}

    def all_features(self) -> list["Feature"]:
        """Every feature in this feature's history (self included)."""
        seen: set[int] = set()
        out: list[Feature] = []
        stack = [self]
        while stack:
            f = stack.pop()
            if id(f) in seen:
                continue
            seen.add(id(f))
            out.append(f)
            stack.extend(f.parents)
        return out

    def lineage_ops(self) -> tuple[str, ...]:
        """Operation names of the stages between the raw ancestors and this
        feature, ancestor-first (the OpVectorColumnHistory stage-chain analog,
        OpVectorColumnMetadata.scala:67-204). Raw generator stages are elided;
        consecutive duplicates collapse (diamond lineage)."""
        ops: list[str] = []
        seen: set[int] = set()
        stack: list[tuple["Feature", bool]] = [(self, False)]
        while stack:
            f, done = stack.pop()
            if done:
                if (f.origin_stage is not None and not f.is_raw
                        and getattr(f.origin_stage, "operation_name", None)):
                    op = f.origin_stage.operation_name
                    if not ops or ops[-1] != op:
                        ops.append(op)
                continue
            if id(f) in seen:
                continue
            seen.add(id(f))
            stack.append((f, True))
            stack.extend((p, False) for p in f.parents)
        return tuple(ops)

    def pretty_lineage(self, indent: int = 0) -> str:
        """Human-readable lineage tree (analog of prettyParentStages)."""
        pad = "  " * indent
        op = self.origin_stage.operation_name if self.origin_stage else "raw"
        lines = [f"{pad}{self.name}: {self.kind.name} <- {op}"]
        for p in self.parents:
            lines.append(p.pretty_lineage(indent + 1))
        return "\n".join(lines)

    def history(self) -> dict:
        """JSON-able lineage record (analog of FeatureHistory)."""
        return {
            "name": self.name,
            "kind": self.kind.name,
            "is_response": self.is_response,
            "origin_stage": self.origin_stage.uid if self.origin_stage else None,
            "parents": [p.name for p in self.parents],
            "raw_features": [r.name for r in self.raw_features()],
        }


def validate_distinct_names(features: Iterable[Feature]) -> None:
    seen: dict[str, Feature] = {}
    for f in features:
        if f.name in seen and seen[f.name] is not f:
            raise ValueError(f"duplicate feature name {f.name!r} for distinct features")
        seen[f.name] = f
