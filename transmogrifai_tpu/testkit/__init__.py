"""testkit: seeded random typed-data generators for every feature kind.

TPU-native analog of the reference testkit module (testkit/src/main/scala/com/salesforce/
op/testkit/ — RandomReal.scala, RandomIntegral.scala, RandomBinary.scala, RandomText.scala,
RandomList.scala, RandomSet.scala, RandomMap.scala, RandomVector.scala, RandomData.scala,
ProbabilityOfEmpty.scala, InfiniteStream.scala). Generators are deterministic given a seed,
are conceptually infinite streams (`limit(n)` materializes a prefix), support
`with_probability_of_empty(p)`, and assemble into Tables via `random_data(...)`.
"""
from .generators import (
    RandomStream,
    RandomBinary,
    RandomGeolocation,
    RandomIntegral,
    RandomList,
    RandomMap,
    RandomMultiPickList,
    RandomReal,
    RandomText,
    RandomVector,
    random_data,
)

__all__ = [
    "RandomStream",
    "RandomBinary",
    "RandomGeolocation",
    "RandomIntegral",
    "RandomList",
    "RandomMap",
    "RandomMultiPickList",
    "RandomReal",
    "RandomText",
    "RandomVector",
    "random_data",
]
