"""Random typed-data generators (reference testkit/src/main/scala/com/salesforce/op/testkit/).

Each generator is an infinite, seed-deterministic stream of python values in the shape
`Column.build` expects for its feature kind (None = missing). `limit(n)` takes a prefix;
`with_probability_of_empty(p)` mirrors the reference's ProbabilityOfEmpty mixin
(ProbabilityOfEmpty.scala); `random_data` zips named streams into a Table the way
RandomData/StandardRandomData do.

Generators are *restartable*: each `limit`/iteration re-derives its rng from the seed, so
the same generator yields the same prefix every time (the reference achieves this with
reset-able scala Randoms seeded in the ctor).
"""
from __future__ import annotations

import string
from typing import Any, Callable, Iterator, Optional, Sequence

import numpy as np

from ..types import Column, Table, kind_of


class RandomStream:
    """Infinite seeded stream of typed values (reference InfiniteStream.scala).

    `producer(rng) -> value` draws one value; wrappers compose (empty-probability,
    mapping). The feature-kind name travels along so `random_data` can build Columns.
    """

    def __init__(self, kind_name: str, producer: Callable[[np.random.Generator], Any],
                 seed: int = 42):
        self.kind_name = kind_name
        self._factory = lambda: producer  # stateless producer reused across iterations
        self.seed = seed

    @classmethod
    def stateful(cls, kind_name: str,
                 factory: Callable[[], Callable[[np.random.Generator], Any]],
                 seed: int = 42) -> "RandomStream":
        """Stream whose producer carries per-iteration state (e.g. a date cursor);
        factory() is called at the start of every iteration, so `limit` stays
        deterministic and restartable."""
        s = cls(kind_name, lambda rng: None, seed)
        s._factory = factory
        return s

    @classmethod
    def _from_factory(cls, kind_name, factory, seed) -> "RandomStream":
        return cls.stateful(kind_name, factory, seed)

    # --- configuration (reference ProbabilityOfEmpty.scala) ---------------------------
    def with_probability_of_empty(self, p: float) -> "RandomStream":
        """Each drawn value is independently replaced by None with probability p."""
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"probability of empty must be in [0, 1], got {p}")
        inner_factory = self._factory

        def factory():
            inner = inner_factory()
            return lambda rng: None if rng.random() < p else inner(rng)

        return RandomStream._from_factory(self.kind_name, factory, self.seed)

    def with_seed(self, seed: int) -> "RandomStream":
        s = RandomStream(self.kind_name, lambda rng: None, seed)
        s._factory = self._factory
        return s

    def map(self, fn: Callable[[Any], Any], kind_name: Optional[str] = None) -> "RandomStream":
        inner_factory = self._factory

        def factory():
            inner = inner_factory()

            def produce(rng):
                v = inner(rng)
                return None if v is None else fn(v)

            return produce

        return RandomStream._from_factory(kind_name or self.kind_name, factory, self.seed)

    # --- consumption ------------------------------------------------------------------
    def __iter__(self) -> Iterator[Any]:
        rng = np.random.default_rng(self.seed)
        produce = self._factory()
        while True:
            yield produce(rng)

    def limit(self, n: int) -> list:
        """Materialize the first n values (reference `take(n)`); deterministic."""
        it = iter(self)
        return [next(it) for _ in range(n)]

    def column(self, n: int) -> Column:
        return Column.build(kind_of(self.kind_name), self.limit(n))


# --- numerics (reference RandomReal.scala) -----------------------------------------------
class RandomReal:
    """Factories for Real-family streams; kind defaults to Real (use kind= for
    Currency/Percent/RealNN)."""

    @staticmethod
    def normal(mean: float = 0.0, sigma: float = 1.0, kind: str = "Real",
               seed: int = 42) -> RandomStream:
        return RandomStream(kind, lambda rng: float(rng.normal(mean, sigma)), seed)

    @staticmethod
    def uniform(low: float = 0.0, high: float = 1.0, kind: str = "Real",
                seed: int = 42) -> RandomStream:
        return RandomStream(kind, lambda rng: float(rng.uniform(low, high)), seed)

    @staticmethod
    def poisson(lam: float = 1.0, kind: str = "Real", seed: int = 42) -> RandomStream:
        return RandomStream(kind, lambda rng: float(rng.poisson(lam)), seed)

    @staticmethod
    def exponential(scale: float = 1.0, kind: str = "Real", seed: int = 42) -> RandomStream:
        return RandomStream(kind, lambda rng: float(rng.exponential(scale)), seed)

    @staticmethod
    def gamma(shape: float = 2.0, scale: float = 1.0, kind: str = "Real",
              seed: int = 42) -> RandomStream:
        return RandomStream(kind, lambda rng: float(rng.gamma(shape, scale)), seed)

    @staticmethod
    def lognormal(mean: float = 0.0, sigma: float = 1.0, kind: str = "Real",
                  seed: int = 42) -> RandomStream:
        return RandomStream(kind, lambda rng: float(rng.lognormal(mean, sigma)), seed)

    @staticmethod
    def weibull(a: float = 1.5, kind: str = "Real", seed: int = 42) -> RandomStream:
        return RandomStream(kind, lambda rng: float(rng.weibull(a)), seed)


class RandomIntegral:
    """Reference RandomIntegral.scala: integers and date streams."""

    @staticmethod
    def integers(low: int = 0, high: int = 100, kind: str = "Integral",
                 seed: int = 42) -> RandomStream:
        return RandomStream(kind, lambda rng: int(rng.integers(low, high)), seed)

    @staticmethod
    def dates(start_ms: int = 1_500_000_000_000, max_step_ms: int = 86_400_000,
              kind: str = "Date", seed: int = 42) -> RandomStream:
        """Monotone timestamps: start + cumulative random steps (reference
        RandomIntegral.dates). The cursor lives in per-iteration producer state, so
        every fresh iteration restarts the walk and `limit(n)` stays deterministic."""

        def factory():
            cursor = [start_ms]

            def produce(rng: np.random.Generator):
                cursor[0] += int(rng.integers(1, max_step_ms))
                return cursor[0]

            return produce

        return RandomStream.stateful(kind, factory, seed)


class RandomBinary:
    """Reference RandomBinary.scala."""

    @staticmethod
    def of(probability_of_true: float = 0.5, kind: str = "Binary",
           seed: int = 42) -> RandomStream:
        return RandomStream(kind, lambda rng: bool(rng.random() < probability_of_true), seed)


# --- text (reference RandomText.scala) ---------------------------------------------------
_DOMAINS = ("example.com", "sample.org", "test.net", "mail.io")
_COUNTRIES = ("USA", "Canada", "Mexico", "France", "Germany", "Japan", "Brazil")
_STATES = ("CA", "NY", "TX", "WA", "OR", "FL", "IL")
_CITIES = ("Springfield", "Rivertown", "Lakeside", "Hillview", "Georgetown")
_STREETS = ("Main St", "Oak Ave", "Pine Rd", "Maple Dr", "Cedar Ln")


def _rand_word(rng: np.random.Generator, lo: int = 3, hi: int = 10) -> str:
    n = int(rng.integers(lo, hi + 1))
    letters = rng.integers(0, 26, size=n)
    return "".join(string.ascii_lowercase[i] for i in letters)


class RandomText:
    """Factories for the Text family (reference RandomText.scala: strings, emails, urls,
    phones, postalCodes, ids, uniqueIds, picklists, comboBoxes, base64, countries,
    states, cities, streets, textAreas)."""

    @staticmethod
    def strings(min_words: int = 1, max_words: int = 5, kind: str = "Text",
                seed: int = 42) -> RandomStream:
        return RandomStream(
            kind,
            lambda rng: " ".join(
                _rand_word(rng) for _ in range(int(rng.integers(min_words, max_words + 1)))
            ),
            seed,
        )

    @staticmethod
    def text_areas(min_words: int = 5, max_words: int = 30, seed: int = 42) -> RandomStream:
        return RandomText.strings(min_words, max_words, kind="TextArea", seed=seed)

    @staticmethod
    def emails(domains: Sequence[str] = _DOMAINS, seed: int = 42) -> RandomStream:
        return RandomStream(
            "Email",
            lambda rng: f"{_rand_word(rng)}.{_rand_word(rng)}@"
                        f"{domains[int(rng.integers(0, len(domains)))]}",
            seed,
        )

    @staticmethod
    def urls(domains: Sequence[str] = _DOMAINS, seed: int = 42) -> RandomStream:
        return RandomStream(
            "URL",
            lambda rng: f"https://{domains[int(rng.integers(0, len(domains)))]}/"
                        f"{_rand_word(rng)}",
            seed,
        )

    @staticmethod
    def phones(seed: int = 42) -> RandomStream:
        return RandomStream(
            "Phone",
            lambda rng: "+1" + "".join(str(d) for d in rng.integers(0, 10, size=10)),
            seed,
        )

    @staticmethod
    def postal_codes(seed: int = 42) -> RandomStream:
        return RandomStream(
            "PostalCode",
            lambda rng: "".join(str(d) for d in rng.integers(0, 10, size=5)),
            seed,
        )

    @staticmethod
    def ids(seed: int = 42) -> RandomStream:
        return RandomStream("ID", lambda rng: f"id_{int(rng.integers(0, 10**9)):09d}", seed)

    @staticmethod
    def unique_ids(seed: int = 42) -> RandomStream:
        """Sequential unique ids (reference RandomText.uniqueIds): a random per-stream
        prefix plus a per-iteration counter, so ids are unique and monotone."""

        def factory():
            counter = [0]

            def produce(rng: np.random.Generator):
                if counter[0] == 0:
                    counter.append(int(rng.integers(0, 2**31)))  # stream prefix
                counter[0] += 1
                return f"uid_{counter[1]:010d}_{counter[0]:09d}"

            return produce

        return RandomStream.stateful("ID", factory, seed)

    @staticmethod
    def picklists(domain: Sequence[str], kind: str = "PickList",
                  seed: int = 42) -> RandomStream:
        if not domain:
            raise ValueError("picklists need a non-empty domain")
        return RandomStream(
            kind, lambda rng: domain[int(rng.integers(0, len(domain)))], seed
        )

    @staticmethod
    def combo_boxes(domain: Sequence[str], seed: int = 42) -> RandomStream:
        return RandomText.picklists(domain, kind="ComboBox", seed=seed)

    @staticmethod
    def base64(min_len: int = 8, max_len: int = 32, seed: int = 42) -> RandomStream:
        import base64 as b64

        def produce(rng: np.random.Generator):
            n = int(rng.integers(min_len, max_len + 1))
            return b64.b64encode(rng.bytes(n)).decode("ascii")

        return RandomStream("Base64", produce, seed)

    @staticmethod
    def countries(seed: int = 42) -> RandomStream:
        return RandomText.picklists(_COUNTRIES, kind="Country", seed=seed)

    @staticmethod
    def states(seed: int = 42) -> RandomStream:
        return RandomText.picklists(_STATES, kind="State", seed=seed)

    @staticmethod
    def cities(seed: int = 42) -> RandomStream:
        return RandomText.picklists(_CITIES, kind="City", seed=seed)

    @staticmethod
    def streets(seed: int = 42) -> RandomStream:
        return RandomText.picklists(_STREETS, kind="Street", seed=seed)


# --- collections (reference RandomList.scala, RandomSet.scala) ---------------------------
class RandomList:
    @staticmethod
    def of_texts(min_len: int = 0, max_len: int = 5, seed: int = 42) -> RandomStream:
        return RandomStream(
            "TextList",
            lambda rng: [_rand_word(rng) for _ in range(int(rng.integers(min_len, max_len + 1)))],
            seed,
        )

    @staticmethod
    def of_dates(start_ms: int = 1_500_000_000_000, max_step_ms: int = 3_600_000,
                 min_len: int = 0, max_len: int = 5, kind: str = "DateList",
                 seed: int = 42) -> RandomStream:
        def produce(rng: np.random.Generator):
            n = int(rng.integers(min_len, max_len + 1))
            steps = rng.integers(1, max_step_ms, size=n) if n else []
            return list(start_ms + np.cumsum(steps).astype(np.int64)) if n else []

        return RandomStream(kind, produce, seed)


class RandomMultiPickList:
    @staticmethod
    def of(domain: Sequence[str], min_len: int = 0, max_len: int = 3,
           seed: int = 42) -> RandomStream:
        if not domain:
            raise ValueError("multipicklists need a non-empty domain")

        def produce(rng: np.random.Generator):
            n = int(rng.integers(min_len, min(max_len, len(domain)) + 1))
            idx = rng.choice(len(domain), size=n, replace=False)
            return frozenset(domain[i] for i in idx)

        return RandomStream("MultiPickList", produce, seed)


# --- maps (reference RandomMap.scala) ----------------------------------------------------
class RandomMap:
    @staticmethod
    def of(value_stream: RandomStream, keys: Sequence[str], kind: Optional[str] = None,
           min_size: int = 1, seed: int = 42) -> RandomStream:
        """Map stream drawing each value from value_stream's producer; kind defaults to
        `<ValueKind>Map` (reference RandomMap.of)."""
        map_kind = kind or f"{value_stream.kind_name}Map"
        kind_of(map_kind)  # validate early
        inner_factory = value_stream._factory

        def factory():
            inner = inner_factory()

            def produce(rng: np.random.Generator):
                n = int(rng.integers(min_size, len(keys) + 1))
                idx = rng.choice(len(keys), size=n, replace=False)
                return {keys[i]: inner(rng) for i in sorted(idx)}

            return produce

        return RandomStream.stateful(map_kind, factory, seed)


# --- vectors / geolocation (reference RandomVector.scala, RandomList.ofGeolocations) -----
class RandomVector:
    @staticmethod
    def normal(dim: int, mean: float = 0.0, sigma: float = 1.0,
               seed: int = 42) -> RandomStream:
        return RandomStream(
            "OPVector",
            lambda rng: rng.normal(mean, sigma, size=dim).astype(np.float32),
            seed,
        )

    @staticmethod
    def dense(dim: int, low: float = 0.0, high: float = 1.0, seed: int = 42) -> RandomStream:
        return RandomStream(
            "OPVector",
            lambda rng: rng.uniform(low, high, size=dim).astype(np.float32),
            seed,
        )

    @staticmethod
    def sparse(dim: int, density: float = 0.1, seed: int = 42) -> RandomStream:
        def produce(rng: np.random.Generator):
            v = rng.normal(size=dim).astype(np.float32)
            return np.where(rng.random(dim) < density, v, 0.0).astype(np.float32)

        return RandomStream("OPVector", produce, seed)


class RandomGeolocation:
    @staticmethod
    def of(seed: int = 42) -> RandomStream:
        return RandomStream(
            "Geolocation",
            lambda rng: (
                float(rng.uniform(-90, 90)),
                float(rng.uniform(-180, 180)),
                float(rng.integers(1, 10)),
            ),
            seed,
        )


# --- table assembly (reference RandomData.scala / StandardRandomData.scala) --------------
def random_data(streams: dict[str, RandomStream], n: int) -> Table:
    """Zip named streams into an n-row Table; each stream draws independently from its
    own seed, so tables are reproducible per (streams, n)."""
    cols = {name: s.column(n) for name, s in streams.items()}
    return Table(cols, n)
