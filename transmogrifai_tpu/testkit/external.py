"""A tiny self-contained sklearn-protocol estimator for wrapper tests/examples.

`ExternalPredictorWrapper(factory="transmogrifai_tpu.testkit.external:CentroidClassifier")`
hosts it as a stage — the documented minimal example of the external-estimator
protocol (fit/predict/predict_proba, numpy in/out; see stages/model/wrapper.py).
"""
from __future__ import annotations

import numpy as np


class CentroidClassifier:
    """Nearest-class-centroid binary classifier with a temperature'd distance
    softmax. No dependencies; weights live in `centroids_`."""

    def __init__(self, temperature: float = 1.0):
        self.temperature = float(temperature)
        self.centroids_ = None

    def fit(self, X, y, sample_weight=None):
        X = np.asarray(X, np.float64)
        y = np.asarray(y)
        w = np.ones(len(y)) if sample_weight is None else np.asarray(sample_weight)
        cents = []
        for c in (0.0, 1.0):
            m = (y == c) & (w > 0)
            cents.append(np.average(X[m], axis=0, weights=w[m]) if m.any()
                         else np.zeros(X.shape[1]))
        self.centroids_ = np.stack(cents)
        return self

    def _scores(self, X):
        X = np.asarray(X, np.float64)
        d = ((X[:, None, :] - self.centroids_[None, :, :]) ** 2).sum(-1)
        z = -d / max(self.temperature, 1e-6)
        e = np.exp(z - z.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)

    def predict(self, X):
        return self._scores(X).argmax(axis=1).astype(np.float32)

    def predict_proba(self, X):
        return self._scores(X).astype(np.float32)
