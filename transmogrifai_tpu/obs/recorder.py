"""Flight recorder: a bounded ring of recent span events, dumped on failure.

Post-mortem chaos debugging used to be log-archaeology: a breaker trips or a
seeded fault kills a worker mid-shard, and reconstructing "what was the
process doing in the seconds before" means grepping interleaved stderr. The
flight recorder makes it data: every process keeps the last N span events
(`obs.add_event` feeds it whether or not a tracer is active — breaker
transitions, chaos injections, ingest lease churn, serve shed decisions all
flow through that one chokepoint) plus the counter deltas since arming, and
dumps the whole ring as `flightrec-<role>.json` the moment something goes
wrong:

  - a chaos injection fires (`chaos:inject` — the PR-6 FaultInjector sites),
  - a circuit breaker trips OPEN (`breaker:transition` with to=open),
  - a deadline-armed dispatch breaches (`resilience:deadline`),
  - the process takes SIGQUIT (kill -QUIT <pid>: on-demand snapshot of a
    wedged-but-alive process),
  - or an uncaught exception is about to end the process (sys.excepthook).

The ring is a fixed-capacity `collections.deque(maxlen=N)`: appends are
single bytecode-level operations (no explicit lock on the hot path — the
"lock-free" in the module's contract), and the dump path copies it wholesale
under a dump lock. Dumps are atomic (temp + fsync + os.replace) and
last-write-wins per role, so the file on disk always reflects the most recent
trigger. `flightrec_dumps_total{reason}` counts every dump on the registry so
federation surfaces recorder activity fleet-wide.
"""
from __future__ import annotations

import collections
import json
import os
import signal
import sys
import threading
import time
from typing import Optional

from . import metrics as _metrics
from .context import process_role

__all__ = [
    "FlightRecorder", "active_recorder", "install_recorder",
    "maybe_install_from_env", "uninstall_recorder",
]

DEFAULT_CAPACITY = 512

#: minimum seconds between dumps for the SAME reason — a chaos schedule that
#: fires every batch must not turn the recorder into a disk-write loop; the
#: ring still retains the newest events for the next dump that does land
_DUMP_MIN_INTERVAL_S = 0.5


def _trigger_reason(name: str, attrs: dict) -> Optional[str]:
    """Map a span event to a dump reason, or None for ordinary events."""
    if name == "chaos:inject":
        return "chaos_inject"
    if name == "breaker:transition" and attrs.get("to") == "open":
        return "breaker_open"
    if name == "resilience:deadline":
        return "deadline_breach"
    if name == "quality:breach":
        return "quality_breach"
    return None


class FlightRecorder:
    """Per-process bounded event ring with trigger-driven atomic dumps."""

    def __init__(self, role: Optional[str] = None, out_dir: str = ".",
                 capacity: int = DEFAULT_CAPACITY, registry=None):
        self.role = role or process_role()
        self.out_dir = out_dir
        self._ring: collections.deque = collections.deque(maxlen=int(capacity))
        self._registry = registry
        self._armed_at_unix = time.time()
        self._baseline = self._counter_values()
        self._dump_lock = threading.Lock()
        self._last_dump: dict[str, float] = {}  # reason -> monotonic stamp
        self.dumps = 0

    def _reg(self):
        return (self._registry if self._registry is not None
                else _metrics.default_registry())

    # --- hot path ---------------------------------------------------------------------
    def record(self, name: str, attrs: dict) -> None:
        """Append one span event to the ring; dump if it is a trigger."""
        self._ring.append({"t_unix": round(time.time(), 6),
                           "name": name, "attrs": attrs})
        reason = _trigger_reason(name, attrs)
        if reason is not None:
            self.dump(reason)

    # --- metric deltas ----------------------------------------------------------------
    def _counter_values(self) -> dict[str, float]:
        vals: dict[str, float] = {}
        for m in self._reg().collect():
            if m.kind == "counter":
                vals[m.name + _metrics._label_str(m.labels)] = m.value
        return vals

    def metric_deltas(self) -> dict[str, float]:
        """Counter movement since arming — the "what was the process actually
        doing" complement to the event ring (rows committed, batches scored,
        retries burned between arming and the trigger)."""
        deltas = {}
        for key, v in self._counter_values().items():
            d = v - self._baseline.get(key, 0.0)
            if d != 0:
                deltas[key] = round(d, 9)
        return deltas

    # --- dump -------------------------------------------------------------------------
    def path(self) -> str:
        return os.path.join(self.out_dir, f"flightrec-{self.role}.json")

    def dump(self, reason: str, force: bool = False) -> Optional[str]:
        """Write the ring + metric deltas atomically; returns the path, or
        None when rate-limited (same reason within the min interval).
        `force` bypasses the rate limit (SIGQUIT / crash dumps always land)."""
        now = time.monotonic()
        with self._dump_lock:
            last = self._last_dump.get(reason)
            if not force and last is not None \
                    and now - last < _DUMP_MIN_INTERVAL_S:
                return None
            self._last_dump[reason] = now
            payload = {
                "role": self.role,
                "pid": os.getpid(),
                "reason": reason,
                "armed_at_unix": round(self._armed_at_unix, 6),
                "dumped_at_unix": round(time.time(), 6),
                "events": list(self._ring),
                "metric_deltas": self.metric_deltas(),
                "metrics": self._reg().snapshot(),
            }
            path = self.path()
            os.makedirs(self.out_dir or ".", exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(payload, fh, default=str)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            self.dumps += 1
        # count AFTER the write so the dump's own snapshot doesn't include
        # the increment it is about to cause
        self._reg().counter(
            "flightrec_dumps_total",
            help="flight-recorder dumps by trigger reason",
            labels={"reason": reason, "role": self.role}).inc()
        return path


# --- process-global installation --------------------------------------------------------
_ACTIVE: Optional[FlightRecorder] = None
_PREV_EXCEPTHOOK = None
_PREV_SIGQUIT = None


def active_recorder() -> Optional[FlightRecorder]:
    return _ACTIVE


def install_recorder(role: Optional[str] = None, out_dir: str = ".",
                     capacity: int = DEFAULT_CAPACITY, registry=None,
                     signals: bool = True) -> FlightRecorder:
    """Arm a process-wide flight recorder: `obs.add_event` starts feeding it,
    SIGQUIT dumps on demand (main thread only — signal handlers cannot be
    registered elsewhere), and uncaught exceptions dump before the interpreter
    reports them. Re-installing replaces the previous recorder."""
    global _ACTIVE, _PREV_EXCEPTHOOK, _PREV_SIGQUIT
    rec = FlightRecorder(role=role, out_dir=out_dir, capacity=capacity,
                         registry=registry)
    _ACTIVE = rec
    if signals and _PREV_EXCEPTHOOK is None:
        _PREV_EXCEPTHOOK = sys.excepthook

        def _hook(exc_type, exc, tb):
            cur = _ACTIVE
            if cur is not None:
                try:
                    cur._ring.append({
                        "t_unix": round(time.time(), 6), "name": "crash",
                        "attrs": {"type": exc_type.__name__, "msg": str(exc)}})
                    cur.dump("crash", force=True)
                except Exception:
                    pass  # a recorder failure must never mask the real crash
            (_PREV_EXCEPTHOOK or sys.__excepthook__)(exc_type, exc, tb)

        sys.excepthook = _hook
    if signals and hasattr(signal, "SIGQUIT") \
            and threading.current_thread() is threading.main_thread():
        try:
            def _on_sigquit(signum, frame):
                cur = _ACTIVE
                if cur is not None:
                    cur.dump("sigquit", force=True)

            prev = signal.signal(signal.SIGQUIT, _on_sigquit)
            if _PREV_SIGQUIT is None:
                _PREV_SIGQUIT = prev
        except (ValueError, OSError):
            pass  # embedded interpreters without signal support
    return rec


def uninstall_recorder() -> None:
    """Disarm and restore the hooks (test isolation)."""
    global _ACTIVE, _PREV_EXCEPTHOOK, _PREV_SIGQUIT
    _ACTIVE = None
    if _PREV_EXCEPTHOOK is not None:
        sys.excepthook = _PREV_EXCEPTHOOK
        _PREV_EXCEPTHOOK = None
    if _PREV_SIGQUIT is not None and hasattr(signal, "SIGQUIT") \
            and threading.current_thread() is threading.main_thread():
        try:
            signal.signal(signal.SIGQUIT, _PREV_SIGQUIT)
        except (ValueError, OSError):
            pass
        _PREV_SIGQUIT = None


def maybe_install_from_env(role: Optional[str] = None) -> Optional[FlightRecorder]:
    """Arm from the TT_FLIGHTREC_DIR environment variable — the one-line hook
    every entrypoint (op run/serve/ingest-serve, the ingest worker main)
    calls, so `TT_FLIGHTREC_DIR=/tmp/rec op serve ...` arms the whole fleet
    (spawned workers inherit the environment)."""
    out_dir = os.environ.get("TT_FLIGHTREC_DIR")
    if not out_dir:
        return None
    cur = active_recorder()
    if cur is not None and cur.out_dir == out_dir:
        return cur  # idempotent: repeated runs keep the armed ring intact
    return install_recorder(role=role, out_dir=out_dir)
