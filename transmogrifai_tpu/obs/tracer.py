"""Hierarchical span tracer with XLA compile attribution.

The structured successor to the flat phase timer (`profiling.py`): spans carry
parent/child structure, wall time, the XLA cost model's FLOP/byte estimates,
device memory deltas (where the backend exposes `memory_stats()`), and — the
headline — every XLA compilation event observed while the span was the calling
thread's innermost open span. That last part is what turns "the soak was slow"
into "steady train #7 recompiled `_select_pad_kernel`, opened under
fit:SanityCheckerModel": the two recurring silent-failure classes of rounds 4-5
(steady-state retraces, unwarmed first trains) become attributable facts in a
report instead of hand-run compile-log archaeology.

Thread model: each Tracer keeps a *per-thread* stack of open spans. A span
opened in a worker thread with no explicit parent nests under that thread's
innermost span, falling back to the tracer root — so warmup's parallel solo
fits attribute their compiles somewhere sensible even unannotated. For real
nesting across threads, capture `obs.current_span()` in the parent thread and
pass it as `span(..., parent=captured)` from the worker.

Export formats:
  * `report()` — JSON, a backward-compatible superset of the old
    `Profiler.report()` ({"phases": [...]} plus "spans" and "compiles").
  * `export_chrome(path)` — Chrome-trace/Perfetto JSON (load at ui.perfetto.dev
    or chrome://tracing).
  * `text_tree()` — a one-screen tree for terminals (`op run --trace`).
"""
from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Optional

from . import context

#: compile-event kinds, in pipeline order: python tracing -> StableHLO lowering
#: -> XLA backend compile; "cache_hit" marks a persistent-cache executable
#: retrieval (deserialization — cheap relative to a compile, not free).
COMPILE_KINDS = ("trace", "lower", "compile", "cache_hit")


@dataclass
class PhaseTiming:
    """Aggregated wall clock of all spans sharing one name (legacy shape)."""

    name: str
    wall_s: float = 0.0
    count: int = 0


@dataclass
class CompileEvent:
    """One observed XLA compilation-pipeline event, attributed to a span."""

    kind: str          # one of COMPILE_KINDS
    program: str       # jit program name when known, "" otherwise
    duration_s: float
    t_s: float         # offset of the event's END from tracer start
    span: str          # slash path of the attributed span
    thread: int        # ident of the thread the event fired in

    def to_dict(self) -> dict:
        return {"kind": self.kind, "program": self.program,
                "duration_s": round(self.duration_s, 6),
                "t_s": round(self.t_s, 6), "span": self.span}


class Span:
    """One node of the trace tree. Created via Tracer.span(); not by hand."""

    __slots__ = ("name", "parent", "children", "t0", "t1", "thread",
                 "compiles", "cost", "mem_delta_bytes", "events",
                 "span_id", "remote_parent")

    def __init__(self, name: str, parent: Optional["Span"] = None):
        self.name = name
        self.parent = parent
        self.children: list[Span] = []
        self.t0 = 0.0
        self.t1 = 0.0
        self.thread = threading.get_ident()
        self.compiles: list[CompileEvent] = []
        self.cost: Optional[dict[str, float]] = None
        self.mem_delta_bytes: Optional[int] = None
        #: point-in-time annotations attached via Tracer.add_event (e.g. the
        #: plan analyzer's downgraded diagnostics in strict=False trains):
        #: list of {"name": ..., **attrs} dicts
        self.events: list[dict] = []
        #: process-unique hex id — the cross-process linkage key: a remote
        #: side that received this span's id as a TraceContext carries it as
        #: `remote_parent`, and the stitch tool joins the two dumps on it
        self.span_id = context.new_span_id()
        #: span_id of the span in ANOTHER process this span logically nests
        #: under (arrived via LEASE ctx / traceparent header); None locally
        self.remote_parent: Optional[str] = None

    @property
    def wall_s(self) -> float:
        return (self.t1 or time.perf_counter()) - self.t0

    @property
    def path(self) -> str:
        parts = []
        node: Optional[Span] = self
        while node is not None:
            parts.append(node.name)
            node = node.parent
        return "/".join(reversed(parts))

    def to_dict(self) -> dict:
        out: dict[str, Any] = {"name": self.name,
                               "wall_s": round(self.wall_s, 6)}
        if self.compiles:
            out["compiles"] = [e.to_dict() for e in self.compiles]
        if self.cost:
            out["cost"] = dict(self.cost)
        if self.mem_delta_bytes is not None:
            out["mem_delta_bytes"] = self.mem_delta_bytes
        if self.events:
            out["events"] = [dict(e) for e in self.events]
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        return out


class Tracer:
    """Collects a span tree plus every compile event fired inside it.

    Also exposes the legacy Profiler surface (`phases`, `add_phase`,
    `add_cost`, `device_cost`, `report()["phases"]`) so existing callers and
    reports keep working unchanged.
    """

    def __init__(self, trace_dir: Optional[str] = None, name: str = "run",
                 role: Optional[str] = None):
        self.trace_dir = trace_dir
        self.root = Span(name)
        self.root.t0 = time.perf_counter()
        #: wall-clock anchor of root.t0 — perf_counter epochs differ per
        #: process, so cross-process stitching aligns dumps on this instead
        self.t0_unix = time.time()
        #: distributed trace identity; a process that receives a remote
        #: TraceContext adopts its id so one fleet run shares ONE trace_id
        self.trace_id = context.new_trace_id()
        self.role = role or context.process_role()
        #: Chrome dumps of child processes (ingest workers, daemon) registered
        #: via adopt_dump(); export_chrome(stitched=True) folds them in
        self.child_dumps: list[str] = []
        self.phases: dict[str, PhaseTiming] = {}
        self.device_cost: dict[str, dict[str, float]] = {}
        self.compile_events: list[CompileEvent] = []
        self._order: list[str] = []
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._mem_fn = _memory_stats_fn()

    def adopt_trace_id(self, trace_id: str) -> None:
        """Take on a remote trace id (last adoption wins — one fleet run is
        one trace, so repeated leases from the same coordinator are
        idempotent here)."""
        if trace_id:
            self.trace_id = trace_id

    def adopt_dump(self, path: str) -> None:
        """Register a child process's Chrome dump for stitched export."""
        with self._lock:
            if path not in self.child_dumps:
                self.child_dumps.append(path)

    # --- span stack (per thread) ------------------------------------------------------
    def _stack(self) -> list[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current_span(self) -> Span:
        st = self._stack()
        return st[-1] if st else self.root

    @contextmanager
    def span(self, name: str, parent: Optional[Span] = None,
             remote_parent: Optional[str] = None):
        """Open a child span of `parent` (default: the calling thread's
        innermost open span, falling back to the tracer root).
        `remote_parent` stamps the span id of a span in ANOTHER process
        (arrived as a TraceContext) so stitched exports can link it."""
        sp = Span(name, parent=parent or self.current_span())
        if remote_parent:
            sp.remote_parent = remote_parent
        with self._lock:
            sp.parent.children.append(sp)
        mem0 = self._mem_fn() if self._mem_fn else None
        st = self._stack()
        st.append(sp)
        sp.t0 = time.perf_counter()
        try:
            yield sp
        finally:
            sp.t1 = time.perf_counter()
            st.pop()
            if mem0 is not None:
                mem1 = self._mem_fn()
                if mem1 is not None:
                    sp.mem_delta_bytes = mem1 - mem0
            self.add_phase(name, sp.t1 - sp.t0)

    # --- legacy Profiler surface ------------------------------------------------------
    def add_phase(self, name: str, wall_s: float) -> None:
        # lock: phases report from worker threads too (warmup's parallel solo
        # fits) — the check-then-create and the += pair would lose updates
        # unprotected
        with self._lock:
            t = self.phases.get(name)
            if t is None:
                t = self.phases[name] = PhaseTiming(name)
                self._order.append(name)
            t.wall_s += wall_s
            t.count += 1

    def add_cost(self, name: str, cost: dict[str, float]) -> None:
        with self._lock:
            self.device_cost[name] = dict(cost)
        sp = self.current_span()
        if sp is not self.root:
            sp.cost = dict(cost)

    def add_event(self, name: str, **attrs) -> None:
        """Attach a point-in-time annotation to the calling thread's innermost
        open span (the root outside any span). The tracer-relative timestamp
        rides along as "t_s" so exporters can place the instant on the
        timeline (export_chrome emits these as instant events)."""
        sp = self.current_span()
        ev = {"name": name, **attrs}
        ev.setdefault("t_s", round(time.perf_counter() - self.root.t0, 6))
        with self._lock:
            sp.events.append(ev)

    # --- compile attribution (called by watchdog listeners) ---------------------------
    def on_compile_event(self, kind: str, program: str, duration_s: float) -> None:
        sp = self.current_span()
        now = time.perf_counter()
        ev = CompileEvent(kind=kind, program=program, duration_s=duration_s,
                          t_s=now - self.root.t0, span=sp.path,
                          thread=threading.get_ident())
        with self._lock:
            sp.compiles.append(ev)
            self.compile_events.append(ev)

    # --- reports ----------------------------------------------------------------------
    def finish(self) -> None:
        # idempotent but monotone: a mid-run report() (e.g. the runner
        # reporting inside a CLI-owned tracer) must not freeze the root early
        self.root.t1 = time.perf_counter()

    def compile_report(self, max_events: int = 200) -> dict:
        """Answer "what compiled, when, and which span caused it"."""
        with self._lock:
            events = list(self.compile_events)
        counts = {k: 0 for k in COMPILE_KINDS}
        secs = {k: 0.0 for k in COMPILE_KINDS}
        by_span: dict[str, dict[str, Any]] = {}
        for e in events:
            counts[e.kind] = counts.get(e.kind, 0) + 1
            secs[e.kind] = secs.get(e.kind, 0.0) + e.duration_s
            row = by_span.setdefault(e.span, {k: 0 for k in COMPILE_KINDS})
            row[e.kind] = row.get(e.kind, 0) + 1
        out = {
            "counts": counts,
            "seconds": {k: round(v, 6) for k, v in secs.items()},
            "by_span": by_span,
            "events": [e.to_dict() for e in events[:max_events]],
        }
        if len(events) > max_events:
            out["events_dropped"] = len(events) - max_events
        return out

    def report(self) -> dict:
        """Backward-compatible superset of the old Profiler.report()."""
        self.finish()
        with self._lock:  # snapshot vs concurrent add_phase/add_cost threads
            order = list(self._order)
            phases = {n: (self.phases[n].wall_s, self.phases[n].count)
                      for n in order}
            device_cost = {k: dict(v) for k, v in self.device_cost.items()}
        out: dict[str, Any] = {
            "phases": [
                {"name": n, "wall_s": round(phases[n][0], 6),
                 "count": phases[n][1]}
                for n in order
            ],
        }
        if device_cost:
            total_flops = sum(c.get("flops", 0.0)
                              for c in device_cost.values())
            out["device_cost"] = {
                "programs": device_cost,
                "total_estimated_flops": total_flops,
            }
        if self.trace_dir:
            out["trace_dir"] = self.trace_dir
        out["spans"] = self.root.to_dict()
        out["compiles"] = self.compile_report()
        return out

    # --- Chrome trace / Perfetto ------------------------------------------------------
    def chrome_payload(self) -> dict:
        """The Chrome-trace JSON payload (the `traceEvents` array format
        Perfetto and chrome://tracing load), in memory. Spans become complete
        ("X") events on their thread's track; compile events become "X" events
        in a "compile" category; cache hits are instants; span events
        (`add_event`: oplint diagnostics, serve:routing decisions, drift
        alerts) become instant ("i") events in an "event" category on the
        span's thread. Every span carries its `span_id` (and `remote_parent`
        when set) in args, and a `metadata` block anchors the dump in
        wall-clock time — together the inputs `obs.fleet.stitch_chrome_traces`
        needs to join per-process dumps into one distributed timeline."""
        self.finish()
        t_base = self.root.t0
        events: list[dict] = []
        threads: dict[int, int] = {}

        def tid_of(ident: int) -> int:
            if ident not in threads:
                threads[ident] = len(threads)
                events.append({"ph": "M", "name": "thread_name", "pid": 1,
                               "tid": threads[ident],
                               "args": {"name": f"thread-{len(threads) - 1}"
                                        if len(threads) > 1 else "main"}})
            return threads[ident]

        def walk(sp: Span) -> None:
            args: dict[str, Any] = {"path": sp.path, "span_id": sp.span_id}
            if sp.parent is not None:
                args["parent_span_id"] = sp.parent.span_id
            if sp.remote_parent:
                args["remote_parent"] = sp.remote_parent
            events.append({
                "ph": "X", "name": sp.name, "cat": "span", "pid": 1,
                "tid": tid_of(sp.thread),
                "ts": round((sp.t0 - t_base) * 1e6, 3),
                "dur": round(max(sp.wall_s, 0.0) * 1e6, 3),
                "args": args,
            })
            for ev in sp.events:
                # instant events on the span's own thread track: oplint
                # findings, serve:routing decisions, drift alerts — without
                # these the timeline shows WHERE time went but not WHAT the
                # run decided. Events predating the t_s stamp fall back to
                # the span start.
                attrs = {k: v for k, v in ev.items() if k not in ("name", "t_s")}
                ts_s = ev.get("t_s", sp.t0 - t_base)
                events.append({
                    "ph": "i", "s": "t", "cat": "event",
                    "name": str(ev.get("name", "event")), "pid": 1,
                    "tid": tid_of(sp.thread),
                    "ts": round(float(ts_s) * 1e6, 3),
                    "args": {"span": sp.path, **attrs},
                })
            for c in sp.children:
                walk(c)

        walk(self.root)
        with self._lock:
            compile_events = list(self.compile_events)
        for e in compile_events:
            base = {"cat": "compile", "pid": 1, "tid": tid_of(e.thread),
                    "name": f"{e.kind}:{e.program or '?'}",
                    "args": {"span": e.span, "program": e.program}}
            if e.duration_s > 0:
                base.update({"ph": "X", "dur": round(e.duration_s * 1e6, 3),
                             "ts": round((e.t_s - e.duration_s) * 1e6, 3)})
            else:
                base.update({"ph": "i", "s": "t",
                             "ts": round(e.t_s * 1e6, 3)})
            events.append(base)
        return {
            "traceEvents": events, "displayTimeUnit": "ms",
            "metadata": {
                "trace_id": self.trace_id, "role": self.role,
                "pid": os.getpid(), "name": self.root.name,
                "t0_unix": round(self.t0_unix, 6),
            },
        }

    def export_chrome(self, path: str, stitched: bool = False) -> str:
        """Write the Chrome-trace JSON to `path`. With `stitched=True`, child
        process dumps registered via `adopt_dump()` (ingest workers' exports,
        the daemon's) are merged in — per-process pid lanes, wall-clock
        aligned, remote-parent links drawn as flow arrows — yielding ONE
        end-to-end ingest→train→serve timeline (see obs.fleet)."""
        payload = self.chrome_payload()
        if stitched:
            from . import fleet

            with self._lock:
                dumps = list(self.child_dumps)
            payload = fleet.stitch_chrome_traces([payload] + dumps)
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
        with open(path, "w") as fh:
            json.dump(payload, fh)
        return path

    # --- text tree --------------------------------------------------------------------
    def text_tree(self, max_lines: int = 40) -> str:
        """One-screen indented tree: wall time, compile counts/seconds per span."""
        self.finish()
        lines: list[str] = []

        def annot(sp: Span) -> str:
            parts = [f"{sp.wall_s * 1e3:9.1f} ms"]
            if sp.compiles:
                n = sum(1 for e in sp.compiles if e.kind == "compile")
                lo = sum(1 for e in sp.compiles if e.kind == "lower")
                ch = sum(1 for e in sp.compiles if e.kind == "cache_hit")
                cs = sum(e.duration_s for e in sp.compiles)
                tag = []
                if n:
                    tag.append(f"{n} compile")
                if lo:
                    tag.append(f"{lo} lower")
                if ch:
                    tag.append(f"{ch} cache-hit")
                if tag:
                    parts.append(f"[{', '.join(tag)}; {cs:.2f}s]")
            if sp.cost and sp.cost.get("flops"):
                parts.append(f"{sp.cost['flops'] / 1e9:.2f} GFLOP")
            if sp.mem_delta_bytes:
                parts.append(f"mem {sp.mem_delta_bytes / 1e6:+.1f} MB")
            return "  ".join(parts)

        def walk(sp: Span, depth: int) -> None:
            lines.append(f"{'  ' * depth}{sp.name:<{max(40 - 2 * depth, 8)}}"
                         f" {annot(sp)}")
            for c in sp.children:
                walk(c, depth + 1)

        walk(self.root, 0)
        if len(lines) > max_lines:
            dropped = len(lines) - max_lines
            lines = lines[:max_lines] + [f"... (+{dropped} more spans)"]
        return "\n".join(lines)


def _memory_stats_fn():
    """Return a zero-arg callable yielding bytes-in-use of device 0, or None
    when the backend does not expose memory_stats (host CPU returns None)."""
    try:
        import jax

        dev = jax.local_devices()[0]
        stats = dev.memory_stats()
        if not stats or "bytes_in_use" not in stats:
            return None

        def fn() -> Optional[int]:
            try:
                s = dev.memory_stats()
                return int(s["bytes_in_use"]) if s else None
            except Exception:
                return None

        return fn
    except Exception:
        return None
