"""obs — unified span tracer + XLA compile/retrace watchdog.

The runtime-telemetry substrate for every layer of the stack (the structured
successor to the flat `profiling` phase timer; see docs/observability.md):

    from transmogrifai_tpu import obs

    with obs.trace() as t:
        runner.run("train", params)
    print(t.text_tree())            # one-screen span tree with compile counts
    t.export_chrome("trace.json")   # load at ui.perfetto.dev
    t.compile_report()              # what compiled, attributed to spans

    with obs.retrace_budget(0):     # steady state must not compile
        model = workflow.train(table=table)

`obs.span("name")` is a zero-overhead no-op without an active tracer, so
library code annotates unconditionally. All of `workflow`, `select`, `serve`,
`check`, and the warmup path carry spans.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

from .context import TraceContext, new_span_id, new_trace_id, process_role
from .cost import cached_compiled, compiled_flops, cost_analysis, record_cost
from .fleet import (
    FleetAggregator,
    MetricsPusher,
    fleet_totals,
    stitch_chrome_traces,
)
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    parse_prometheus,
)
from .quality import (
    QualityAlert,
    QualityMonitor,
    QualitySketch,
    QualityThresholds,
    quality_from_snapshot,
    sketch_metrics,
)
from .recorder import (
    FlightRecorder,
    active_recorder,
    install_recorder,
    maybe_install_from_env,
    uninstall_recorder,
)
from .tracer import CompileEvent, PhaseTiming, Span, Tracer
from .watchdog import RetraceBudget, RetraceBudgetExceeded
from .watchdog import activate as _activate
from .watchdog import deactivate as _deactivate

__all__ = [
    "CompileEvent", "Counter", "FleetAggregator", "FlightRecorder", "Gauge",
    "Histogram", "MetricsPusher", "MetricsRegistry", "PhaseTiming",
    "QualityAlert", "QualityMonitor", "QualitySketch", "QualityThresholds",
    "RetraceBudget", "RetraceBudgetExceeded", "Span", "TraceContext",
    "Tracer", "active_recorder", "add_event", "cached_compiled",
    "compiled_flops", "cost_analysis", "current", "current_span",
    "current_trace_context", "default_registry", "fleet_totals",
    "install_recorder", "maybe_install_from_env", "new_span_id",
    "new_trace_id", "parse_prometheus", "process_role",
    "quality_from_snapshot", "record_cost", "retrace_budget",
    "sketch_metrics", "span", "stitch_chrome_traces", "trace",
    "uninstall_recorder",
]

#: innermost-first stack of active tracers (module-global, shared across
#: threads on purpose: a tracer opened on the main thread must see spans and
#: compiles from warmup's worker threads)
_ACTIVE: list[Tracer] = []


def current() -> Optional[Tracer]:
    """The innermost active tracer, or None."""
    return _ACTIVE[-1] if _ACTIVE else None


def current_span() -> Optional[Span]:
    """The calling thread's innermost open span of the active tracer (the
    tracer root when no span is open), or None without a tracer. Capture this
    before handing work to a thread pool and pass it as `span(..., parent=)`
    to nest worker-side spans under the caller."""
    t = current()
    return t.current_span() if t is not None else None


@contextmanager
def trace(trace_dir: Optional[str] = None, name: str = "run",
          role: Optional[str] = None):
    """Activate a Tracer for the dynamic extent; optionally also capture an
    on-disk jax.profiler trace viewable in TensorBoard/XProf (trace_dir).
    `role` names this process's lane in stitched fleet exports (defaults to
    the TT_ROLE environment variable / "run")."""
    tracer = Tracer(trace_dir=trace_dir, name=name, role=role)
    _ACTIVE.append(tracer)
    _activate(tracer, "tracer")
    started_trace = False
    try:
        # inside the try: a start_trace failure (unwritable dir, a profiler
        # trace already running) must still unwind the tracer stack and the
        # watchdog's logger takeover
        if trace_dir is not None:
            import jax

            jax.profiler.start_trace(trace_dir)
            started_trace = True
        yield tracer
    finally:
        if started_trace:
            import jax

            jax.profiler.stop_trace()
        _deactivate(tracer, "tracer")
        _ACTIVE.remove(tracer)
        tracer.finish()


def add_event(name: str, **attrs) -> None:
    """Attach a point-in-time annotation to the active tracer's current span
    (e.g. oplint diagnostics downgraded by `train(strict=False)`); no-op
    without a tracer. The armed flight recorder (obs.recorder) is fed
    REGARDLESS of tracer state — breaker transitions, chaos injections, and
    deadline breaches all flow through here, which is what makes this the
    recorder's single chokepoint."""
    t = current()
    if t is not None:
        t.add_event(name, **attrs)
    rec = active_recorder()
    if rec is not None:
        rec.record(name, attrs)


@contextmanager
def span(name: str, parent: Optional[Span] = None,
         remote_parent: Optional[str] = None):
    """Open a named span on the active tracer; no-op without one.
    `remote_parent` links the span under a span id from ANOTHER process
    (arrived as a TraceContext) for stitched exports."""
    t = current()
    if t is None:
        yield None
        return
    with t.span(name, parent=parent, remote_parent=remote_parent) as sp:
        yield sp


def current_trace_context() -> Optional[TraceContext]:
    """The (trace_id, current span_id) pair to hand the NEXT hop — stamped
    into LEASE payloads, traceparent headers, and autopilot retrain spawns.
    None without an active tracer."""
    t = current()
    if t is None:
        return None
    return TraceContext(trace_id=t.trace_id, span_id=t.current_span().span_id)


def retrace_budget(budget: int = 0, kinds=("lower", "compile"),
                   action: str = "raise") -> RetraceBudget:
    """Enforce "at most `budget` compilation events in this block".

    Counts XLA pipeline events of the given kinds ("trace", "lower",
    "compile", "cache_hit"); the default catches any program (re)build even
    when the persistent compile cache absorbs the backend compile. With
    action="raise" the violation raises RetraceBudgetExceeded at context exit;
    "warn" logs each excess event instead.
    """
    return RetraceBudget(budget=budget, kinds=kinds, action=action)
