"""XLA cost-model capture with a cached lowering.

The old `profiling.record_cost`/`compiled_flops` called
`jitted_fn.lower(*args).compile()` every time — the AOT path does not share
executables with the function's own call cache, so each cost lookup paid a
full second backend compile of an already-compiled program. Here the Compiled
object is memoized per (jitted function, abstract input signature): the first
lookup pays one AOT compile (or a persistent-cache retrieval), every later
lookup on the warm path is a dict hit.

The cache holds weak references to the jitted functions, so per-fit jit
wrappers (the selector builds them per search) do not leak; entries evict when
the function is collected.
"""
from __future__ import annotations

import threading
import weakref
from typing import Any, Optional

#: id(fn) -> (weakref to fn, {signature: Compiled})
_CACHE: dict[int, tuple[Any, dict]] = {}
_LOCK = threading.Lock()

_COST_KEYS = ("flops", "bytes accessed", "utilization operand 0 {}")


def _leaf_sig(leaf) -> tuple:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return ("a", tuple(shape), str(dtype))
    return ("o", type(leaf).__name__, leaf if isinstance(
        leaf, (int, float, bool, str, bytes, type(None))) else id(leaf))


def _signature(args, kwargs) -> tuple:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef, tuple(_leaf_sig(x) for x in leaves))


def cached_compiled(jitted_fn, *args, **kwargs):
    """`jitted_fn.lower(*args).compile()`, memoized on (fn, input signature)."""
    key = id(jitted_fn)
    sig = _signature(args, kwargs)
    with _LOCK:
        entry = _CACHE.get(key)
        if entry is not None and entry[0]() is not None:
            hit = entry[1].get(sig)
            if hit is not None:
                return hit
    compiled = jitted_fn.lower(*args, **kwargs).compile()
    with _LOCK:
        entry = _CACHE.get(key)
        if entry is None or entry[0]() is None:
            try:
                ref = weakref.ref(jitted_fn,
                                  lambda _r, _k=key: _CACHE.pop(_k, None))
            except TypeError:  # not weakrefable: still cache, pinning the fn
                ref = (lambda fn: (lambda: fn))(jitted_fn)
            entry = _CACHE[key] = (ref, {})
        entry[1][sig] = compiled
    return compiled


def cost_analysis(compiled) -> dict[str, float]:
    """Normalize Compiled.cost_analysis() across jax versions (list vs dict)."""
    analysis = compiled.cost_analysis()
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    return {k: float(v) for k, v in dict(analysis).items()
            if isinstance(v, (int, float))}


def record_cost(name: str, jitted_fn, *args, **kwargs) -> None:
    """Attach the XLA cost-model estimate of a jitted program to the active
    tracer (flops / bytes accessed — the compiler's own numbers, not wall-clock
    measurement). Free on the warm path; no-op without an active tracer."""
    from . import current

    tracer = current()
    if tracer is None:
        return
    try:
        full = cost_analysis(cached_compiled(jitted_fn, *args, **kwargs))
        tracer.add_cost(name, {k: v for k, v in full.items() if k in _COST_KEYS})
    except Exception:
        # cost analysis is best-effort: some backends/fns don't expose it
        pass


def compiled_flops(jitted_fn, *args, **kwargs) -> Optional[float]:
    """FLOPs of one invocation per XLA's own cost model (not wall-clock)."""
    try:
        full = cost_analysis(cached_compiled(jitted_fn, *args, **kwargs))
        return float(full.get("flops", 0.0))
    except Exception:
        return None
