"""Cross-process trace context: W3C-traceparent-style propagation.

One run of the fleet — coordinator, ingest workers, serving daemon, autopilot
— is many processes, and a request that crosses the ingest socket or the
daemon's HTTP surface used to fall off the trace at the boundary. This module
is the identity layer that keeps it on: a `TraceContext` is (trace_id,
span_id) where `trace_id` names the whole distributed trace and `span_id`
names the REMOTE PARENT — the span on the sending side under which the
receiving process's work logically nests.

Wire forms (both directions of every boundary):

  - HTTP header (daemon `/v1/score`):  `traceparent: 00-<32 hex>-<16 hex>-01`
    — the W3C Trace Context shape, so external tooling that already speaks
    traceparent interoperates.
  - Framed transport (ingest LEASE/BATCH): a `"ctx"` dict
    `{"trace_id": ..., "span_id": ...}` riding the JSON payload.

Receivers adopt the remote trace_id onto their local tracer
(`Tracer.adopt_trace_id`) and open their top span with
`remote_parent=ctx.span_id`; the stitch tool (`obs.fleet.stitch_chrome_traces`
/ `op trace-merge`) then links the per-process Chrome dumps into one
end-to-end timeline keyed by the shared trace_id.

Parsing is deliberately forgiving — `from_wire`/`from_traceparent` return
None on anything malformed rather than raising, because a bad ctx from a
mismatched peer must never take down a frame handler or an HTTP route.
"""
from __future__ import annotations

import binascii
import os
import re
from dataclasses import dataclass
from typing import Optional

__all__ = [
    "TraceContext", "new_span_id", "new_trace_id", "process_role",
]

#: version 00, sampled flag set — the only traceparent shape we emit
_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-(?P<trace>[0-9a-f]{32})-(?P<span>[0-9a-f]{16})-[0-9a-f]{2}$")

_HEX_TRACE_RE = re.compile(r"^[0-9a-f]{32}$")
_HEX_SPAN_RE = re.compile(r"^[0-9a-f]{16}$")


def new_trace_id() -> str:
    """128-bit random hex trace id (collision-safe across processes without
    any coordination — the property fleet stitching needs)."""
    return binascii.hexlify(os.urandom(16)).decode("ascii")


def new_span_id() -> str:
    """64-bit random hex span id."""
    return binascii.hexlify(os.urandom(8)).decode("ascii")


def process_role(default: str = "run") -> str:
    """This process's fleet role ("coordinator", "ingest-worker", "serve",
    "run", ...). Spawned subprocesses inherit it via the TT_ROLE environment
    variable; the entrypoints set it explicitly. Labels every federated
    metric series and names the flight-recorder dump file."""
    return os.environ.get("TT_ROLE", default)


@dataclass(frozen=True)
class TraceContext:
    """An immutable (trace_id, parent span_id) pair crossing one boundary."""

    trace_id: str
    span_id: str

    @classmethod
    def new(cls) -> "TraceContext":
        return cls(trace_id=new_trace_id(), span_id=new_span_id())

    def child(self, span_id: Optional[str] = None) -> "TraceContext":
        """A context for the NEXT hop: same trace, fresh (or given) parent
        span id — the id of the local span the remote side should nest
        under."""
        return TraceContext(trace_id=self.trace_id,
                            span_id=span_id or new_span_id())

    # --- HTTP header form -------------------------------------------------------------
    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, header: Optional[str]) -> Optional["TraceContext"]:
        if not header or not isinstance(header, str):
            return None
        m = _TRACEPARENT_RE.match(header.strip().lower())
        if not m:
            return None
        return cls(trace_id=m.group("trace"), span_id=m.group("span"))

    # --- framed-transport form --------------------------------------------------------
    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_wire(cls, obj) -> Optional["TraceContext"]:
        if not isinstance(obj, dict):
            return None
        trace_id = obj.get("trace_id")
        span_id = obj.get("span_id")
        if (not isinstance(trace_id, str) or not isinstance(span_id, str)
                or not _HEX_TRACE_RE.match(trace_id)
                or not _HEX_SPAN_RE.match(span_id)):
            return None
        return cls(trace_id=trace_id, span_id=span_id)
