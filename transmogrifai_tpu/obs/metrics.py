"""Numeric telemetry: a thread-safe metrics registry with Prometheus export.

The companion of the span tracer (tracer.py): spans answer "where did the time
go in THIS run", the registry answers "what are the running totals/levels/
distributions of the process" — mesh transfer counters, input-pipeline stall
seconds and queue depths, serving routing decisions and latency percentiles,
feature-drift gauges. tf.data (arXiv:2101.12127) makes the case that an input
runtime is only tunable when these numbers exist as first-class metrics; the
TensorFlow system paper (arXiv:1605.08695) treats the unified metrics layer as
a subsystem in its own right. Before this module each producer kept an ad-hoc
dict (`mesh._MESH_STATS`, `PipelineStats`, `serve:routing` span events) with
no percentiles and no export format.

Three instrument kinds, Prometheus-shaped:

  - Counter   — monotone float total (`.inc(n)`); name by convention `*_total`
                or `*_seconds_total`.
  - Gauge     — last-written level (`.set(v)`, `.inc`/`.dec`).
  - Histogram — log-bucketed counts for exposition PLUS a bounded sample
                reservoir for exact p50/p95/p99 (exact while the observation
                count stays within the reservoir; uniform reservoir sampling —
                deterministic seed — beyond it).

Every instrument takes an optional frozen label set at creation
(`registry.counter("serve_routing_total", labels={"backend": "cpu"})`); the
(name, labels) pair is the identity, so repeated get-or-create calls from any
thread return the same instrument. Export:

  - `registry.snapshot()`   — plain-JSON dict (rides AppMetrics' `metrics`
                              section and `op monitor --json`)
  - `registry.to_prometheus()` — text exposition format 0.0.4 (`op monitor
                              --prom`; scrapeable)
  - `parse_prometheus(text)` — strict validity check of an exposition (the CI
                              lint and the tests share it)

All updates are lock-protected: producers include the input pipeline's
producer thread and warmup's solo-fit pool, so unsynchronized `+=` would lose
increments exactly like the tracer's phase table would (tracer.py add_phase).
"""
from __future__ import annotations

import math
import random
import re
import threading
import zlib
from typing import Optional, Sequence

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "default_registry",
    "parse_prometheus", "reset_default_registry",
]

#: default log-spaced histogram bounds: 10 µs doubling up to ~84 s — covers
#: sub-ms CPU serving through multi-second cold device dispatches in 24 buckets
DEFAULT_BUCKETS = tuple(1e-5 * (2.0 ** i) for i in range(24))

#: exact-percentile window: reservoir size per histogram (beyond this the
#: percentiles degrade gracefully to uniform-sample estimates)
DEFAULT_RESERVOIR = 4096

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _freeze_labels(labels: Optional[dict]) -> tuple:
    if not labels:
        return ()
    for k in labels:
        if not _LABEL_RE.match(k):
            raise ValueError(f"invalid label name {k!r}")
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _label_str(labels: tuple) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + body + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Metric:
    """Shared identity + lock of all instrument kinds."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labels: tuple = ()):
        self.name = _check_name(name)
        self.help = help
        self.labels = labels
        self._lock = threading.Lock()


class Counter(_Metric):
    """Monotone total. `inc(n)` with n >= 0; negative increments raise."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", labels: tuple = ()):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> dict:
        return {"value": self.value}


class Gauge(_Metric):
    """Last-written level; `set`/`inc`/`dec`."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", labels: tuple = ()):
        super().__init__(name, help, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> dict:
        return {"value": self.value}


class Histogram(_Metric):
    """Log-bucketed distribution with exact small-count percentiles.

    Two structures per instrument, updated under one lock:
      - cumulative bucket counts over `bounds` (+Inf implicit) + sum/count —
        the Prometheus exposition shape, mergeable across scrapes;
      - a bounded reservoir of raw observations — p50/p95/p99 are computed
        from it at snapshot time, EXACT while count <= reservoir size, then a
        uniform (seeded, deterministic) sample estimate.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "", labels: tuple = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 reservoir: int = DEFAULT_RESERVOIR):
        super().__init__(name, help, labels)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        self._reservoir_max = int(reservoir)
        self._samples: list[float] = []
        # deterministic reservoir: tests and repeated benches see stable
        # percentile estimates past the exact window. crc32, not hash():
        # python hash() is salted per process, which would re-randomize the
        # eviction sequence across runs (the same reason raw_feature_filter
        # uses a stable hash for its text buckets)
        import zlib

        self._rng = random.Random(0x5EED ^ zlib.crc32(name.encode("utf-8")))

    def observe(self, v: float) -> None:
        v = float(v)
        if not math.isfinite(v):
            return  # a NaN latency must never poison sum/percentiles
        with self._lock:
            self._sum += v
            self._count += 1
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            lo, hi = 0, len(self.bounds)
            while lo < hi:  # first bound >= v (bisect; bounds are sorted)
                mid = (lo + hi) // 2
                if self.bounds[mid] >= v:
                    hi = mid
                else:
                    lo = mid + 1
            self._counts[lo] += 1
            if len(self._samples) < self._reservoir_max:
                self._samples.append(v)
            elif self._reservoir_max > 0:  # Algorithm R: uniform over the stream
                j = self._rng.randrange(self._count)
                if j < self._reservoir_max:
                    self._samples[j] = v

    def observe_many(self, values) -> None:
        """Fold a batch of observations under ONE lock acquisition — for
        hot-path producers that already hold a batch (same per-value
        semantics as `observe`)."""
        with self._lock:
            for v in values:
                v = float(v)
                if not math.isfinite(v):
                    continue
                self._sum += v
                self._count += 1
                if v < self._min:
                    self._min = v
                if v > self._max:
                    self._max = v
                lo, hi = 0, len(self.bounds)
                while lo < hi:
                    mid = (lo + hi) // 2
                    if self.bounds[mid] >= v:
                        hi = mid
                    else:
                        lo = mid + 1
                self._counts[lo] += 1
                if len(self._samples) < self._reservoir_max:
                    self._samples.append(v)
                elif self._reservoir_max > 0:
                    j = self._rng.randrange(self._count)
                    if j < self._reservoir_max:
                        self._samples[j] = v

    def observe_weighted(self, value, count: int) -> None:
        """Fold `count` identical observations in O(1) — for producers whose
        values are already binned (the quality plane's sketch centers). Only
        valid without a reservoir: with sampling armed this falls back to the
        per-value loop so Algorithm R stays uniform over the stream."""
        c = int(count)
        if c <= 0:
            return
        if self._reservoir_max > 0:
            self.observe_many([value] * c)
            return
        v = float(value)
        if not math.isfinite(v):
            return
        with self._lock:
            self._sum += v * c
            self._count += c
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            lo, hi = 0, len(self.bounds)
            while lo < hi:
                mid = (lo + hi) // 2
                if self.bounds[mid] >= v:
                    hi = mid
                else:
                    lo = mid + 1
            self._counts[lo] += c

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> Optional[float]:
        """q in [0, 100]; None before any observation."""
        with self._lock:
            samples = sorted(self._samples)
        if not samples:
            return None
        idx = min(len(samples) - 1, max(0, math.ceil(q / 100.0 * len(samples)) - 1))
        return samples[idx]

    def snapshot(self, samples: bool = False) -> dict:
        """Plain-JSON view. With `samples=True` the snapshot additionally
        carries the raw internals (`bounds`, per-bucket `raw_counts`, the
        sorted `reservoir`) that `MetricsRegistry.merge` needs to fold this
        histogram into another registry EXACTLY — the federation wire shape
        (METRICS frames, `/fleet/metrics`). The default stays lean because
        plain snapshots ride AppMetrics and `op monitor --json`."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
            mn, mx = self._min, self._max
            reservoir = sorted(self._samples)

        def pct(q: float) -> Optional[float]:
            if not reservoir:
                return None
            idx = min(len(reservoir) - 1,
                      max(0, math.ceil(q / 100.0 * len(reservoir)) - 1))
            return reservoir[idx]

        cum = 0
        buckets = {}
        for b, c in zip(self.bounds, counts[:-1]):
            cum += c
            buckets[f"{b:g}"] = cum
        buckets["+Inf"] = total
        out = {
            "count": total, "sum": round(s, 9),
            "min": None if total == 0 else mn,
            "max": None if total == 0 else mx,
            "p50": pct(50), "p95": pct(95), "p99": pct(99),
            "buckets": buckets,
        }
        if samples:
            out["bounds"] = list(self.bounds)
            out["raw_counts"] = counts
            out["reservoir"] = reservoir
        return out

    # --- federation merge -------------------------------------------------------------
    def merge(self, other: "Histogram") -> None:
        """Fold another histogram's state into this one: exact bucket/sum/
        count addition (requires identical bounds) plus a seeded reservoir
        union. While the combined reservoirs fit, the union is lossless, so
        fleet p50/p95/p99 over merged processes equal the single-process
        oracle over the same observations; beyond the cap a deterministic
        seeded subsample keeps percentile estimates stable across runs."""
        if not isinstance(other, Histogram):
            raise TypeError(f"cannot merge {type(other).__name__} into histogram")
        # snapshot the other side under ITS lock first, then apply under ours
        # — never hold both locks (merge in both directions would deadlock)
        with other._lock:
            o_counts = list(other._counts)
            o_sum, o_count = other._sum, other._count
            o_min, o_max = other._min, other._max
            o_samples = list(other._samples)
        self._merge_state(other.bounds, o_counts, o_sum, o_count,
                          o_min, o_max, o_samples)

    def _merge_state(self, bounds, counts, sum_, count, mn, mx, samples) -> None:
        """Apply a consistent remote-histogram state (already detached from
        any lock) into this instrument. Shared by `merge` (live instrument)
        and `MetricsRegistry.merge` (wire snapshot)."""
        bounds = tuple(float(b) for b in bounds)
        if bounds != self.bounds:
            raise ValueError(
                f"histogram {self.name!r}: cannot merge mismatched bucket "
                f"bounds ({len(bounds)} vs {len(self.bounds)} bounds)")
        if len(counts) != len(self._counts):
            raise ValueError(
                f"histogram {self.name!r}: bucket count array length "
                f"{len(counts)} != {len(self._counts)}")
        count = int(count)
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += int(c)
            self._sum += float(sum_)
            self._count += count
            if count > 0:
                if mn is not None:
                    self._min = min(self._min, float(mn))
                if mx is not None:
                    self._max = max(self._max, float(mx))
            pool = self._samples + [float(v) for v in samples]
            if len(pool) > self._reservoir_max:
                # deterministic union past the cap: seed from the name plus
                # the combined count so repeated merges of the same streams
                # pick the same subsample (bench/test stability), while
                # successive pushes from a growing stream still re-sample
                rng = random.Random(0x5EED
                                    ^ zlib.crc32(self.name.encode("utf-8"))
                                    ^ (self._count & 0xFFFFFFFF))
                pool = rng.sample(pool, self._reservoir_max)
            self._samples = pool


class MetricsRegistry:
    """Get-or-create registry of instruments keyed by (name, labels).

    One registry per process is the normal shape (`default_registry()`); tests
    construct private ones. A name is bound to ONE instrument kind — asking
    for a gauge under an existing counter name raises, the mistake Prometheus
    servers reject at scrape time.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[tuple, _Metric] = {}  # (name, labels) -> instrument
        self._kinds: dict[str, str] = {}
        self._help: dict[str, str] = {}

    # --- get-or-create ----------------------------------------------------------------
    def _get(self, cls, name: str, help: str, labels: Optional[dict], **kw):
        frozen = _freeze_labels(labels)
        key = (name, frozen)
        with self._lock:
            m = self._metrics.get(key)
            if m is not None:
                if m.kind != cls.kind:
                    raise TypeError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"requested {cls.kind}")
                return m
            existing = self._kinds.get(name)
            if existing is not None and existing != cls.kind:
                raise TypeError(
                    f"metric name {name!r} already bound to kind {existing}")
            m = cls(name, help=help, labels=frozen, **kw)
            self._metrics[key] = m
            self._kinds[name] = cls.kind
            if help:
                self._help.setdefault(name, help)
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[dict] = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[dict] = None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[dict] = None,
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  reservoir: int = DEFAULT_RESERVOIR) -> Histogram:
        return self._get(Histogram, name, help, labels,
                         buckets=buckets, reservoir=reservoir)

    # --- introspection / reset --------------------------------------------------------
    def find(self, name: str, labels: Optional[dict] = None) -> Optional[_Metric]:
        """Look up an instrument WITHOUT creating it (None when absent).
        Read-side callers — the serving daemon's health surface reading a
        model's queue-wait percentiles, tests asserting absence — must not
        materialize empty series just by asking."""
        with self._lock:
            return self._metrics.get((name, _freeze_labels(labels)))

    def collect(self) -> list[_Metric]:
        with self._lock:
            return sorted(self._metrics.values(),
                          key=lambda m: (m.name, m.labels))

    def reset(self) -> None:
        """Drop every instrument (tests / bench isolation — a live service
        never resets; Prometheus counters are cumulative by contract)."""
        with self._lock:
            self._metrics.clear()
            self._kinds.clear()
            self._help.clear()

    # --- export -----------------------------------------------------------------------
    def snapshot(self, samples: bool = False) -> dict:
        """Plain-JSON view: {name: {kind, help, series: [{labels, ...}]}}.
        `samples=True` makes histogram series carry their raw internals so the
        snapshot is losslessly mergeable on the far side (`merge`) — the shape
        METRICS frames and `/fleet/metrics` federation push over the wire."""
        out: dict[str, dict] = {}
        with self._lock:  # one consistent copy vs concurrent _get/reset
            help_map = dict(self._help)
        for m in self.collect():
            entry = out.setdefault(m.name, {
                "kind": m.kind, "help": help_map.get(m.name, ""),
                "series": [],
            })
            snap = (m.snapshot(samples=True)
                    if samples and isinstance(m, Histogram) else m.snapshot())
            entry["series"].append({"labels": dict(m.labels), **snap})
        return out

    def merge(self, snapshot: dict, labels: Optional[dict] = None) -> None:
        """Fold a remote registry `snapshot()` into this registry, optionally
        stamping every folded series with extra labels (the federation layer
        adds `process`/`role` here so per-process series stay distinguishable
        after aggregation). Counters add, gauges take the remote level,
        histograms add bucket counts exactly and union reservoirs seeded
        (lossless while combined counts fit the reservoir — see
        `Histogram.merge`). Histogram series without raw internals (a plain
        `snapshot()`) degrade gracefully: bucket counts are de-cumulated from
        the exposition buckets and the reservoir contribution is empty."""
        extra = dict(labels) if labels else {}
        for name in sorted(snapshot):
            fam = snapshot[name]
            kind = fam.get("kind")
            help_text = fam.get("help", "")
            for series in fam.get("series", []):
                lab = dict(series.get("labels") or {})
                lab.update(extra)
                lab = lab or None
                if kind == "counter":
                    self.counter(name, help=help_text, labels=lab).inc(
                        float(series.get("value", 0.0)))
                elif kind == "gauge":
                    self.gauge(name, help=help_text, labels=lab).set(
                        float(series.get("value", 0.0)))
                elif kind == "histogram":
                    bounds = series.get("bounds")
                    raw = series.get("raw_counts")
                    reservoir = series.get("reservoir", [])
                    if bounds is None or raw is None:
                        bounds, raw = _decumulate_buckets(
                            series.get("buckets", {}),
                            int(series.get("count", 0)))
                        reservoir = []
                    h = self.histogram(name, help=help_text, labels=lab,
                                       buckets=bounds)
                    h._merge_state(bounds, raw, series.get("sum", 0.0),
                                   series.get("count", 0),
                                   series.get("min"), series.get("max"),
                                   reservoir)
                else:
                    raise ValueError(
                        f"cannot merge metric {name!r} of kind {kind!r}")

    def to_prometheus(self) -> str:
        """Text exposition format 0.0.4 (the format every Prometheus scraper
        and `promtool check metrics` accepts)."""
        lines: list[str] = []
        seen: set[str] = set()
        with self._lock:  # one consistent copy vs concurrent _get/reset
            help_map = dict(self._help)
        for m in self.collect():
            if m.name not in seen:
                seen.add(m.name)
                help_text = help_map.get(m.name, "") or m.name
                lines.append(f"# HELP {m.name} {_escape(help_text)}")
                lines.append(f"# TYPE {m.name} {m.kind}")
            ls = _label_str(m.labels)
            if isinstance(m, Histogram):
                snap = m.snapshot()
                for le, cum in snap["buckets"].items():
                    lab = list(m.labels) + [("le", le)]
                    lines.append(
                        f"{m.name}_bucket{_label_str(tuple(lab))} {cum}")
                lines.append(f"{m.name}_sum{ls} {_fmt(snap['sum'])}")
                lines.append(f"{m.name}_count{ls} {snap['count']}")
            else:
                lines.append(f"{m.name}{ls} {_fmt(m.value)}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _decumulate_buckets(buckets: dict, total: int) -> tuple[list, list]:
    """Recover (bounds, per-bucket raw counts) from a snapshot's cumulative
    exposition buckets — the degraded merge path for snapshots that did not
    ship raw internals. The +Inf slot absorbs total minus the last bound's
    cumulative count."""
    bounds = sorted(float(k) for k in buckets if k != "+Inf")
    raw, prev = [], 0
    for b in bounds:
        cum = int(buckets[f"{b:g}"])
        raw.append(cum - prev)
        prev = cum
    raw.append(int(total) - prev)
    return bounds, raw


# --- exposition validity check ----------------------------------------------------------
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^}]*\})?"
    r"\s+(?P<value>[^\s]+)(\s+\d+)?$")
_LABEL_PAIR_RE = re.compile(
    r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"$')


def parse_prometheus(text: str) -> dict[str, dict]:
    """Strictly parse a text exposition; raises ValueError on any malformed
    line. Returns {metric_name: {"type": ..., "samples": [(name, labels,
    value)]}} — `tools/ci_check.sh` and the tests share this as the format
    lint (HELP/TYPE ordering, label syntax, numeric values, histogram _sum/
    _count/_bucket consistency)."""
    families: dict[str, dict] = {}
    typed: dict[str, str] = {}
    seen_series: set[tuple] = set()
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 3 or not _NAME_RE.match(parts[2]):
                raise ValueError(f"line {i}: malformed HELP: {line!r}")
            families.setdefault(parts[2], {"type": None, "samples": []})
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or not _NAME_RE.match(parts[2]) \
                    or parts[3] not in ("counter", "gauge", "histogram",
                                        "summary", "untyped"):
                raise ValueError(f"line {i}: malformed TYPE: {line!r}")
            if parts[2] in typed:
                raise ValueError(f"line {i}: duplicate TYPE for {parts[2]}")
            typed[parts[2]] = parts[3]
            families.setdefault(parts[2], {"type": None, "samples": []})
            families[parts[2]]["type"] = parts[3]
            continue
        if line.startswith("#"):
            continue  # free comment
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {i}: malformed sample: {line!r}")
        labels_body = (m.group("labels") or "{}")[1:-1]
        pairs: list[str] = []
        if labels_body:
            pairs = _split_label_pairs(labels_body, i, line)
            for pair in pairs:
                if not _LABEL_PAIR_RE.match(pair):
                    raise ValueError(f"line {i}: malformed label {pair!r}")
        # duplicate (name, labels) series are a merge bug (two processes'
        # series folded without distinguishing labels): fail loudly here so
        # the CI exposition lint catches a bad federation pass. Label ORDER
        # is normalized — `a="1",b="2"` and `b="2",a="1"` are one series.
        series_key = (m.group("name"), tuple(sorted(pairs)))
        if series_key in seen_series:
            raise ValueError(
                f"line {i}: duplicate series {m.group('name')}"
                f"{m.group('labels') or ''}")
        seen_series.add(series_key)
        raw_v = m.group("value")
        if raw_v not in ("+Inf", "-Inf", "NaN"):
            try:
                float(raw_v)
            except ValueError:
                raise ValueError(
                    f"line {i}: non-numeric value {raw_v!r}") from None
        sample_name = m.group("name")
        family = sample_name
        for suf in ("_bucket", "_sum", "_count"):
            base = sample_name[: -len(suf)] if sample_name.endswith(suf) else None
            if base and typed.get(base) in ("histogram", "summary"):
                family = base
                break
        families.setdefault(family, {"type": typed.get(family), "samples": []})
        families[family]["samples"].append(
            (sample_name, m.group("labels") or "", raw_v))
    # histogram consistency: every histogram family needs _bucket/_sum/_count
    for name, fam in families.items():
        if fam.get("type") == "histogram" and fam["samples"]:
            kinds = {s[0] for s in fam["samples"]}
            for suf in ("_bucket", "_sum", "_count"):
                if name + suf not in kinds:
                    raise ValueError(
                        f"histogram {name} missing {name}{suf} samples")
            if not any('le="+Inf"' in s[1] for s in fam["samples"]
                       if s[0] == name + "_bucket"):
                raise ValueError(f"histogram {name} missing +Inf bucket")
    return families


def _split_label_pairs(body: str, lineno: int, line: str) -> list[str]:
    """Split `a="x",b="y,z"` on commas OUTSIDE quotes."""
    pairs, cur, in_q, esc = [], [], False, False
    for ch in body:
        if esc:
            cur.append(ch)
            esc = False
            continue
        if ch == "\\":
            cur.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
            cur.append(ch)
            continue
        if ch == "," and not in_q:
            pairs.append("".join(cur))
            cur = []
            continue
        cur.append(ch)
    if in_q:
        raise ValueError(f"line {lineno}: unterminated label quote: {line!r}")
    if cur:
        pairs.append("".join(cur))
    return pairs


# --- process default --------------------------------------------------------------------
_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem publishes into (mesh
    placement counters, pipeline stalls, serving routing/latency, drift
    gauges). AppMetrics' `metrics` section and `op monitor --prom/--json`
    export exactly this."""
    return _DEFAULT


def reset_default_registry() -> None:
    """Test/bench isolation only — see MetricsRegistry.reset()."""
    _DEFAULT.reset()
