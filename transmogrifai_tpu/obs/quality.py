"""Online model-quality plane: mergeable (score, label) sketches, windowed
AuPR/AuROC/Brier, and edge-triggered quality alerts.

The serving stack can see its own latency (the fleet plane) and its input
distribution (ServingMonitor covariate drift), but it is blind to the only
thing users care about: whether predictions are still *right*. A concept
flip — the label rule inverts while the feature marginals stay put — leaves
every `serving_js_divergence` gauge flat and the autopilot asleep. This
module is the missing signal:

  sketch   a `QualitySketch` holds INTEGER (pos, neg) counts over K fixed
           score bins in [0, 1] — nothing else. It is a monoid (merge adds
           counts), and because the state is integers, merge order can never
           perturb it: the fleet-merged sketch is the SAME OBJECT the
           single-process oracle holds, so every derived metric (AuPR,
           AuROC, Brier, calibration) is bit-for-bit identical. The same
           discipline FeatureDistribution uses for drift histograms,
           applied to ground truth.
  monitor  a `QualityMonitor` folds joined (score, label) pairs (the
           `LabelJoiner`'s output) into a sliding-window sketch, derives the
           windowed metrics, exports them as `serving_quality_*` gauges plus
           one `serving_quality_scores{model, label}` histogram whose bucket
           bounds ARE the sketch's bin edges — histograms federate exactly
           through `MetricsRegistry.merge`/`FleetAggregator`, so the gauges
           are for dashboards and the histogram is the ground truth a
           remote aggregator recomputes metrics from (`quality_from_
           snapshot`).
  alert    train stamps the holdout metric into model.json
           (`quality_baseline`); `check()` fires an edge-triggered
           `QualityAlert` when the windowed metric breaches the baseline by
           `margin`, emits `quality:breach` (a flight-recorder dump
           trigger), and re-arms on recovery — the same rising/falling-edge
           contract ServingMonitor keeps for covariate drift. The autopilot
           reads `active` as its quality trigger tier.

The pure-Python estimators mirror `evaluators/metrics_ops.py` semantics at
bin granularity: tied scores (one bin = one tied run) contribute a single
PR/ROC curve point, the PR curve opens at (recall 0, first precision), and
P/N denominators floor at 1.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Mapping, Optional, Sequence

import numpy as np

__all__ = [
    "QUALITY_BINS", "QualityAlert", "QualitySketch", "QualityMonitor",
    "QualityThresholds", "quality_from_snapshot", "sketch_metrics",
]

#: fixed score-bin resolution of every sketch/histogram in the plane. All
#: sketches share it so merges are always well-formed; 64 bins keep binned
#: AuPR within ~1e-3 of the exact-score value on smooth score distributions
#: while the per-model histogram stays 2 x 64 integers on the wire.
QUALITY_BINS = 64


def _bin_edges(bins: int) -> list[float]:
    """Histogram bucket bounds matching `_bin_of`: bucket k is
    (k/bins, (k+1)/bins] under the registry's `bisect_left` placement, so a
    score histogram observed at BIN CENTERS lands count-for-count on the
    sketch's bins."""
    return [(k + 1) / bins for k in range(bins)]


def _bin_of(score: float, bins: int) -> int:
    """clip(int(s * bins), 0, bins - 1) — the same rule as
    metrics_ops.bin_score_metrics, so offline and online calibration bins
    line up."""
    k = int(score * bins)
    return 0 if k < 0 else (bins - 1 if k >= bins else k)


class QualitySketch:
    """Integer (pos, neg) counts per score bin — the mergeable quality state.

    The whole point is what this class does NOT hold: no float sums, no
    wall-clock, no reservoir. Float addition is non-associative, so any float
    in the monoid state would let merge ORDER leak into the fleet-merged
    metrics; integer counts make `merge` exactly commutative/associative and
    the derived metrics a pure function of the counts.
    """

    __slots__ = ("bins", "pos", "neg")

    def __init__(self, bins: int = QUALITY_BINS):
        self.bins = int(bins)
        if self.bins < 2:
            raise ValueError(f"QualitySketch needs >= 2 bins, got {bins}")
        self.pos = [0] * self.bins
        self.neg = [0] * self.bins

    # --- fold -------------------------------------------------------------------------
    def observe(self, score: float, label: float) -> None:
        k = _bin_of(float(score), self.bins)
        if float(label) > 0.5:
            self.pos[k] += 1
        else:
            self.neg[k] += 1

    def observe_many(self, pairs: Sequence[tuple]) -> None:
        for score, label in pairs:
            self.observe(score, label)

    # --- monoid -----------------------------------------------------------------------
    def merge(self, other: "QualitySketch") -> None:
        if other.bins != self.bins:
            raise ValueError(
                f"cannot merge QualitySketch({other.bins} bins) into "
                f"{self.bins} bins — the plane fixes one resolution")
        for k in range(self.bins):
            self.pos[k] += other.pos[k]
            self.neg[k] += other.neg[k]

    def copy(self) -> "QualitySketch":
        out = QualitySketch(self.bins)
        out.pos = list(self.pos)
        out.neg = list(self.neg)
        return out

    def reset(self) -> None:
        self.pos = [0] * self.bins
        self.neg = [0] * self.bins

    # --- (de)serialization (checkpoint + wire) ------------------------------------------
    def to_json(self) -> dict:
        return {"version": 1, "bins": self.bins,
                "pos": list(self.pos), "neg": list(self.neg)}

    @classmethod
    def from_json(cls, doc: Mapping) -> "QualitySketch":
        sk = cls(int(doc["bins"]))
        pos, neg = list(doc["pos"]), list(doc["neg"])
        if len(pos) != sk.bins or len(neg) != sk.bins:
            raise ValueError("QualitySketch payload length != bins")
        sk.pos = [int(c) for c in pos]
        sk.neg = [int(c) for c in neg]
        return sk

    @classmethod
    def from_counts(cls, pos: Sequence[int], neg: Sequence[int],
                    ) -> "QualitySketch":
        """Rebuild from two raw per-bin count vectors (the federation path:
        `serving_quality_scores{label=...}` histogram `raw_counts`)."""
        if len(pos) != len(neg):
            raise ValueError("pos/neg count vectors differ in length")
        sk = cls(len(pos))
        sk.pos = [int(c) for c in pos]
        sk.neg = [int(c) for c in neg]
        return sk

    # --- totals -----------------------------------------------------------------------
    @property
    def n(self) -> int:
        return sum(self.pos) + sum(self.neg)

    @property
    def n_pos(self) -> int:
        return sum(self.pos)

    @property
    def n_neg(self) -> int:
        return sum(self.neg)

    def metrics(self) -> dict:
        return sketch_metrics(self)


def sketch_metrics(sk: QualitySketch, calibration_bins: int = 10) -> dict:
    """AuPR / AuROC / Brier / calibration from integer bin counts.

    One bin = one tied-score run, so the curve logic is metrics_ops'
    boundary-masked sweep with the mask made explicit: descending bins each
    contribute ONE cumulative (TP, FP) point; trapezoids integrate between
    them. Every float here is DERIVED from the same integers in the same
    order, so two sketches with equal counts produce bitwise-equal metrics —
    the property the fleet-vs-oracle contract pins.
    """
    bins = sk.bins
    P, N = sk.n_pos, sk.n_neg
    n = P + N
    out: dict[str, Any] = {"n": n, "n_pos": P, "n_neg": N,
                           "pos_rate": (P / n) if n else 0.0}
    if n == 0:
        out.update({"AuPR": 0.0, "AuROC": 0.5, "BrierScore": 0.0,
                    "calibration": []})
        return out

    # --- AuROC: pair-counting over descending bins (exact for binned data;
    # ties inside a bin count 1/2, metrics_ops' trapezoid does the same)
    denom_roc = P * N
    if denom_roc:
        auc = 0
        neg_below = N  # negatives in strictly lower bins than the current
        for k in range(bins - 1, -1, -1):
            neg_below -= sk.neg[k]
            auc += 2 * sk.pos[k] * neg_below + sk.pos[k] * sk.neg[k]
        out["AuROC"] = auc / (2.0 * denom_roc)
    else:
        out["AuROC"] = 0.5

    # --- AuPR: threshold sweep high->low; curve starts at (0, first_prec)
    # like metrics_ops.binary_curve_aucs; P floors at 1 in the denominator
    tp = 0
    fp = 0
    p_den = P if P else 1
    prev_recall = 0.0
    prev_prec: Optional[float] = None
    aupr = 0.0
    for k in range(bins - 1, -1, -1):
        if sk.pos[k] == 0 and sk.neg[k] == 0:
            continue
        tp += sk.pos[k]
        fp += sk.neg[k]
        recall = tp / p_den
        prec = tp / (tp + fp)
        if prev_prec is None:
            prev_prec = prec  # the (recall 0, first precision) opening point
        aupr += (recall - prev_recall) * (prec + prev_prec) / 2.0
        prev_recall, prev_prec = recall, prec
    out["AuPR"] = aupr

    # --- Brier at bin centers: sum over bins of pos*(1-c)^2 + neg*c^2
    brier = 0.0
    for k in range(bins):
        if sk.pos[k] == 0 and sk.neg[k] == 0:
            continue
        c = (k + 0.5) / bins
        brier += sk.pos[k] * (1.0 - c) ** 2 + sk.neg[k] * c ** 2
    out["BrierScore"] = brier / n

    # --- calibration reliability: coarse bins of (mean predicted, observed)
    cal = []
    step = max(1, bins // max(1, calibration_bins))
    for lo in range(0, bins, step):
        hi = min(lo + step, bins)
        cp = sum(sk.pos[lo:hi])
        cn = sum(sk.neg[lo:hi])
        if cp + cn == 0:
            continue
        centers = 0.0
        for k in range(lo, hi):
            centers += (sk.pos[k] + sk.neg[k]) * ((k + 0.5) / bins)
        cal.append({"lo": lo / bins, "hi": hi / bins,
                    "n": cp + cn,
                    "mean_score": centers / (cp + cn),
                    "pos_rate": cp / (cp + cn)})
    out["calibration"] = cal
    return out


@dataclass(frozen=True)
class QualityThresholds:
    """When a windowed metric becomes an alert.

    `margin` is the direction-aware breach distance from the stamped
    baseline (AuPR 0.91 at train, margin 0.1 -> alert under 0.81).
    `min_joined` gates BOTH checks — a three-pair window alerting on noise
    would page someone at 3 a.m. for a coin flip."""

    margin: float = 0.1
    min_joined: int = 64

    def to_json(self) -> dict:
        return {"margin": self.margin, "min_joined": self.min_joined}


@dataclass
class QualityAlert:
    """One baseline breach, structured for handlers/logs."""

    model: str
    metric: str
    value: float
    baseline: float
    margin: float
    joined: int
    message: str

    def to_json(self) -> dict:
        return {"model": self.model, "metric": self.metric,
                "value": round(self.value, 6),
                "baseline": round(self.baseline, 6),
                "margin": self.margin, "joined": self.joined,
                "message": self.message}


class QualityMonitor:
    """Windowed quality tracking + edge-triggered baseline alerts for one
    served model.

    Thread-safe: `observe_pair` arrives from the feedback route's handler
    threads while `check`/`report` run on the autopilot's poll thread. The
    registry carries two faces of the same data:

      serving_quality_scores{model, label}   histogram, bounds = bin edges —
                                             the EXACT federation carrier
                                             (cumulative; never windowed)
      serving_quality_{aupr,auroc,brier}     derived gauges over the current
                                             window (dashboards, `op top`)
      serving_quality_joined_pairs           gauge: pairs in the window
    """

    def __init__(self, baseline: Optional[Mapping] = None,
                 thresholds: Optional[QualityThresholds] = None,
                 registry=None, source: str = "serve",
                 bins: int = QUALITY_BINS,
                 window_pairs: Optional[int] = 4096,
                 check_every: int = 64):
        from .metrics import default_registry

        #: {"metric", "value", "larger_is_better", ...} — Workflow.train's
        #: `quality_baseline` stamp. None disables alerting (metrics still
        #: compute and export: a model trained before the stamp existed can
        #: still be WATCHED, just not paged on).
        self.baseline = dict(baseline) if baseline else None
        self.thresholds = thresholds or QualityThresholds()
        self.registry = (registry if registry is not None
                         else default_registry())
        self.source = source
        self._model_labels = ({"model": source}
                              if source and source != "serve" else {})
        #: sliding window: the alerting sketch resets every `window_pairs`
        #: joined pairs (after a final check over the full window) so the
        #: signal tracks RECENT truth; the cumulative sketch feeds the
        #: federation histogram and never resets. None = cumulative only.
        self.window_pairs = (max(1, int(window_pairs))
                             if window_pairs else None)
        self.check_every = max(1, int(check_every))
        self._lock = threading.Lock()
        self.window = QualitySketch(bins)
        self.cumulative = QualitySketch(bins)
        self.pairs = 0
        self._pairs_in_window = 0
        self._active: set[str] = set()
        self.alerts: list[QualityAlert] = []
        self._max_alerts = 256
        edges = _bin_edges(bins)
        self._hist = {
            "pos": self.registry.histogram(
                "serving_quality_scores",
                help="joined prediction scores by true label — bucket "
                     "bounds are the quality-sketch bin edges, so "
                     "fleet-merged buckets rebuild the exact sketch",
                labels={"label": "pos", **self._model_labels},
                buckets=edges, reservoir=0),
            "neg": self.registry.histogram(
                "serving_quality_scores",
                help="joined prediction scores by true label — bucket "
                     "bounds are the quality-sketch bin edges, so "
                     "fleet-merged buckets rebuild the exact sketch",
                labels={"label": "neg", **self._model_labels},
                buckets=edges, reservoir=0),
        }
        self._gauges: dict[str, Any] = {}

    @classmethod
    def for_model(cls, model, thresholds: Optional[QualityThresholds] = None,
                  registry=None, **kwargs) -> "QualityMonitor":
        """Build from a WorkflowModel's `quality_baseline` stamp (train
        stamps it from the selector's holdout metrics; load restores it).
        A model without the stamp still gets a monitor — unalerted."""
        baseline = getattr(model, "quality_baseline", None)
        return cls(baseline, thresholds=thresholds, registry=registry,
                   **kwargs)

    def _gauge(self, name: str, help_text: str):
        g = self._gauges.get(name)
        if g is None:
            g = self._gauges[name] = self.registry.gauge(
                name, help=help_text, labels=dict(self._model_labels))
        return g

    # --- fold (feedback-join output; never raises into the caller) ----------------------
    def observe_pair(self, score: float, label: float) -> None:
        self.observe_pairs([(score, label)])

    def observe_pairs(self, pairs) -> None:
        """Fold a batch of joined (score, label) pairs under ONE lock
        acquisition; a check fires when the batch crosses a `check_every`
        boundary (for a single pair this is exactly the old per-pair
        cadence). Never raises into the caller."""
        try:
            if not pairs:
                return
            bins = self.window.bins
            # vectorized fold: bin every pair at once (astype truncates
            # toward zero exactly like `_bin_of`'s int()), then apply the
            # per-bin count deltas — O(pairs) C work + O(bins) Python work,
            # so a 512-pair feedback batch costs about what one pair used to
            arr = np.asarray(pairs, dtype=np.float64)
            if arr.ndim != 2 or not np.isfinite(arr[:, 0]).all():
                raise ValueError("malformed (score, label) pairs")
            k = np.clip((arr[:, 0] * bins).astype(np.int64), 0, bins - 1)
            pos_mask = arr[:, 1] > 0.5
            pc = np.bincount(k[pos_mask], minlength=bins)
            nc = np.bincount(k[~pos_mask], minlength=bins)
            pos_bins = np.nonzero(pc)[0]
            neg_bins = np.nonzero(nc)[0]
            n = int(arr.shape[0])
            with self._lock:
                # one k feeds window AND cumulative (same bin count)
                wp, wn = self.window.pos, self.window.neg
                cp, cn = self.cumulative.pos, self.cumulative.neg
                for i in pos_bins:
                    c = int(pc[i])
                    wp[i] += c
                    cp[i] += c
                for i in neg_bins:
                    c = int(nc[i])
                    wn[i] += c
                    cn[i] += c
                before = self.pairs
                self.pairs += n
                self._pairs_in_window += n
                due = (self.pairs // self.check_every
                       > before // self.check_every)
                window_full = (self.window_pairs is not None
                               and self._pairs_in_window >= self.window_pairs)
            # the histogram observes the BIN CENTER, not the raw score: the
            # bucket a center lands in is exactly the sketch bin, so merged
            # raw_counts rebuild the sketch count-for-count (weighted fold —
            # the monitor's histograms carry no reservoir)
            for i in pos_bins:
                self._hist["pos"].observe_weighted((int(i) + 0.5) / bins,
                                                   int(pc[i]))
            for i in neg_bins:
                self._hist["neg"].observe_weighted((int(i) + 0.5) / bins,
                                                   int(nc[i]))
            if due or window_full:
                self._check_safe()
            if window_full:
                with self._lock:
                    self.window.reset()
                    self._pairs_in_window = 0
        except Exception:
            self.registry.counter(
                "serving_quality_errors_total",
                help="internal quality-monitor failures swallowed off the "
                     "feedback path").inc()

    def _check_safe(self) -> None:
        try:
            self.check()
        except Exception:
            self.registry.counter(
                "serving_quality_errors_total",
                help="internal quality-monitor failures swallowed off the "
                     "feedback path").inc()

    # --- decision -----------------------------------------------------------------------
    def _window_metrics(self) -> dict:
        with self._lock:
            sk = self.window.copy()
        return sketch_metrics(sk)

    def check(self) -> list[QualityAlert]:
        """Evaluate the windowed metric against the baseline; returns alerts
        NEWLY fired by this call. Edge-triggered: an episode re-arms only
        after the metric recovers past the breach line (or `resolve_active`
        clears it). Also refreshes the derived gauges — check() is the one
        place window metrics turn into registry levels."""
        from .. import obs

        m = self._window_metrics()
        self._gauge("serving_quality_aupr",
                    "windowed AuPR over joined (score, label) pairs"
                    ).set(m["AuPR"])
        self._gauge("serving_quality_auroc",
                    "windowed AuROC over joined (score, label) pairs"
                    ).set(m["AuROC"])
        self._gauge("serving_quality_brier",
                    "windowed Brier score over joined (score, label) pairs"
                    ).set(m["BrierScore"])
        self._gauge("serving_quality_joined_pairs",
                    "joined (score, label) pairs in the current window"
                    ).set(m["n"])
        base = self.baseline
        th = self.thresholds
        new: list[QualityAlert] = []
        cleared: list[tuple] = []
        if not base or m["n"] < th.min_joined:
            return new
        metric = str(base.get("metric", "AuPR"))
        value = m.get(metric)
        if value is None:
            return new
        baseline_v = float(base.get("value", 0.0))
        larger = bool(base.get("larger_is_better", True))
        if larger:
            breached = value < baseline_v - th.margin
        else:
            breached = value > baseline_v + th.margin
        with self._lock:
            if breached:
                if metric not in self._active:
                    self._active.add(metric)
                    alert = QualityAlert(
                        model=self.source, metric=metric, value=float(value),
                        baseline=baseline_v, margin=th.margin,
                        joined=int(m["n"]),
                        message=(f"{self.source}: windowed {metric} "
                                 f"{value:.4f} breached baseline "
                                 f"{baseline_v:.4f} by > {th.margin} over "
                                 f"{m['n']} joined pairs"))
                    new.append(alert)
                    if len(self.alerts) < self._max_alerts:
                        self.alerts.append(alert)
            elif metric in self._active:
                self._active.discard(metric)
                cleared.append((metric, float(value), baseline_v))
        for alert in new:
            # `quality:breach` is a flight-recorder dump trigger: the event
            # ring around a quality regression is exactly what post-mortems
            # need (what swapped, what drifted, what fed back)
            obs.add_event("quality:breach", **alert.to_json())
            self.registry.counter(
                "serving_quality_alerts_total",
                help="quality-baseline breaches (edge-triggered)",
                labels={"metric": alert.metric,
                        **self._model_labels}).inc()
        for metric, value, baseline_v in cleared:
            obs.add_event("quality:cleared", model=self.source,
                          metric=metric, value=round(value, 6),
                          baseline=round(baseline_v, 6))
            self.registry.counter(
                "serving_quality_cleared_total",
                help="quality episodes that ended: the windowed metric "
                     "recovered past the breach line",
                labels={"metric": metric, **self._model_labels}).inc()
        return new

    @property
    def active(self) -> list[str]:
        with self._lock:
            return sorted(self._active)

    def resolve_active(self, reason: str = "resolved") -> list[str]:
        """Explicitly clear active episodes (the autopilot calls this on a
        DEMOTED champion's monitor — no feedback will ever reach it again,
        so the falling edge must be synthesized or the episode latches)."""
        from .. import obs

        with self._lock:
            resolved = sorted(self._active)
            self._active.clear()
        for metric in resolved:
            obs.add_event("quality:cleared", model=self.source,
                          metric=metric, reason=reason)
            self.registry.counter(
                "serving_quality_cleared_total",
                help="quality episodes that ended: the windowed metric "
                     "recovered past the breach line",
                labels={"metric": metric, **self._model_labels}).inc()
        return resolved

    # --- reporting ----------------------------------------------------------------------
    def report(self) -> dict:
        m = self._window_metrics()
        with self._lock:
            return {
                "source": self.source,
                "pairs": self.pairs,
                "window": m,
                "cumulative_pairs": self.cumulative.n,
                "baseline": dict(self.baseline) if self.baseline else None,
                "thresholds": self.thresholds.to_json(),
                "alerts": [a.to_json() for a in self.alerts],
                "active_alerts": sorted(self._active),
            }


# --- federation read path ----------------------------------------------------------------
def quality_from_snapshot(metrics_snapshot: Mapping) -> dict[str, dict]:
    """Per-model quality metrics recomputed from a (merged) registry
    snapshot's `serving_quality_scores` histogram series.

    THE shared read path: `op top`'s quality panel, `op monitor --quality`,
    and the federation test all call this on
    `FleetAggregator.snapshot()["metrics"]`. Because the histogram's bucket
    counts merge exactly and the sketch is rebuilt from those integer
    counts, the result over a fleet equals the single-process oracle
    bit-for-bit. Series must carry `raw_counts` (snapshot(samples=True) —
    every federation surface already does)."""
    fam = metrics_snapshot.get("serving_quality_scores") or {}
    per_model: dict[str, dict[str, list[int]]] = {}
    for series in fam.get("series", []):
        labels = series.get("labels") or {}
        model = labels.get("model", "serve")
        side = labels.get("label")
        raw = series.get("raw_counts")
        if side not in ("pos", "neg") or raw is None or len(raw) < 3:
            continue
        # raw_counts carries one +Inf overflow slot past the real bins; the
        # monitor observes bin centers (< 1.0 = the last bound) so it is
        # always 0 — fold it into the top bin anyway rather than drop counts
        counts = [int(c) for c in raw[:-1]]
        counts[-1] += int(raw[-1])
        slot = per_model.setdefault(model, {})
        if side in slot:  # several processes: merged registries pre-fold by
            prior = slot[side]  # (role, process) label — fold the rest here
            if len(prior) != len(counts):
                continue
            slot[side] = [a + b for a, b in zip(prior, counts)]
        else:
            slot[side] = counts
    out: dict[str, dict] = {}
    for model, sides in sorted(per_model.items()):
        bins = len(sides.get("pos") or sides.get("neg") or [])
        if not bins:
            continue
        pos = sides.get("pos") or [0] * bins
        neg = sides.get("neg") or [0] * bins
        if len(pos) != len(neg):
            continue
        sk = QualitySketch.from_counts(pos, neg)
        out[model] = sketch_metrics(sk)
    return out
