"""Fleet observability plane: metrics federation + cross-process trace stitch.

The system is many processes — an ingest coordinator with a worker fleet, the
serving daemon, autopilot, the training run itself — and each keeps its own
`MetricsRegistry` and `Tracer`. This module is the layer that makes them ONE
observable system (the disaggregated-fleet view the tf.data-service story,
arXiv 2210.14826, argues a data service needs):

  - `FleetAggregator` — latest-snapshot-per-process federation. Workers and
    serving replicas push `registry.snapshot(samples=True)` over the framed
    transport (METRICS frame) or HTTP; local registries attach as pull
    sources. `merged()` folds every snapshot into a fresh registry with
    `process`/`role` labels via `MetricsRegistry.merge` — counters sum
    exactly, histogram buckets add exactly, reservoirs union seeded, so fleet
    p50/p95/p99 are well-defined (equal to a single-process oracle while the
    combined reservoirs fit). Exposed at `/fleet/metrics` (daemon), the
    FLEET_METRICS frame (ingest service), `op monitor --fleet`, and `op top`.

  - `MetricsPusher` — the worker-side push cadence: builds METRICS payloads
    from the local registry on an interval, transport-agnostic (the caller
    supplies the send callable, so ingest sockets and HTTP POST both work).

  - `stitch_chrome_traces` — joins per-process Chrome dumps into one
    distributed timeline: one pid lane per process, wall-clock aligned on
    each dump's `t0_unix` anchor, `remote_parent` span links drawn as flow
    arrows, single trace_id asserted in the merged metadata. `op trace-merge`
    and `Tracer.export_chrome(stitched=True)` are thin shells over it.

  - `render_top` — the text body of `op top`: per-role/process rates, queue
    waits, breaker states, drift gauges, and measured-vs-predicted resource
    counters (the PR-15 static ResourceModel calibration feed) with a live
    rel_error column.
"""
from __future__ import annotations

import json
import math
import threading
import time
from typing import Callable, Iterable, Optional, Union

from . import metrics as _metrics

__all__ = [
    "FleetAggregator", "MetricsPusher", "fleet_totals", "measured_resources",
    "render_top",
    "stitch_chrome_traces",
]


class FleetAggregator:
    """Latest-snapshot-per-(role, process) metrics federation.

    Push sources call `ingest()` with a remote registry snapshot (replacing
    that process's previous one — snapshots are cumulative, so latest-wins is
    the correct fold); local registries attach once via `attach_local` and
    are pulled fresh at every `merged()`. Aggregation rebuilds a scratch
    registry from scratch each time, which keeps the fold exact and
    idempotent under repeated pushes from a growing stream.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._snaps: dict[tuple[str, str], dict] = {}
        self._pushed_at: dict[tuple[str, str], float] = {}
        self._locals: dict[tuple[str, str], Callable[[], dict]] = {}

    def attach_local(self, role: str, process: Union[str, int], source) -> None:
        """Register an in-process pull source: a MetricsRegistry or a zero-arg
        callable returning a mergeable snapshot."""
        if hasattr(source, "snapshot"):
            fn = lambda: source.snapshot(samples=True)  # noqa: E731
        else:
            fn = source
        with self._lock:
            self._locals[(str(role), str(process))] = fn

    def ingest(self, role: str, process: Union[str, int], snapshot: dict) -> None:
        """Accept one pushed snapshot (METRICS frame / HTTP POST body)."""
        if not isinstance(snapshot, dict):
            return
        key = (str(role), str(process))
        with self._lock:
            self._snaps[key] = snapshot
            self._pushed_at[key] = time.time()

    def processes(self) -> list[dict]:
        now = time.time()
        with self._lock:
            out = [{"role": r, "process": p, "source": "local"}
                   for (r, p) in self._locals]
            out += [{"role": r, "process": p, "source": "push",
                     "age_s": round(now - self._pushed_at[(r, p)], 3)}
                    for (r, p) in self._snaps]
        out.sort(key=lambda d: (d["role"], d["process"]))
        return out

    def raw_snapshots(self) -> list[dict]:
        """Every per-process snapshot unmerged (`{"role", "process",
        "snapshot"}` rows, local sources pulled fresh) — the FLEET_METRICS
        reply shape, so a remote requester can run the exact merge itself."""
        with self._lock:
            pushed = sorted((r, p, s) for (r, p), s in self._snaps.items())
            locals_ = sorted(self._locals.items())
        out = [{"role": r, "process": p, "snapshot": s} for r, p, s in pushed]
        out += [{"role": r, "process": p, "snapshot": fn()}
                for (r, p), fn in locals_]
        return out

    def merged(self) -> _metrics.MetricsRegistry:
        """A fresh registry holding every process's series, distinguished by
        `process`/`role` labels (no silent collisions — `parse_prometheus`
        rejects duplicate series, so a bad fold fails loudly in CI)."""
        with self._lock:
            pushed = list(self._snaps.items())
            locals_ = list(self._locals.items())
        reg = _metrics.MetricsRegistry()
        for (role, process), snap in sorted(pushed):
            reg.merge(snap, labels={"role": role, "process": process})
        for (role, process), fn in sorted(locals_):
            reg.merge(fn(), labels={"role": role, "process": process})
        return reg

    def to_prometheus(self) -> str:
        return self.merged().to_prometheus()

    def snapshot(self) -> dict:
        """JSON fleet view: who is reporting + the merged metrics."""
        return {"processes": self.processes(),
                "metrics": self.merged().snapshot(samples=True)}


def fleet_totals(metrics_snapshot: dict, name: str) -> float:
    """Sum a counter/gauge across every labeled series of the merged
    snapshot — the fleet-wide total the acceptance check pins against the
    sum of per-process registries."""
    fam = metrics_snapshot.get(name) or {}
    return sum(float(s.get("value", 0.0)) for s in fam.get("series", []))


class MetricsPusher:
    """Interval-driven registry push from a worker/replica process.

    Transport-agnostic: `send` receives the JSON-able payload dict
    (`{"role", "process", "snapshot"}`) and ships it however the caller's
    channel works (METRICS frame on the ingest socket, HTTP POST to the
    daemon's /fleet/metrics). Send failures propagate to the caller, which
    owns the channel's reconnect policy.
    """

    def __init__(self, send: Callable[[dict], None], *, role: str,
                 process: Union[str, int], registry=None,
                 interval_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic):
        self._send = send
        self.role = str(role)
        self.process = str(process)
        self._registry = registry
        self.interval_s = float(interval_s)
        self._clock = clock
        self._last = -math.inf
        self.pushes = 0

    def _reg(self):
        return (self._registry if self._registry is not None
                else _metrics.default_registry())

    def payload(self) -> dict:
        return {"role": self.role, "process": self.process,
                "snapshot": self._reg().snapshot(samples=True)}

    def push(self) -> None:
        self._send(self.payload())
        self._last = self._clock()
        self.pushes += 1

    def maybe_push(self, force: bool = False) -> bool:
        """Push when the interval elapsed (or forced — shutdown paths force a
        final push so fleet totals reflect the complete stream)."""
        if force or self._clock() - self._last >= self.interval_s:
            self.push()
            return True
        return False


# --- cross-process trace stitching ------------------------------------------------------
def _load_payload(x) -> dict:
    if isinstance(x, dict):
        return x
    with open(x) as fh:
        return json.load(fh)


def stitch_chrome_traces(inputs: Iterable, out_path: Optional[str] = None) -> dict:
    """Merge per-process Chrome-trace dumps into one distributed timeline.

    `inputs` mixes in-memory payloads and file paths. Each input becomes its
    own pid lane (named from the dump's role/pid metadata); timestamps are
    re-based onto the earliest dump's wall-clock anchor (`t0_unix`) so events
    from different processes line up; every span carrying a `remote_parent`
    id that resolves to a span/event in ANOTHER input gains a flow arrow
    (ph "s"/"f") from parent to child — the visual stitch of ingest→train→
    serve. The merged metadata reports the root trace_id (the earliest
    process's) plus every distinct trace_id seen, so "one run, one trace_id"
    is checkable downstream.
    """
    payloads = [_load_payload(x) for x in inputs]
    if not payloads:
        raise ValueError("stitch_chrome_traces needs at least one trace dump")
    metas = [p.get("metadata") or {} for p in payloads]
    anchors = [m.get("t0_unix") for m in metas]
    known = [a for a in anchors if isinstance(a, (int, float))]
    base = min(known) if known else 0.0

    events_out: list[dict] = []
    span_index: dict[str, tuple[int, int, float]] = {}
    processes: list[dict] = []
    for i, (payload, meta) in enumerate(zip(payloads, metas)):
        pid = i + 1
        anchor = meta.get("t0_unix")
        off_us = ((anchor - base) * 1e6
                  if isinstance(anchor, (int, float)) else 0.0)
        role = meta.get("role") or f"proc{pid}"
        processes.append({"pid_lane": pid, "role": role,
                          "os_pid": meta.get("pid"),
                          "trace_id": meta.get("trace_id"),
                          "t0_unix": anchor})
        events_out.append({"ph": "M", "name": "process_name", "pid": pid,
                           "args": {"name": f"{role} (pid {meta.get('pid', '?')})"}})
        for ev in payload.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + off_us, 3)
            events_out.append(ev)
            args = ev.get("args") or {}
            sid = args.get("span_id")
            if isinstance(sid, str):
                span_index[sid] = (pid, int(ev.get("tid", 0)),
                                   float(ev.get("ts", 0.0)))

    flows: list[dict] = []
    for ev in events_out:
        args = ev.get("args")
        rp = args.get("remote_parent") if isinstance(args, dict) else None
        if not isinstance(rp, str):
            continue
        src = span_index.get(rp)
        if src is None or src[0] == ev.get("pid"):
            continue
        src_pid, src_tid, src_ts = src
        fid = len(flows) // 2 + 1
        flows.append({"ph": "s", "cat": "stitch", "name": "ctx", "id": fid,
                      "pid": src_pid, "tid": src_tid, "ts": src_ts})
        flows.append({"ph": "f", "bp": "e", "cat": "stitch", "name": "ctx",
                      "id": fid, "pid": ev["pid"],
                      "tid": int(ev.get("tid", 0)),
                      "ts": float(ev.get("ts", 0.0))})
        args["stitched"] = True

    trace_ids: list[str] = []
    for m in metas:
        tid = m.get("trace_id")
        if isinstance(tid, str) and tid not in trace_ids:
            trace_ids.append(tid)
    root_meta = min(
        (m for m in metas if isinstance(m.get("t0_unix"), (int, float))
         and m.get("trace_id")),
        key=lambda m: m["t0_unix"], default=metas[0])
    merged = {
        "traceEvents": events_out + flows,
        "displayTimeUnit": "ms",
        "metadata": {
            "stitched": True,
            "trace_id": root_meta.get("trace_id"),
            "trace_ids": trace_ids,
            "processes": processes,
            "links": len(flows) // 2,
        },
    }
    if out_path:
        import os

        os.makedirs(os.path.dirname(os.path.abspath(out_path)) or ".",
                    exist_ok=True)
        with open(out_path, "w") as fh:
            json.dump(merged, fh)
    return merged


# --- op top rendering -------------------------------------------------------------------
def _per_role(metrics_snapshot: dict) -> dict[tuple[str, str], dict]:
    """Regroup a merged snapshot by (role, process): {metric_name: series}."""
    out: dict[tuple[str, str], dict] = {}
    for name, fam in metrics_snapshot.items():
        for series in fam.get("series", []):
            labels = series.get("labels") or {}
            key = (labels.get("role", "?"), labels.get("process", "?"))
            row = out.setdefault(key, {})
            # several same-name series can land on one (role, process) —
            # e.g. per-edge ingest counters; fold values, keep one histogram
            if fam.get("kind") == "histogram":
                row.setdefault(name, series)
            else:
                prior = row.get(name, {}).get("value", 0.0) \
                    if name in row else 0.0
                row[name] = {"value": prior + float(series.get("value", 0.0)),
                             "kind": fam.get("kind")}
    return out


def _sum_suffix(row: dict, suffix: str) -> float:
    return sum(v.get("value", 0.0) for n, v in row.items()
               if n.endswith(suffix) and "value" in v)


def _per_model_value(metrics_snapshot: Optional[dict], name: str) -> dict:
    """Fold one metric family's series by `model` label (counters/gauges sum
    across processes — each replica's joined count or pending depth adds)."""
    out: dict[str, float] = {}
    for series in ((metrics_snapshot or {}).get(name) or {}).get("series", []):
        model = (series.get("labels") or {}).get("model", "serve")
        out[model] = out.get(model, 0.0) + float(series.get("value", 0.0))
    return out


_BREAKER_STATES = {0: "closed", 1: "OPEN", 2: "half"}


def measured_resources(metrics_snapshot: dict) -> dict:
    """Fleet-measured counterpart of the static ResourceModel totals.

    Pulls the counters the cost model prices — collective traffic and
    resident optimizer state — out of a merged metrics snapshot, keyed to
    match `ResourceModel` totals so callers can diff them directly. Shared
    by the `op top` measured-vs-predicted block and the `op autotune`
    calibration feed (a live fleet's counters are calibration rows the
    tuner did not have to train for)."""
    return {
        "collective_bytes": fleet_totals(metrics_snapshot,
                                         "mesh_collective_bytes_total"),
        "hbm_bytes": fleet_totals(metrics_snapshot,
                                  "train_optimizer_state_bytes"),
    }


def render_top(prev: Optional[dict], cur: dict, dt_s: float,
               predictions: Optional[dict] = None) -> str:
    """Render one `op top` frame from two successive fleet snapshots.

    `prev`/`cur` are `FleetAggregator.snapshot()["metrics"]` dicts (prev may
    be None on the first poll — rates show as 0). `predictions` is the PR-15
    static ResourceModel's totals ({"hbm_bytes", "collective_bytes"}); when
    given, a measured-vs-predicted block with rel_error closes the frame —
    the calibration feed the `op autotune` roadmap item needs.
    """
    prev_roles = _per_role(prev) if prev else {}
    cur_roles = _per_role(cur)
    dt = max(float(dt_s), 1e-9)
    lines = [f"{'ROLE':<14} {'PROC':<10} {'ROWS/S':>10} {'BATCH/S':>9} "
             f"{'QWAIT p95':>11} {'BREAKER':>8} {'DRIFT':>8} {'DUMPS':>6}"]
    for key in sorted(cur_roles):
        row = cur_roles[key]
        before = prev_roles.get(key, {})
        rows_rate = (_sum_suffix(row, "_rows_total")
                     - _sum_suffix(before, "_rows_total")) / dt
        batch_rate = (_sum_suffix(row, "_batches_total")
                      - _sum_suffix(before, "_batches_total")) / dt
        qwait = row.get("ingest_queue_wait_seconds") \
            or row.get("serve_queue_wait_seconds") or {}
        q95 = qwait.get("p95")
        breaker = row.get("breaker_state", {}).get("value")
        drift = max((v.get("value", 0.0) for n, v in row.items()
                     if ("js_divergence" in n or "drift" in n)
                     and "value" in v), default=None)
        dumps = _sum_suffix(row, "flightrec_dumps_total")
        lines.append(
            f"{key[0]:<14.14} {key[1]:<10.10} {rows_rate:>10.1f} "
            f"{batch_rate:>9.1f} "
            f"{(f'{q95 * 1e3:.1f}ms' if q95 is not None else '-'):>11} "
            f"{(_BREAKER_STATES.get(int(breaker), '?') if breaker is not None else '-'):>8} "
            f"{(f'{drift:.4f}' if drift is not None else '-'):>8} "
            f"{dumps:>6.0f}")
    from .quality import quality_from_snapshot

    quality = quality_from_snapshot(cur)
    if quality:
        # model-quality panel: metrics recomputed from the fleet-merged
        # score histograms (the exact-federation carrier), join throughput
        # from counter deltas, pending-join depth from the gauges
        joined_cur = _per_model_value(cur, "feedback_joined_total")
        joined_prev = _per_model_value(prev, "feedback_joined_total")
        pending = _per_model_value(cur, "feedback_pending")
        lines.append("")
        lines.append(f"{'MODEL':<14} {'AuPR':>8} {'BRIER':>8} {'PAIRS':>8} "
                     f"{'JOIN/S':>8} {'PENDING':>8}")
        for model in sorted(quality):
            m = quality[model]
            rate = (joined_cur.get(model, 0.0)
                    - joined_prev.get(model, 0.0)) / dt
            lines.append(
                f"{model:<14.14} {m['AuPR']:>8.4f} "
                f"{m['BrierScore']:>8.4f} {m['n']:>8d} {rate:>8.1f} "
                f"{pending.get(model, 0.0):>8.0f}")
    if predictions:
        measured = measured_resources(cur)
        lines.append("")
        lines.append(f"{'RESOURCE':<18} {'PREDICTED':>14} {'MEASURED':>14} "
                     f"{'rel_error':>10}")
        for res in ("hbm_bytes", "collective_bytes"):
            pred = predictions.get(res)
            meas = measured.get(res, 0.0)
            if pred is None:
                continue
            rel = abs(meas - pred) / pred if pred else math.inf
            lines.append(f"{res:<18} {pred:>14.3g} {meas:>14.3g} "
                         f"{rel:>10.3f}")
    return "\n".join(lines)
