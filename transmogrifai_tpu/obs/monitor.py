"""Serving-time feature-drift monitor against training baselines.

The serving half of the paper's feature-validation story (RawFeatureFilter,
SURVEY L4): training compares feature distributions between the TRAIN and
SCORING tables once, offline — this module runs the same comparison
continuously at serving time, against a baseline stamped into the model
artifact at train time.

  train:  Workflow.train computes one FeatureDistribution per raw feature
          (fill rate + histogram over training-range bins; text features hash
          into fixed buckets) — `compute_serving_baseline`. WorkflowModel.save
          writes them to model.json under "serving_baseline"; load restores.
  serve:  a ServingMonitor folds every scoring batch into per-feature
          STREAMING sketches (the same mergeable FeatureDistribution monoid:
          counts and histograms add) using cheap numpy on already-host
          columns, then emits per-feature fill-rate and Jensen-Shannon-
          divergence gauges into the metrics registry and raises structured
          DriftAlerts past configurable thresholds.

The monitor NEVER raises on the scoring hot path: any internal failure lands
on the `serving_monitor_errors_total` counter and scoring proceeds. Alerts are
span events + registry counters, one per (feature, kind) episode — an alert
re-arms only after the signal drops back under threshold.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Mapping, Optional, Sequence

import numpy as np

from ..filter.raw_feature_filter import FeatureDistribution, RawFeatureFilter

#: default histogram resolution of the stamped baseline — coarser than the
#: RawFeatureFilter's offline default (100): serving sketches merge per batch,
#: and 32 bins keep the JS signal while shrinking the artifact
BASELINE_BINS = 32
#: row cap for the train-time baseline pass (evenly-spaced subsample):
#: stamping must stay O(sample) however large the training table is
BASELINE_SAMPLE_ROWS = 8192


@dataclass(frozen=True)
class DriftThresholds:
    """When a drifting feature becomes an alert.

    max_js_divergence: JS (log2, [0, 1]) between the training histogram and
    the serving sketch. max_fill_delta: |train fill rate - serving fill rate|.
    min_rows: observations required before EITHER check arms (tiny sketches
    alert on noise). Serving thresholds default tighter than the offline
    RawFeatureFilter exclusion thresholds (0.90) — monitoring warns well
    before training would have excluded the feature."""

    max_js_divergence: float = 0.25
    max_fill_delta: float = 0.15
    min_rows: int = 256

    def to_json(self) -> dict:
        return {"max_js_divergence": self.max_js_divergence,
                "max_fill_delta": self.max_fill_delta,
                "min_rows": self.min_rows}


@dataclass
class DriftAlert:
    """One threshold crossing, structured for handlers/logs."""

    feature: str
    kind: str          # "js_divergence" | "fill_rate"
    value: float
    threshold: float
    rows_seen: int
    message: str

    def to_json(self) -> dict:
        return {"feature": self.feature, "kind": self.kind,
                "value": round(self.value, 6),
                "threshold": self.threshold,
                "rows_seen": self.rows_seen, "message": self.message}


# --- baseline computation / (de)serialization -------------------------------------------
def compute_serving_baseline(features: Sequence[Any], table,
                             bins: int = BASELINE_BINS,
                             sample_rows: int = BASELINE_SAMPLE_ROWS,
                             ) -> dict[str, FeatureDistribution]:
    """Per-raw-feature training distributions for the model artifact.

    Reuses the RawFeatureFilter's distribution pass (numeric histograms over
    the training range, hashed-value buckets for text) on an evenly-spaced
    row subsample capped at `sample_rows` — deterministic, O(sample) whatever
    the table size. Responses are skipped (serving is unlabeled)."""
    n = table.nrows
    if n > sample_rows:
        idx = np.linspace(0, n - 1, sample_rows).astype(np.int64)
        table = table.slice(idx)
    rff = RawFeatureFilter(bins=bins)
    return rff.compute_distributions(
        [f for f in features if not f.is_response], table)


def baseline_to_json(dists: Mapping[str, FeatureDistribution]) -> dict:
    """model.json "serving_baseline" payload. Unlike FeatureDistribution.
    to_json (a report), this keeps bin_edges — the serving sketch must bin
    scoring values over the SAME edges or JS is meaningless."""
    feats = {}
    for name, d in dists.items():
        feats[name] = {
            "kind": d.kind, "count": int(d.count),
            "null_count": int(d.null_count),
            "histogram": np.asarray(d.histogram, np.float64).tolist(),
            "bin_edges": (None if d.bin_edges is None
                          else np.asarray(d.bin_edges, np.float64).tolist()),
        }
    return {"version": 1, "bins": _bins_of(dists), "features": feats}


def baseline_from_json(doc: Mapping) -> dict[str, FeatureDistribution]:
    out = {}
    for name, f in doc.get("features", {}).items():
        out[name] = FeatureDistribution(
            name=name, kind=f["kind"], count=int(f["count"]),
            null_count=int(f["null_count"]),
            histogram=np.asarray(f["histogram"], np.float64),
            bin_edges=(None if f.get("bin_edges") is None
                       else np.asarray(f["bin_edges"], np.float64)),
        )
    return out


def _bins_of(dists: Mapping[str, FeatureDistribution]) -> int:
    for d in dists.values():
        if len(d.histogram):
            return int(len(d.histogram))
    return BASELINE_BINS


class _NamedFeature:
    """Adapter: RawFeatureFilter._distribution reads only `.name` off the
    feature object (compute_distributions additionally `.is_response`), and
    serving batches carry bare column names."""

    __slots__ = ("name", "is_response")

    def __init__(self, name: str):
        self.name = name
        self.is_response = False


class ServingMonitor:
    """Streaming drift detector for one served model.

    Thread-safe: `observe_table` is called from the input pipeline's producer
    thread (ScoreFunction.stream, the runner's streaming loop) while `check`/
    `report` run on the caller thread. Construct from a model —
    `ServingMonitor.for_model(model)` — or directly from a baseline dict.
    """

    #: per-batch stride-sample cap: drift is a statistical signal, so folding
    #: every row of every batch buys nothing but hot-path python time — 128
    #: rows/batch keeps the monitor at a few percent of streamed-scoring cost
    #: while a mean shift still crosses threshold within a couple of batches
    MAX_ROWS_PER_BATCH = 128
    #: threshold evaluation every N observed batches (check() also runs on
    #: demand and inside report(), so the final state never lags)
    CHECK_EVERY = 8

    def __init__(self, baseline: Mapping[str, FeatureDistribution],
                 thresholds: Optional[DriftThresholds] = None,
                 registry=None, source: str = "serve",
                 kinds: Optional[Mapping[str, Any]] = None,
                 max_rows_per_batch: Optional[int] = MAX_ROWS_PER_BATCH,
                 check_every: int = CHECK_EVERY,
                 window_batches: Optional[int] = None):
        from .metrics import default_registry

        if not baseline:
            raise ValueError(
                "empty serving baseline — train with a current build (or "
                "re-save the model) so model.json carries 'serving_baseline'")
        self.baseline = dict(baseline)
        #: {feature name: FeatureKind} — required only by observe_rows (raw
        #: record batches carry no kind metadata); for_model fills it in
        self.kinds = dict(kinds) if kinds else {}
        self.thresholds = thresholds or DriftThresholds()
        self.registry = registry if registry is not None else default_registry()
        self.source = source
        #: extra metric labels: monitors with a NON-default source (the
        #: daemon admits one monitor per model, labeled by serving name)
        #: carry it as a `model` label on every gauge/counter series — two
        #: co-resident models with the same feature names (exactly the
        #: autopilot's champion + challenger) must not clobber each other's
        #: drift signals. The default "serve" source keeps the historical
        #: label-less series (offline `op monitor`, runner monitors).
        self._model_labels = ({"model": source}
                              if source and source != "serve" else {})
        self.max_rows_per_batch = max_rows_per_batch
        self.check_every = max(1, int(check_every))
        #: sliding-window mode: every N observed batches the per-feature
        #: sketches reset (after a threshold check over the full window), so
        #: the JS/fill signals track RECENT traffic. Cumulative sketches
        #: (None, the default) dilute a past drift episode only slowly —
        #: fine for offline reports, but a closed-loop consumer (the
        #: autopilot, a pager) needs the falling edge within a bounded
        #: number of batches after the traffic actually recovers.
        self.window_batches = (max(1, int(window_batches))
                               if window_batches else None)
        self._batches_in_window = 0
        bins = _bins_of(self.baseline)
        self._rff = RawFeatureFilter(bins=bins)
        #: gauges cached per feature: get-or-create freezes/sorts labels under
        #: the registry lock — measurable at per-batch frequency
        self._gauges: dict[tuple[str, str], Any] = {}
        self._lock = threading.Lock()
        self.sketches: dict[str, FeatureDistribution] = {}
        self.batches = 0
        self.rows = 0
        #: (feature, kind) pairs currently past threshold — an alert fires on
        #: the False->True edge and re-arms when the signal recovers
        self._active: set[tuple[str, str]] = set()
        self.alerts: list[DriftAlert] = []
        self._max_alerts = 256
        # instruments are created once; observe() only updates them
        self._rows_c = self.registry.counter(
            "serving_monitor_rows_total",
            help="rows folded into the serving drift sketches")
        self._batches_c = self.registry.counter(
            "serving_monitor_batches_total",
            help="scoring batches observed by the drift monitor")
        self._errors_c = self.registry.counter(
            "serving_monitor_errors_total",
            help="internal monitor failures swallowed off the scoring hot path")
        self._skipped_c = self.registry.counter(
            "serving_monitor_skipped_columns_total",
            help="column observations skipped (device-resident or absent)")

    @classmethod
    def for_model(cls, model, thresholds: Optional[DriftThresholds] = None,
                  registry=None, **kwargs) -> "ServingMonitor":
        """Build from a WorkflowModel's stamped baseline (train stamps it;
        load restores it). Raises ValueError when the model predates the
        baseline contract. Extra kwargs (max_rows_per_batch, check_every,
        source) pass through to the constructor."""
        baseline = getattr(model, "serving_baseline", None)
        if not baseline:
            raise ValueError(
                "model carries no serving_baseline (trained before drift "
                "monitoring existed?) — retrain or re-save to stamp one")
        kinds = {f.name: f.kind for f in model.raw_features
                 if not f.is_response}
        return cls(baseline, thresholds=thresholds, registry=registry,
                   kinds=kinds, **kwargs)

    # --- observation (hot path; never raises) -----------------------------------------
    def observe_table(self, table, n: Optional[int] = None,
                      allow_device_fetch: bool = False) -> None:
        """Fold one scoring batch. `n` limits to the first n rows (serving
        pads batches to bucket sizes; filler rows must not skew fill rates).
        Only already-host columns are folded — a device-resident column would
        cost a D2H fetch on the scoring path, so it is counted as skipped
        instead. `allow_device_fetch=True` opts into that fetch for OFFLINE
        batch-scoring runs (the runner's `score` run type), where the arrays
        come back to the host for persistence anyway."""
        try:
            cols = {name: table[name] for name in table.names()}
            self._observe_cols(cols, n, allow_device_fetch=allow_device_fetch)
        except Exception:
            self._errors_c.inc()

    def observe_columns(self, cols: Mapping[str, Any],
                        n: Optional[int] = None) -> None:
        try:
            self._observe_cols(dict(cols), n)
        except Exception:
            self._errors_c.inc()

    def observe_rows(self, rows: Sequence[Mapping[str, Any]]) -> None:
        """Fold a batch of raw record dicts (the streaming runner's arrival
        shape — its table build is device-eager, so the monitor builds its
        own HOST columns from the rows instead of fetching device arrays
        back). Requires `kinds` (for_model provides them)."""
        try:
            if not rows or not self.kinds:
                if rows:
                    self._skipped_c.inc(len(self.baseline))
                return
            idx = self._sample_idx(len(rows))
            if idx is not None:
                # sample BEFORE column building: the per-row dict.get loops
                # are the dominant cost of folding a record batch
                rows = [rows[i] for i in idx]
            from ..types import Column

            cols = {}
            for name in self.baseline:
                kind = self.kinds.get(name)
                if kind is None:
                    continue
                try:
                    cols[name] = Column.build(
                        kind, [r.get(name) for r in rows], device=False)
                except (TypeError, ValueError):
                    self._skipped_c.inc()  # malformed values: skip, don't raise
            self._observe_cols(cols, None)
        except Exception:
            self._errors_c.inc()

    def _sample_idx(self, n_rows: int) -> Optional[np.ndarray]:
        """Evenly-spaced sample of EXACTLY max_rows_per_batch indices (None =
        fold every row). Drift is statistical — the cap bounds the python
        cost of huge batches without blinding the sketch. Exactness matters:
        an under-filled sample (the naive ceil-stride) delays the min_rows
        alert gate by whole batches."""
        cap = self.max_rows_per_batch
        if not cap or n_rows <= cap:
            return None
        # i * n/cap with n/cap > 1: floors are strictly increasing, so the
        # sample is cap DISTINCT evenly-spaced rows
        return np.linspace(0, n_rows, cap, endpoint=False).astype(np.int64)

    def _gauge(self, kind: str, name: str):
        g = self._gauges.get((kind, name))
        if g is None:
            help_text = {
                "fill": "serving-side fill rate per raw feature",
                "js": "JS divergence (log2) of the serving sketch vs the "
                      "training baseline, per raw feature",
            }[kind]
            metric = ("serving_fill_rate" if kind == "fill"
                      else "serving_js_divergence")
            g = self._gauges[(kind, name)] = self.registry.gauge(
                metric, help=help_text,
                labels={"feature": name, **self._model_labels})
        return g

    def _observe_cols(self, cols: dict, n: Optional[int],
                      allow_device_fetch: bool = False) -> None:
        folded_rows = 0
        idx_cache: dict[int, Optional[np.ndarray]] = {}
        for name, base in self.baseline.items():
            col = cols.get(name)
            if col is not None and not _host_resident(col) \
                    and allow_device_fetch:
                col = _fetched_host_copy(col)
            if col is None or not _host_resident(col):
                self._skipped_c.inc()
                continue
            if n is not None and n < len(col):
                col = col.slice(np.arange(n))
            n_col = len(col)
            if n_col not in idx_cache:
                idx_cache[n_col] = self._sample_idx(n_col)
            idx = idx_cache[n_col]
            if idx is not None:
                col = col.slice(idx)
            dist = self._rff._distribution(_NamedFeature(name), col,
                                           train_dist=base)
            with self._lock:
                sk = self.sketches.get(name)
                if sk is None:
                    self.sketches[name] = dist
                else:
                    _merge_into(sk, dist)
                sk = self.sketches[name]
                fill, js = sk.fill_rate, base.js_divergence(sk)
            folded_rows = max(folded_rows, len(col))
            self._gauge("fill", name).set(fill)
            self._gauge("js", name).set(js)
        with self._lock:
            self.batches += 1
            self.rows += folded_rows
            self._batches_in_window += 1
            due = self.batches % self.check_every == 0
            window_full = (self.window_batches is not None
                           and self._batches_in_window >= self.window_batches)
        self._batches_c.inc()
        self._rows_c.inc(folded_rows)
        if due or window_full:
            # the check always runs over the FULL window before a reset
            # drops it: a drift episode confined to one window must still
            # alert (and a recovery must still clear) off that window's data
            self._check_safe()
        if window_full:
            with self._lock:
                self.sketches.clear()
                self._batches_in_window = 0

    # --- drift decision ---------------------------------------------------------------
    def _feature_state(self, name: str) -> Optional[dict]:
        base = self.baseline[name]
        sk = self.sketches.get(name)
        if sk is None:
            return None
        return {
            "feature": name, "kind": base.kind,
            "rows": sk.count,
            "train_fill_rate": round(base.fill_rate, 6),
            "serving_fill_rate": round(sk.fill_rate, 6),
            "fill_delta": round(abs(base.fill_rate - sk.fill_rate), 6),
            "js_divergence": round(base.js_divergence(sk), 6),
        }

    def _check_safe(self) -> None:
        try:
            self.check()
        except Exception:
            self._errors_c.inc()

    def check(self) -> list[DriftAlert]:
        """Evaluate thresholds; returns alerts NEWLY fired by this call (the
        full history stays on `self.alerts`). Each new alert lands as an
        `obs` span event and on serving_drift_alerts_total."""
        from .. import obs

        th = self.thresholds
        new: list[DriftAlert] = []
        cleared: list[tuple] = []
        with self._lock:
            for name in self.baseline:
                st = self._feature_state(name)
                if st is None or st["rows"] < th.min_rows:
                    continue
                for kind, value, limit in (
                        ("js_divergence", st["js_divergence"],
                         th.max_js_divergence),
                        ("fill_rate", st["fill_delta"], th.max_fill_delta)):
                    key = (name, kind)
                    if value > limit:
                        if key in self._active:
                            continue
                        self._active.add(key)
                        alert = DriftAlert(
                            feature=name, kind=kind, value=float(value),
                            threshold=limit, rows_seen=int(st["rows"]),
                            message=(f"{name}: serving {kind} {value:.4f} > "
                                     f"{limit} after {st['rows']} rows"))
                        new.append(alert)
                        if len(self.alerts) < self._max_alerts:
                            self.alerts.append(alert)
                    elif key in self._active:
                        # the FALLING edge: the feature returned in-
                        # distribution — without this signal an alert
                        # latches forever from any consumer's point of view
                        # (the autopilot would retrain in a loop, a pager
                        # would never resolve)
                        self._active.discard(key)
                        gauge_v = (value if kind == "js_divergence"
                                   else st["serving_fill_rate"])
                        cleared.append((name, kind, float(value), limit,
                                        float(gauge_v)))
        for alert in new:
            obs.add_event("drift", **alert.to_json())
            self.registry.counter(
                "serving_drift_alerts_total",
                help="structured drift alerts raised past thresholds",
                labels={"feature": alert.feature, "kind": alert.kind,
                        **self._model_labels}).inc()
        for name, kind, value, limit, gauge_v in cleared:
            obs.add_event("drift:cleared", feature=name, kind=kind,
                          value=round(value, 6), threshold=limit)
            self.registry.counter(
                "serving_drift_cleared_total",
                help="drift episodes that ended: the feature returned "
                     "in-distribution after an alert",
                labels={"feature": name, "kind": kind,
                        **self._model_labels}).inc()
            # reset the signal gauge to the recovered value so dashboards
            # and the autopilot see the edge, not the episode's peak
            self._gauge("js" if kind == "js_divergence" else "fill",
                        name).set(gauge_v)
        return new

    def resolve_active(self, reason: str = "resolved") -> list[tuple[str, str]]:
        """Explicitly clear every active alert, emitting the same
        `drift:cleared` signal + counter the natural falling edge does (with
        a `reason` attribute marking it operator/controller-resolved). The
        autopilot calls this on a DEMOTED champion's monitor after a
        promotion: no traffic will ever feed that monitor again, so without
        an explicit resolution its episode would latch forever from any
        pager's point of view. Returns the (feature, kind) pairs cleared."""
        from .. import obs

        with self._lock:
            resolved = sorted(self._active)
            self._active.clear()
        for name, kind in resolved:
            obs.add_event("drift:cleared", feature=name, kind=kind,
                          reason=reason)
            self.registry.counter(
                "serving_drift_cleared_total",
                help="drift episodes that ended: the feature returned "
                     "in-distribution after an alert",
                labels={"feature": name, "kind": kind,
                        **self._model_labels}).inc()
        return resolved

    # --- reporting --------------------------------------------------------------------
    def report(self) -> dict:
        self._check_safe()  # the throttle must never stale a report
        with self._lock:
            feats = [st for name in sorted(self.baseline)
                     if (st := self._feature_state(name)) is not None]
            return {
                "source": self.source,
                "batches": self.batches, "rows": self.rows,
                "thresholds": self.thresholds.to_json(),
                "features": feats,
                "alerts": [a.to_json() for a in self.alerts],
                "active_alerts": sorted(
                    f"{f}:{k}" for f, k in self._active),
            }

    def pretty(self) -> str:
        rep = self.report()
        lines = [f"ServingMonitor: {rep['rows']} rows / {rep['batches']} "
                 f"batches observed, {len(rep['alerts'])} alert(s)"]
        if rep["features"]:
            hdr = (f"  {'feature':<24} {'kind':<12} {'fill(train)':>11} "
                   f"{'fill(serve)':>11} {'JS':>8}  status")
            lines.append(hdr)
            active = {a.split(":")[0] for a in rep["active_alerts"]}
            for st in rep["features"]:
                flag = "DRIFT" if st["feature"] in active else "ok"
                lines.append(
                    f"  {st['feature']:<24} {st['kind']:<12} "
                    f"{st['train_fill_rate']:>11.4f} "
                    f"{st['serving_fill_rate']:>11.4f} "
                    f"{st['js_divergence']:>8.4f}  {flag}")
        for a in rep["alerts"][-5:]:
            lines.append(f"  ! {a['message']}")
        return "\n".join(lines)


def demo_monitor(registry=None, rows: int = 512,
                 thresholds: Optional[DriftThresholds] = None) -> ServingMonitor:
    """Self-contained demo/smoke: a synthetic 3-feature baseline observed
    against one in-distribution batch and one drifted batch (mean-shifted
    numeric + degraded fill). Populates the registry's serving_* series with
    real values and fires at least one DriftAlert — `op monitor --demo` and
    the CI exposition lint run on this, needing no dataset or model."""
    from ..types import Column, Table

    rng = np.random.default_rng(7)

    def table(shift: float = 0.0, missing: float = 0.0, n: int = rows) -> Table:
        x = rng.normal(loc=shift, size=n)
        x_vals = [None if rng.random() < missing else float(v) for v in x]
        cats = [str(c) for c in rng.choice(list("abcd"), size=n)]
        return Table({
            "x": Column.build("Real", x_vals, device=False),
            "y": Column.build("Real", list(rng.normal(size=n)), device=False),
            "cat": Column.build("PickList", cats, device=False),
        })

    feats = [_NamedFeature(n) for n in ("x", "y", "cat")]
    baseline = compute_serving_baseline(feats, table())
    if thresholds is None:
        thresholds = DriftThresholds(min_rows=min(rows, 256))
    mon = ServingMonitor(baseline, registry=registry, source="demo",
                         thresholds=thresholds)
    mon.observe_table(table())                          # in-distribution
    mon.observe_table(table(shift=6.0, missing=0.5))    # drifted
    return mon


def _host_resident(col) -> bool:
    """True when observing the column is pure numpy (no D2H). Prediction-dict
    and device-array columns are skipped on the hot path."""
    v = getattr(col, "values", None)
    if isinstance(v, np.ndarray):
        return True
    return isinstance(v, (list, tuple))


def _fetched_host_copy(col):
    """Host Column copy of a device-array column (one device_get per array),
    or None for shapes the monitor cannot fold (prediction dicts)."""
    from ..types import Column

    v = getattr(col, "values", None)
    if v is None or isinstance(v, dict):
        return None
    try:
        vals = np.asarray(v)
        mask = None if col.mask is None else np.asarray(col.mask)
        return Column(col.kind, vals, mask, schema=col.schema)
    except Exception:
        return None


def _merge_into(acc: FeatureDistribution, d: FeatureDistribution) -> None:
    """Monoid merge (the reference reduces FeatureDistribution over RDD
    partitions the same way): counts add, histograms add bin-wise. Histogram
    shapes always agree — both sides binned over the baseline's edges."""
    acc.count += d.count
    acc.null_count += d.null_count
    if len(acc.histogram) == len(d.histogram):
        acc.histogram = np.asarray(acc.histogram, np.float64) + \
            np.asarray(d.histogram, np.float64)
