"""XLA compile/retrace watchdog: jax.monitoring listeners + a log-capture shim.

Two complementary sources, stitched per-thread:

* `jax.monitoring` duration events carry WHAT happened and for how long —
  `/jax/core/compile/jaxpr_trace_duration` (python tracing),
  `/jax/core/compile/jaxpr_to_mlir_module_duration` (StableHLO lowering; one
  per program build, fires even when the persistent compile cache absorbs the
  XLA compile — this is the retrace signal), and
  `/jax/core/compile/backend_compile_duration` (a real XLA compile). Plain
  events under `/jax/compilation_cache/` mark persistent-cache retrievals.
  None of them carry the program NAME.
* jax's dispatch logger emits "Finished tracing + transforming <name> …" /
  "Finished jaxpr to MLIR module conversion jit(<name>) …" / "Finished XLA
  compilation of jit(<name>) …" immediately BEFORE recording the matching
  duration event, in the same thread — at DEBUG level when
  `jax.config.jax_log_compiles` is off, WARNING when on. A logging.Handler
  captures the name into a thread-local mailbox; the next duration event of
  that kind (same thread) consumes it. This is the `jax_log_compiles` shim:
  capture without flipping the user-visible config.

Listeners register once per process (jax.monitoring has no deregistration) and
fast-path out when no Tracer or RetraceBudget is active. The log handler is
attached/detached with an activation refcount so idle processes pay nothing.

`RetraceBudget` turns the rounds-4/5 soak methodology into an enforced
invariant: `with obs.retrace_budget(0): train()` raises (at context exit, so a
partially-compiled run still finishes cleanly) or warns when steady-state code
compiles. Default counted kinds are ("lower", "compile"): a retrace always
lowers, even when the persistent cache then hands back a cached executable.
"""
from __future__ import annotations

import logging
import re
import threading
from typing import Optional

_logger = logging.getLogger("transmogrifai_tpu.obs")

_EVENT_KINDS = {
    "/jax/core/compile/jaxpr_trace_duration": "trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower",
    "/jax/core/compile/backend_compile_duration": "compile",
}
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

#: dispatch-log message -> (kind, program-name) extraction
_LOG_PATTERNS = (
    ("trace", re.compile(r"Finished tracing \+ transforming (.+?) for pjit")),
    ("lower", re.compile(r"Finished jaxpr to MLIR module conversion jit\((.+?)\) in")),
    ("compile", re.compile(r"Finished XLA compilation of jit\((.+?)\) in")),
)
#: loggers that emit the messages above (dispatch owns all three in current
#: jax; pxla's "Compiling <name> with global shapes" is a fallback lower-name)
_JAX_LOGGER_NAMES = ("jax._src.dispatch", "jax._src.interpreters.pxla")
_PXLA_COMPILING = re.compile(r"Compiling ([^\s]+) with global shapes")

# consumers: active tracers and budgets (appended/removed by their contexts)
_tracers: list = []
_budgets: list = []
_state_lock = threading.Lock()
_tls = threading.local()  # per-thread {kind: pending program name}

_listeners_installed = False
_handler: Optional["_NameCaptureHandler"] = None
_saved_levels: dict[str, int] = {}
_saved_effective: dict[str, int] = {}
_saved_propagate: dict[str, bool] = {}
_activations = 0


def _pending() -> dict:
    d = getattr(_tls, "pending", None)
    if d is None:
        d = _tls.pending = {}
    return d


class _NameCaptureHandler(logging.Handler):
    """Captures jit program names from jax's compile-pipeline log lines.

    While attached, the captured loggers are opened to DEBUG (the name-bearing
    lines log at DEBUG when jax_log_compiles is off) with propagation stopped;
    records that met the logger's ORIGINAL effective level are re-forwarded to
    its parent so user-visible logging behavior is unchanged."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:
            msg = ""
        matched = False
        for kind, pat in _LOG_PATTERNS:
            m = pat.search(msg)
            if m:
                _pending()[kind] = m.group(1)
                matched = True
                break
        if not matched:
            m = _PXLA_COMPILING.search(msg)
            if m:
                _pending().setdefault("lower", m.group(1))
        orig = _saved_effective.get(record.name)
        if orig is not None and record.levelno >= orig:
            parent = logging.getLogger(record.name).parent
            if parent is not None:
                parent.handle(record)


def _on_duration_event(event: str, duration: float, **_kw) -> None:
    kind = _EVENT_KINDS.get(event)
    if kind is None or not (_tracers or _budgets):
        return
    pending = _pending()
    program = pending.pop(kind, "")
    if kind == "lower":
        pending.pop("hit_pending", None)  # a new program build starts clean
    elif kind == "compile" and pending.pop("hit_pending", False):
        # jax's backend_compile_duration event wraps compile_OR_GET_CACHED:
        # when the persistent cache reported a hit since the last lowering
        # (same thread, synchronous sequence lower -> cache_hits -> this),
        # this duration is executable retrieval/deserialization, not an XLA
        # compile — reclassify so "compile" means a REAL compile and
        # cache_hit carries the retrieval cost
        kind = "cache_hit"
    for t in list(_tracers):
        t.on_compile_event(kind, program, duration)
    for b in list(_budgets):
        b.on_event(kind, program)


def _on_event(event: str, **_kw) -> None:
    if event != _CACHE_HIT_EVENT or not (_tracers or _budgets):
        return
    # mark only: the enclosing backend_compile duration event (fires next in
    # this thread) is reclassified to cache_hit and carries the duration
    _pending()["hit_pending"] = True


def _install_listeners() -> None:
    global _listeners_installed
    if _listeners_installed:
        return
    import jax.monitoring as monitoring

    monitoring.register_event_duration_secs_listener(_on_duration_event)
    monitoring.register_event_listener(_on_event)
    _listeners_installed = True


def activate(consumer, kind: str) -> None:
    """Register a Tracer ("tracer") or RetraceBudget ("budget") as live."""
    global _handler, _activations
    with _state_lock:
        _install_listeners()
        (_tracers if kind == "tracer" else _budgets).append(consumer)
        _activations += 1
        if _activations == 1:
            _handler = _NameCaptureHandler()
            for name in _JAX_LOGGER_NAMES:
                lg = logging.getLogger(name)
                _saved_levels[name] = lg.level
                _saved_effective[name] = lg.getEffectiveLevel()
                _saved_propagate[name] = lg.propagate
                lg.setLevel(logging.DEBUG)
                lg.propagate = False  # the handler re-forwards what would show
                lg.addHandler(_handler)


def deactivate(consumer, kind: str) -> None:
    global _handler, _activations
    with _state_lock:
        lst = _tracers if kind == "tracer" else _budgets
        if consumer in lst:
            lst.remove(consumer)
        _activations = max(_activations - 1, 0)
        if _activations == 0 and _handler is not None:
            for name in _JAX_LOGGER_NAMES:
                lg = logging.getLogger(name)
                lg.removeHandler(_handler)
                lg.setLevel(_saved_levels.get(name, 0))
                lg.propagate = _saved_propagate.get(name, True)
            _saved_levels.clear()
            _saved_effective.clear()
            _saved_propagate.clear()
            _handler = None


class RetraceBudgetExceeded(RuntimeError):
    """Steady-state code compiled more than its budget allows."""

    def __init__(self, msg: str, events: list):
        super().__init__(msg)
        self.events = events


class RetraceBudget:
    """Context manager enforcing "at most N compilation events happen here".

    kinds: which event kinds count against the budget. The default
    ("lower", "compile") catches retraces whether or not the persistent
    compile cache absorbs the XLA compile; use ("compile",) to assert only
    "nothing actually XLA-compiled" (e.g. warmed first trains, where cache
    retrievals are expected and correct).

    action="raise" raises RetraceBudgetExceeded at context EXIT (never mid-
    compile, and never masking an in-flight exception); action="warn" logs a
    warning per excess event and never raises.
    """

    def __init__(self, budget: int = 0, kinds=("lower", "compile"),
                 action: str = "raise"):
        if action not in ("raise", "warn"):
            raise ValueError(f"action must be 'raise' or 'warn', got {action!r}")
        self.budget = int(budget)
        self.kinds = tuple(kinds)
        self.action = action
        self.events: list[tuple[str, str]] = []  # (kind, program) that counted
        self._lock = threading.Lock()

    @property
    def count(self) -> int:
        with self._lock:
            return len(self.events)

    @property
    def excess(self) -> list[tuple[str, str]]:
        with self._lock:
            return self.events[self.budget:]

    def on_event(self, kind: str, program: str) -> None:
        if kind not in self.kinds:
            return
        with self._lock:
            self.events.append((kind, program))
            over = len(self.events) > self.budget
        if over and self.action == "warn":
            _logger.warning(
                "retrace budget (%d) exceeded: %s of %r (event %d)",
                self.budget, kind, program or "?", len(self.events))

    def __enter__(self) -> "RetraceBudget":
        activate(self, "budget")
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        deactivate(self, "budget")
        with self._lock:  # late on_event callbacks may still be landing
            events = list(self.events)
        if exc_type is None and self.action == "raise" \
                and len(events) > self.budget:
            detail = ", ".join(f"{k}:{p or '?'}" for k, p in events[:10])
            if len(events) > 10:
                detail += f", … ({len(events) - 10} more)"
            raise RetraceBudgetExceeded(
                f"{len(events)} compilation event(s) exceeded the "
                f"retrace budget of {self.budget} (kinds={self.kinds}): "
                f"{detail}", events)
