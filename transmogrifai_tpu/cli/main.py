"""`op` command-line entry point (analog of the reference's OpWorkflowRunner CLI +
`transmogrifai gen` codegen CLI; reference OpWorkflowRunner.scala:390-424,
cli/.../CommandParser.scala:82-123). Subcommands land with the runner layer."""
from __future__ import annotations

import sys


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    from transmogrifai_tpu import __version__

    if not argv or argv[0] in ("-h", "--help"):
        print(
            "usage: op <command> [args]\n\n"
            "commands:\n"
            "  version   print framework version\n"
            "  (train/score/evaluate/features/init arrive with the runner layer)"
        )
        return 0
    if argv[0] == "version":
        print(__version__)
        return 0
    print(f"op: unknown command {argv[0]!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
